"""graftcheck — AST-driven invariant checker for this repo's own contracts.

The framework carries load-bearing conventions that existed only as prose
(CHANGES.md, docs/): the fleet tier is jax-free at import time, metric
families register at import so the first scrape sees them, one loop thread
owns every socket, duration math never reads the wall clock, the faultpoint
catalog is closed. ``analysis`` turns each of those sentences into a
machine-checked rule over the stdlib ``ast`` — no imports of the checked
code, so checking the jax-free set cannot itself drag in jax.

Layout:

  ``analysis.core``     findings, per-line suppressions, the expiring
                        baseline, source-file loading, the runner
  ``analysis.project``  the repo-specific configuration (what to scan,
                        the jax-free manifest, where the catalogs live)
  ``analysis.rules``    one module per rule (see docs/ANALYSIS.md)

CLI: ``python tools/graftcheck.py --strict`` (the CI gate).
"""

from analysis.core import Baseline, Finding, Project, run_rules  # noqa: F401
