"""graftcheck core: findings, suppressions, expiring baseline, runner.

Everything here is target-agnostic: a ``Project`` describes *what* to scan
(a package directory plus tool scripts under one root) and the rule
modules describe *what must hold*. The test suite exercises rules against
tiny synthetic projects in a tmpdir through exactly this API, so CI and
pytest enforce the same semantics.

Suppression grammar (per line, checked code opts out locally)::

    something_flagged()  # graftcheck: disable=rule-id
    something_flagged()  # graftcheck: disable=rule-a,rule-b

File-wide (anywhere in the file, normally the docstring tail)::

    # graftcheck: disable-file=rule-id

Baseline: a committed JSON list of grandfathered findings, each entry
``{"rule", "path", "reason", "expires": "YYYY-MM-DD"}``. A matching
finding is demoted to *baselined* until the expiry passes — then it is a
failure again (debt has a due date). An entry that matches nothing is
itself a failure: the baseline must shrink as violations are fixed, never
accrete dead weight.
"""

from __future__ import annotations

import ast
import datetime
import json
import os
import re
from dataclasses import dataclass, field

_SUPPRESS_RE = re.compile(r"#\s*graftcheck:\s*disable=([a-zA-Z0-9_,-]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*graftcheck:\s*disable-file=([a-zA-Z0-9_,-]+)"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at a file:line."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "message": self.message,
        }


class SourceFile:
    """One parsed python file: text, AST, and its suppression map."""

    def __init__(self, root: str, abspath: str) -> None:
        self.abspath = abspath
        self.rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree: ast.Module | None = None
        self.syntax_error: str | None = None
        try:
            self.tree = ast.parse(self.text, filename=self.rel)
        except SyntaxError as exc:
            self.syntax_error = f"{type(exc).__name__}: {exc.msg}"
        self.line_suppressions: dict[int, set[str]] = {}
        self.file_suppressions: set[str] = set()
        for i, line in enumerate(self.lines, start=1):
            if "graftcheck" not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if m:
                self.line_suppressions.setdefault(i, set()).update(
                    m.group(1).split(",")
                )
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                self.file_suppressions.update(m.group(1).split(","))

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions:
            return True
        return rule in self.line_suppressions.get(line, ())


class Project:
    """What to scan and where the checked-in contracts live.

    All paths are relative to ``root``. The defaults in
    ``analysis.project.default_project`` describe this repo; tests build
    Projects over synthetic trees in a tmpdir.
    """

    def __init__(
        self,
        root: str,
        package: str,
        tool_dirs: tuple[str, ...] = ("tools",),
        jaxfree: tuple[str, ...] = (),
        forbidden_imports: tuple[str, ...] = ("jax", "jaxlib"),
        catalog_path: str | None = None,
        faults_path: str | None = None,
        resilience_doc: str | None = None,
        observability_doc: str | None = None,
    ) -> None:
        self.root = os.path.abspath(root)
        self.package = package
        self.tool_dirs = tool_dirs
        self.jaxfree = jaxfree
        self.forbidden_imports = forbidden_imports
        self.catalog_path = catalog_path
        self.faults_path = faults_path
        self.resilience_doc = resilience_doc
        self.observability_doc = observability_doc
        self._files: list[SourceFile] | None = None
        self._by_module: dict[str, SourceFile] | None = None

    # -- discovery -----------------------------------------------------------

    def files(self) -> list[SourceFile]:
        if self._files is None:
            out: list[SourceFile] = []
            pkg_root = os.path.join(self.root, self.package)
            for dirpath, dirnames, filenames in os.walk(pkg_root):
                dirnames[:] = [
                    d for d in dirnames if d != "__pycache__"
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(
                            SourceFile(self.root, os.path.join(dirpath, fn))
                        )
            for tool_dir in self.tool_dirs:
                tdir = os.path.join(self.root, tool_dir)
                if not os.path.isdir(tdir):
                    continue
                for dirpath, dirnames, filenames in os.walk(tdir):
                    dirnames[:] = [
                        d for d in dirnames if d != "__pycache__"
                    ]
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            out.append(
                                SourceFile(
                                    self.root, os.path.join(dirpath, fn)
                                )
                            )
            self._files = out
        return self._files

    def by_module(self) -> dict[str, SourceFile]:
        """Dotted module name -> SourceFile (``a/b/__init__.py`` -> ``a.b``;
        tool scripts as ``tools.name``)."""
        if self._by_module is None:
            out = {}
            for sf in self.files():
                rel = sf.rel
                if rel.endswith("/__init__.py"):
                    mod = rel[: -len("/__init__.py")]
                elif rel.endswith(".py"):
                    mod = rel[:-3]
                else:
                    continue
                out[mod.replace("/", ".")] = sf
            self._by_module = out
        return self._by_module

    def read_doc(self, relpath: str) -> str | None:
        p = os.path.join(self.root, relpath)
        if not os.path.exists(p):
            return None
        with open(p, encoding="utf-8") as f:
            return f.read()


# -- baseline ---------------------------------------------------------------


class BaselineError(ValueError):
    """The baseline file itself is malformed."""


class Baseline:
    """Committed grandfathered findings with expiry dates."""

    def __init__(self, entries: list[dict]) -> None:
        for e in entries:
            missing = {"rule", "path", "reason", "expires"} - set(e)
            if missing:
                raise BaselineError(
                    f"baseline entry {e!r} missing keys {sorted(missing)}"
                )
            try:
                datetime.date.fromisoformat(e["expires"])
            except ValueError:
                raise BaselineError(
                    f"baseline entry for {e['rule']}:{e['path']} has "
                    f"unparseable expires {e['expires']!r} (want YYYY-MM-DD)"
                ) from None
        self.entries = entries
        self._hits: set[int] = set()

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls([])
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, list):
            raise BaselineError("baseline must be a JSON list of entries")
        return cls(data)

    def match(self, finding: Finding, today: datetime.date) -> str | None:
        """``"active"`` (suppressed), ``"expired"`` (fails again), or None
        (not baselined). Match granularity is (rule, path): line numbers
        churn with unrelated edits and must not invalidate the entry."""
        for i, e in enumerate(self.entries):
            if e["rule"] == finding.rule and e["path"] == finding.path:
                self._hits.add(i)
                expires = datetime.date.fromisoformat(e["expires"])
                return "active" if today <= expires else "expired"
        return None

    def unused(self) -> list[dict]:
        return [
            e for i, e in enumerate(self.entries) if i not in self._hits
        ]


# -- runner -----------------------------------------------------------------


@dataclass
class Report:
    """Outcome of one checker run, after suppressions and baseline."""

    findings: list[Finding] = field(default_factory=list)  # live failures
    baselined: list[tuple[Finding, dict]] = field(default_factory=list)
    expired: list[tuple[Finding, dict]] = field(default_factory=list)
    unused_baseline: list[dict] = field(default_factory=list)
    suppressed_count: int = 0
    rules_run: list[str] = field(default_factory=list)
    files_scanned: int = 0

    def failed(self) -> bool:
        return bool(
            self.findings or self.expired or self.unused_baseline
        )

    def to_json(self) -> dict:
        return {
            "rules_run": self.rules_run,
            "files_scanned": self.files_scanned,
            "findings": [f.to_json() for f in self.findings],
            "baselined": [
                {**f.to_json(), "expires": e["expires"],
                 "reason": e["reason"]}
                for f, e in self.baselined
            ],
            "expired": [
                {**f.to_json(), "expires": e["expires"],
                 "reason": e["reason"]}
                for f, e in self.expired
            ],
            "unused_baseline": self.unused_baseline,
            "suppressed": self.suppressed_count,
            "failed": self.failed(),
        }


def run_rules(
    project: Project,
    rules,
    baseline: Baseline | None = None,
    today: datetime.date | None = None,
) -> Report:
    """Run every rule over the project; apply suppressions, then the
    baseline. ``rules`` is an iterable of modules/objects exposing
    ``RULE_ID`` and ``check(project) -> list[Finding]``."""
    baseline = baseline or Baseline([])
    today = today or datetime.date.today()
    report = Report()
    report.files_scanned = len(project.files())
    by_rel = {sf.rel: sf for sf in project.files()}

    raw: list[Finding] = []
    # A file the parser rejects can hide anything; surface it as its own
    # finding instead of silently skipping the file in every rule.
    for sf in project.files():
        if sf.syntax_error:
            raw.append(Finding(
                "parse", sf.rel, 1, f"unparseable file: {sf.syntax_error}"
            ))
    for rule in rules:
        report.rules_run.append(rule.RULE_ID)
        raw.extend(rule.check(project))

    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        sf = by_rel.get(f.path)
        if sf is not None and sf.suppressed(f.rule, f.line):
            report.suppressed_count += 1
            continue
        status = baseline.match(f, today)
        if status == "active":
            entry = next(
                e for e in baseline.entries
                if e["rule"] == f.rule and e["path"] == f.path
            )
            report.baselined.append((f, entry))
        elif status == "expired":
            entry = next(
                e for e in baseline.entries
                if e["rule"] == f.rule and e["path"] == f.path
            )
            report.expired.append((f, entry))
        else:
            report.findings.append(f)
    # A baseline entry can only be proven stale by a rule that actually
    # ran: under a --rules subset, entries for unrun rules are simply
    # out of scope, not failures.
    ran = set(report.rules_run)
    report.unused_baseline = [
        e for e in baseline.unused() if e["rule"] in ran
    ]
    return report


# -- shared AST helpers ------------------------------------------------------


def call_name(node: ast.Call) -> str | None:
    """``foo(...)`` -> ``foo``; ``a.b.foo(...)`` -> ``foo``; else None."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def dotted(node: ast.expr) -> str | None:
    """Render a Name/Attribute chain as ``a.b.c`` (None if not a chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.expr | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def literal_dict(path: str, tree: ast.Module, name: str):
    """The literal value assigned to module-level ``name`` (via
    ``ast.literal_eval``), or None when absent/non-literal."""
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                try:
                    return ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return None
    return None
