"""Rule ``journal-catalog`` (R4): every journal event is declared, with
its required keys.

The JSONL run journal is the system's flight recorder: drills, the
continual-learning trigger, and ``tools/obs_report.py`` all *grep it by
event name* and index into event fields. A typo'd name (``fleet_rotaton``)
or a dropped key used to fail silently — the consumer just saw nothing.
Statically enforced over every ``…event("name", key=…)`` call site
(``journal.event`` module function, ``RunJournal.event`` method, and the
re-exported ``event`` alias inside ``obs/journal.py``):

  * the event kind is a string LITERAL and appears in the ``EVENTS``
    catalog (``obs/catalog.py``);
  * the call carries every required key for that kind as an explicit
    keyword (a ``**spread`` at the call site satisfies the remainder —
    the spread's contents are a runtime matter);
  * every catalog entry is emitted by at least one site (no dead names).

``threading.Event()`` and similar constructors don't collide: the rule
matches only lowercase ``event`` call targets with a literal string
first argument.
"""

from __future__ import annotations

import ast

from analysis.core import Finding, Project, literal_dict, str_const

RULE_ID = "journal-catalog"


def collect_sites(project: Project):
    """(sf, line, kind-name-or-None, literal kwargs, has_spread)."""
    sites = []
    for sf in project.files():
        if sf.tree is None:
            continue
        if project.catalog_path and sf.rel == project.catalog_path:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if name != "event":
                continue
            if not node.args:
                continue
            kind = str_const(node.args[0])
            kwargs = frozenset(
                kw.arg for kw in node.keywords if kw.arg is not None
            )
            spread = any(kw.arg is None for kw in node.keywords)
            sites.append((sf, node.lineno, kind, kwargs, spread,
                          node.args[0].lineno if node.args else node.lineno))
    return sites


def load_catalog(project: Project):
    if not project.catalog_path:
        return None, None
    sf = next(
        (s for s in project.files() if s.rel == project.catalog_path), None
    )
    if sf is None or sf.tree is None:
        return None, None
    return literal_dict(project.catalog_path, sf.tree, "EVENTS"), sf


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    sites = collect_sites(project)
    catalog, catalog_sf = load_catalog(project)
    if catalog is None:
        if project.catalog_path and sites:
            findings.append(Finding(
                RULE_ID, project.catalog_path, 1,
                "journal-event catalog (EVENTS literal dict) missing or "
                "unparseable",
            ))
        return findings

    emitted: set[str] = set()
    for sf, line, kind, kwargs, spread, _ in sites:
        if kind is None:
            findings.append(Finding(
                RULE_ID, sf.rel, line,
                "journal event kind must be a string literal (a computed "
                "kind cannot be cataloged or grepped)",
            ))
            continue
        emitted.add(kind)
        required = catalog.get(kind)
        if required is None:
            findings.append(Finding(
                RULE_ID, sf.rel, line,
                f"journal event {kind!r} is not in the EVENTS catalog "
                f"({project.catalog_path})",
            ))
            continue
        if not spread:
            missing = [k for k in required if k not in kwargs]
            if missing:
                findings.append(Finding(
                    RULE_ID, sf.rel, line,
                    f"journal event {kind!r} missing required keys "
                    f"{missing} (catalog requires {list(required)})",
                ))
    for kind in sorted(set(catalog) - emitted):
        findings.append(Finding(
            RULE_ID, catalog_sf.rel, 1,
            f"EVENTS catalog entry {kind!r} is emitted nowhere — remove "
            "it or restore the emit site",
        ))
    return findings
