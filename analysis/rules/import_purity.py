"""Rule ``import-purity`` (R1): the declared jax-free set stays jax-free.

Builds the module-level (import-time) import graph over the scanned
files and proves that no module in the project's ``jaxfree`` manifest
transitively reaches a forbidden top-level distribution (``jax``,
``jaxlib``). Function-scoped imports are deliberately excluded — a lazy
``import jax`` inside a predict path is exactly how the serving stack
keeps the fleet tier importable in milliseconds.

Python semantics the graph models (both have bitten this repo):

  * importing ``a.b.c`` executes ``a/__init__`` and ``a/b/__init__``
    first — an eager re-export in a parent package breaks every child's
    purity;
  * ``from a.b import c`` may bind submodule ``a.b.c``, so that edge is
    resolved when ``a/b/c.py`` exists.

Module-level imports guarded by ``if``/``try`` are counted: an
import-time dependency that only *sometimes* fires is still an
import-time dependency.

The finding reports the full offending chain (root -> … -> jax) so the
fix site is obvious.
"""

from __future__ import annotations

import ast

from analysis.core import Finding, Project

RULE_ID = "import-purity"


def _module_level_imports(tree: ast.Module):
    """Yield (imported name, line) for import statements that execute at
    module import time, including under module-level if/try."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Import):
            for alias in node.names:
                yield alias.name, node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.level == 0:
                yield node.module, node.lineno
                for alias in node.names:
                    # may be a submodule import
                    yield f"{node.module}.{alias.name}", node.lineno
            # relative imports (level > 0) don't occur in this repo's
            # style; absolute-only keeps resolution exact.
        elif isinstance(node, (ast.If, ast.Try)):
            for field in ("body", "orelse", "finalbody", "handlers"):
                for child in getattr(node, field, []):
                    if isinstance(child, ast.ExceptHandler):
                        stack.extend(child.body)
                    else:
                        stack.append(child)


def build_graph(project: Project):
    """module -> list of (imported dotted name, line)."""
    graph = {}
    for mod, sf in project.by_module().items():
        if sf.tree is None:
            graph[mod] = []
            continue
        graph[mod] = list(_module_level_imports(sf.tree))
    return graph


def _resolve_internal(name: str, graph) -> list[str]:
    """Internal modules executed by importing ``name`` (every matching
    package prefix, deepest last)."""
    out = []
    parts = name.split(".")
    for i in range(1, len(parts) + 1):
        prefix = ".".join(parts[:i])
        if prefix in graph:
            out.append(prefix)
    return out


def trace(root: str, graph, forbidden: tuple[str, ...]):
    """BFS from ``root`` over import-time edges; returns the first chain
    reaching a forbidden distribution as a list
    ``[root, …, module, forbidden]``, or None when pure."""
    if root not in graph:
        return ["<missing>"]
    parents: dict[str, tuple[str, int] | None] = {root: None}
    queue = [root]
    while queue:
        mod = queue.pop(0)
        edges = list(graph.get(mod, []))
        # importing a module executes its parent packages too
        parts = mod.split(".")
        for i in range(1, len(parts)):
            prefix = ".".join(parts[:i])
            if prefix in graph:
                edges.append((prefix, 0))
        for name, line in edges:
            top = name.split(".")[0]
            if top in forbidden:
                chain = [f"{name} (line {line})"]
                cur: str | None = mod
                while cur is not None:
                    chain.append(cur)
                    nxt = parents[cur]
                    cur = nxt[0] if nxt else None
                return list(reversed(chain))
            for internal in _resolve_internal(name, graph):
                if internal not in parents:
                    parents[internal] = (mod, line)
                    queue.append(internal)
    return None


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    if not project.jaxfree:
        return findings
    graph = build_graph(project)
    by_module = project.by_module()
    for root in project.jaxfree:
        chain = trace(root, graph, project.forbidden_imports)
        if chain == ["<missing>"]:
            findings.append(Finding(
                RULE_ID, "analysis/project.py", 1,
                f"jax-free manifest names {root!r} but no such module "
                "exists in the scanned tree",
            ))
        elif chain is not None:
            # anchor the finding at the last internal module's import line
            sf = by_module.get(chain[-2]) if len(chain) >= 2 else None
            path = sf.rel if sf else by_module[root].rel
            line = 1
            tail = chain[-1]
            if "(line " in tail:
                line = int(tail.rsplit("(line ", 1)[1].rstrip(")"))
            findings.append(Finding(
                RULE_ID, path, line,
                f"declared jax-free module {root!r} reaches a forbidden "
                f"import at import time: {' -> '.join(chain)}",
            ))
    return findings
