"""Rule ``metrics-catalog`` (R3): every metric family is declared,
import-time-registered, consistently labeled, and documented.

The contract (CHANGES.md PR 6/11, docs/OBSERVABILITY.md): a scrape of a
freshly started process sees every family's metadata — no family may
first appear when it first fires. Statically enforced over every
``<registry>.counter/gauge/histogram("name", …)`` call site:

  * the family name is a string LITERAL (a name built at runtime can't
    be cataloged, alerted on, or grepped);
  * names follow the repo's Prometheus conventions: ``[a-z][a-z0-9_]*``,
    counters end in ``_total``, no family name ends in ``_bucket`` /
    ``_sum`` / ``_count`` (histogram sample suffixes), no ``le`` label;
  * one label set and one kind per family across all sites;
  * calls on the process-global ``REGISTRY`` happen at module top level
    (import-time registration). Instance registries (a ``MetricsRegistry``
    passed into a component, e.g. ``obs.slo``/``obs.quality``) are exempt
    from placement — their import-time guarantee is the component's
    constructor contract — but their names are still cataloged;
  * every name appears in the ``METRICS`` catalog
    (``obs/catalog.py``) with matching kind and labels, every catalog
    entry is registered by some site, and every catalog name appears in
    docs/OBSERVABILITY.md's family table.
"""

from __future__ import annotations

import ast
import re

from analysis.core import Finding, Project, literal_dict, str_const

RULE_ID = "metrics-catalog"

_KINDS = ("counter", "gauge", "histogram")
_NAME_RE = re.compile(r"[a-z][a-z0-9_]*\Z")
_RESERVED_SUFFIXES = ("_bucket", "_sum", "_count")


class _Site:
    __slots__ = ("sf", "line", "kind", "name", "labels", "top_level",
                 "global_registry", "literal")

    def __init__(self, sf, line, kind, name, labels, top_level,
                 global_registry, literal):
        self.sf = sf
        self.line = line
        self.kind = kind
        self.name = name
        self.labels = labels
        self.top_level = top_level
        self.global_registry = global_registry
        self.literal = literal


def _labels_of(call: ast.Call):
    for kw in call.keywords:
        if kw.arg in ("labels", "label_names"):
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                vals = [str_const(e) for e in kw.value.elts]
                if all(v is not None for v in vals):
                    return tuple(vals)
            return None  # non-literal label list
    return ()


def collect_sites(project: Project) -> list[_Site]:
    sites = []
    for sf in project.files():
        if sf.tree is None:
            continue
        if project.catalog_path and sf.rel == project.catalog_path:
            continue
        depth = {"n": 0}

        def walk(node, depth=depth, sf=sf):
            nested = isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            )
            if nested:
                depth["n"] += 1
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in _KINDS:
                    recv = f.value
                    recv_name = recv.attr if isinstance(
                        recv, ast.Attribute
                    ) else (recv.id if isinstance(recv, ast.Name) else None)
                    if recv_name and (
                        recv_name == "REGISTRY"
                        or recv_name.lower().lstrip("_") in
                        ("reg", "registry")
                    ):
                        name = str_const(node.args[0]) if node.args else None
                        sites.append(_Site(
                            sf, node.lineno, f.attr, name,
                            _labels_of(node), depth["n"] == 0,
                            recv_name == "REGISTRY", name is not None,
                        ))
            for child in ast.iter_child_nodes(node):
                walk(child)
            if nested:
                depth["n"] -= 1

        for top in sf.tree.body:
            walk(top)
    return sites


def load_catalog(project: Project):
    """Parse METRICS from the catalog module without importing it."""
    if not project.catalog_path:
        return None, None
    sf = next(
        (s for s in project.files() if s.rel == project.catalog_path), None
    )
    if sf is None or sf.tree is None:
        return None, None
    return literal_dict(project.catalog_path, sf.tree, "METRICS"), sf


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    sites = collect_sites(project)
    catalog, catalog_sf = load_catalog(project)

    by_name: dict[str, list[_Site]] = {}
    for s in sites:
        if not s.literal:
            findings.append(Finding(
                RULE_ID, s.sf.rel, s.line,
                f"metric family name must be a string literal "
                f"({s.kind} registration with a computed name)",
            ))
            continue
        by_name.setdefault(s.name, []).append(s)
        if not _NAME_RE.match(s.name):
            findings.append(Finding(
                RULE_ID, s.sf.rel, s.line,
                f"metric name {s.name!r} violates naming convention "
                "[a-z][a-z0-9_]*",
            ))
        if s.kind == "counter" and not s.name.endswith("_total"):
            findings.append(Finding(
                RULE_ID, s.sf.rel, s.line,
                f"counter family {s.name!r} must end in _total "
                "(Prometheus counter convention)",
            ))
        for suffix in _RESERVED_SUFFIXES:
            if s.name.endswith(suffix):
                findings.append(Finding(
                    RULE_ID, s.sf.rel, s.line,
                    f"family name {s.name!r} ends in reserved histogram "
                    f"sample suffix {suffix!r}",
                ))
        if s.labels is not None and "le" in s.labels:
            findings.append(Finding(
                RULE_ID, s.sf.rel, s.line,
                f"family {s.name!r} declares reserved label 'le'",
            ))
        if s.global_registry and not s.top_level:
            findings.append(Finding(
                RULE_ID, s.sf.rel, s.line,
                f"family {s.name!r} registers on the process-global "
                "REGISTRY inside a function/method — families register "
                "at module import so the first scrape sees them",
            ))

    for name, group in sorted(by_name.items()):
        kinds = {s.kind for s in group}
        if len(kinds) > 1:
            s = group[1]
            findings.append(Finding(
                RULE_ID, s.sf.rel, s.line,
                f"family {name!r} registered with conflicting kinds "
                f"{sorted(kinds)}",
            ))
        label_sets = {s.labels for s in group if s.labels is not None}
        if len(label_sets) > 1:
            s = group[1]
            findings.append(Finding(
                RULE_ID, s.sf.rel, s.line,
                f"family {name!r} registered with conflicting label sets "
                f"{sorted(label_sets)}",
            ))

    if catalog is None:
        if project.catalog_path and sites:
            findings.append(Finding(
                RULE_ID, project.catalog_path or "analysis/project.py", 1,
                "metrics catalog (METRICS literal dict) missing or "
                "unparseable",
            ))
        return findings

    for name, group in sorted(by_name.items()):
        s = group[0]
        entry = catalog.get(name)
        if entry is None:
            findings.append(Finding(
                RULE_ID, s.sf.rel, s.line,
                f"family {name!r} is not declared in the METRICS catalog "
                f"({project.catalog_path})",
            ))
            continue
        cat_kind, cat_labels = entry[0], tuple(entry[1])
        if cat_kind != s.kind:
            findings.append(Finding(
                RULE_ID, s.sf.rel, s.line,
                f"family {name!r} registered as {s.kind} but cataloged "
                f"as {cat_kind}",
            ))
        if s.labels is not None and tuple(s.labels) != cat_labels:
            findings.append(Finding(
                RULE_ID, s.sf.rel, s.line,
                f"family {name!r} registered with labels "
                f"{tuple(s.labels)} but cataloged with {cat_labels}",
            ))
    for name in sorted(set(catalog) - set(by_name)):
        findings.append(Finding(
            RULE_ID, catalog_sf.rel, 1,
            f"METRICS catalog entry {name!r} is registered nowhere — "
            "remove it or restore the family",
        ))

    if project.observability_doc:
        doc = project.read_doc(project.observability_doc)
        if doc is None:
            findings.append(Finding(
                RULE_ID, catalog_sf.rel, 1,
                f"cross-check doc {project.observability_doc} not found",
            ))
        else:
            for name in sorted(catalog):
                if name not in doc:
                    findings.append(Finding(
                        RULE_ID, catalog_sf.rel, 1,
                        f"cataloged family {name!r} is undocumented in "
                        f"{project.observability_doc}",
                    ))
    return findings
