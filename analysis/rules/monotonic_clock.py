"""Rule ``monotonic-clock`` (R5): duration and deadline math never reads
the wall clock.

``time.time()`` jumps — NTP slew, leap smearing, a VM migration — and a
jump inside duration arithmetic becomes a negative stage time, a deadline
that never fires, or a watchdog that fires instantly (for a clinical
predictor, a correctness bug, not a style nit). The repo's convention
(CHANGES.md PR 2/6): ``time.perf_counter()`` for measured durations,
``time.monotonic()`` for deadlines/uptime, wall clock ONLY for
human/manifest timestamps.

Statically: every call to ``time.time()``, ``datetime.now()``,
``datetime.utcnow()`` (including ``datetime.datetime.…``) in the scanned
tree is a finding. Sites that genuinely want a wall-clock *timestamp*
(the journal's ISO-8601 stamps, manifest fields, epoch anchors for trace
export) opt out per line::

    "started": time.time(),  # graftcheck: disable=monotonic-clock

which is exactly the reviewable artifact we want: every wall-clock read
in the codebase is either duration-safe or visibly declared a timestamp.
"""

from __future__ import annotations

import ast

from analysis.core import Finding, Project, dotted

RULE_ID = "monotonic-clock"

_WALL_CALLS = {
    "time.time": "time.time() in code that may feed duration/deadline "
    "math; use time.perf_counter()/time.monotonic(), or mark the line "
    "as a timestamp",
    "datetime.now": "datetime.now() is wall-clock; use "
    "time.monotonic() for deadlines or mark the line as a timestamp",
    "datetime.utcnow": "datetime.utcnow() is wall-clock; use "
    "time.monotonic() for deadlines or mark the line as a timestamp",
    "datetime.datetime.now": "datetime.now() is wall-clock; use "
    "time.monotonic() for deadlines or mark the line as a timestamp",
    "datetime.datetime.utcnow": "datetime.utcnow() is wall-clock; use "
    "time.monotonic() for deadlines or mark the line as a timestamp",
}


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted(node.func)
            if chain in _WALL_CALLS:
                findings.append(Finding(
                    RULE_ID, sf.rel, node.lineno, _WALL_CALLS[chain]
                ))
    return findings
