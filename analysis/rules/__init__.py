"""Rule plugins. ``ALL_RULES`` is the closed, ordered set the CLI runs."""

from analysis.rules import (
    faultpoints,
    import_purity,
    journal_catalog,
    loop_discipline,
    metrics_catalog,
    monotonic_clock,
)

ALL_RULES = (
    import_purity,
    loop_discipline,
    metrics_catalog,
    journal_catalog,
    monotonic_clock,
    faultpoints,
)
