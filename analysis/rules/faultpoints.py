"""Rule ``faultpoint-coherence`` (R6): the three views of the faultpoint
catalog agree exactly.

A faultpoint exists in three places: the ``fire("site")`` call woven into
a hot path, the closed ``SITES`` catalog in ``resilience/faults.py`` that
arm-time validation checks against, and the operator-facing table in
docs/RESILIENCE.md that chaos drills are written from. The three drifting
is how a chaos spec "passes" while injecting nothing. Statically:

  * every ``faults.fire("x")`` site literal appears in ``SITES``;
  * every ``SITES`` entry has at least one ``fire`` site (a cataloged
    faultpoint nothing fires is dead chaos surface);
  * the site names in docs/RESILIENCE.md's catalog table (the
    ``| `site` |`` rows) equal the ``SITES`` keys exactly;
  * ``fire`` is never called with a computed site name.
"""

from __future__ import annotations

import ast
import re

from analysis.core import Finding, Project, literal_dict, str_const

RULE_ID = "faultpoint-coherence"

_DOC_SITE_RE = re.compile(r"^\|\s*`([a-z_]+\.[a-z_]+)`", re.MULTILINE)


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    if not project.faults_path:
        return findings
    faults_sf = next(
        (s for s in project.files() if s.rel == project.faults_path), None
    )
    if faults_sf is None or faults_sf.tree is None:
        return [Finding(
            RULE_ID, project.faults_path, 1,
            "faultpoint catalog module missing or unparseable",
        )]
    sites_catalog = literal_dict(
        project.faults_path, faults_sf.tree, "SITES"
    )
    if not isinstance(sites_catalog, dict):
        return [Finding(
            RULE_ID, faults_sf.rel, 1,
            "SITES must be a literal dict of site -> supported modes",
        )]

    fired: dict[str, list[tuple[str, int]]] = {}
    for sf in project.files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if name != "fire" or not node.args:
                continue
            site = str_const(node.args[0])
            if site is None:
                # fire(site) inside faults.py itself is the dispatcher;
                # a computed site anywhere else defeats arm-time checking
                if sf.rel != project.faults_path:
                    findings.append(Finding(
                        RULE_ID, sf.rel, node.lineno,
                        "faults.fire() with a computed site name — sites "
                        "are a closed catalog",
                    ))
                continue
            fired.setdefault(site, []).append((sf.rel, node.lineno))

    for site, where in sorted(fired.items()):
        if site not in sites_catalog:
            rel, line = where[0]
            findings.append(Finding(
                RULE_ID, rel, line,
                f"fire({site!r}) references a site missing from the "
                f"SITES catalog in {project.faults_path}",
            ))
    for site in sorted(set(sites_catalog) - set(fired)):
        findings.append(Finding(
            RULE_ID, faults_sf.rel, 1,
            f"SITES entry {site!r} has no fire() site anywhere — dead "
            "chaos surface",
        ))

    if project.resilience_doc:
        doc = project.read_doc(project.resilience_doc)
        if doc is None:
            findings.append(Finding(
                RULE_ID, faults_sf.rel, 1,
                f"cross-check doc {project.resilience_doc} not found",
            ))
        else:
            doc_sites = set(_DOC_SITE_RE.findall(doc))
            for site in sorted(set(sites_catalog) - doc_sites):
                findings.append(Finding(
                    RULE_ID, faults_sf.rel, 1,
                    f"site {site!r} is in SITES but missing from the "
                    f"{project.resilience_doc} catalog table",
                ))
            for site in sorted(doc_sites - set(sites_catalog)):
                findings.append(Finding(
                    RULE_ID, faults_sf.rel, 1,
                    f"{project.resilience_doc} documents site {site!r} "
                    "which is not in SITES",
                ))
    return findings
