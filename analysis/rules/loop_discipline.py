"""Rule ``loop-discipline`` (R2): the event loop never blocks, and
cross-thread entry points never touch loop-only internals.

Functions decorated ``@loop_only`` / ``@cross_thread``
(``machine_learning_replications_tpu.contracts``) declare which thread
may run them. Statically enforced, per decorated function body:

  * inside ``@loop_only``: no blocking primitives —

      - ``time.sleep``
      - ``socket.create_connection`` / ``<sock>.connect`` (the loop
        uses non-blocking ``connect_ex``), ``<sock>.makefile``
      - anything reached through ``http.client``
      - ``<lock>.acquire()`` with no ``timeout=``/``blocking=False``
        (an un-timed acquire is an unbounded stall for every socket the
        loop owns; ``with lock:`` around plain state is fine — the rule
        targets the explicit-acquire pattern used for long holds)
      - un-timed ``<thread>.join()``

  * inside ``@cross_thread``: no direct calls (``self.x()`` / ``obj.x()``
    / bare ``x()``) to any name declared ``@loop_only`` anywhere in the
    same file — cross-thread code must marshal through the wake pipe
    (``_post``/``call_later``), never run loop internals off-thread;

  * one function must not carry both decorators.

The check is name-based within one file — the honest scope for a stdlib
AST: it will not follow a call through an alias or another module.
That covers the real hazard (a maintainer "just calling" a loop method
from a handler thread three lines away) without pretending to be a type
system.
"""

from __future__ import annotations

import ast

from analysis.core import Finding, Project, dotted

RULE_ID = "loop-discipline"

_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep() blocks the event loop",
    "socket.create_connection":
        "socket.create_connection() is a blocking connect "
        "(use non-blocking connect_ex through the loop)",
}
_BLOCKING_PREFIXES = {
    "http.client": "http.client is a blocking HTTP stack "
    "(use the loop-owned UpstreamPool)",
}
_BLOCKING_METHODS = {
    "connect": "blocking socket connect (use connect_ex on a "
    "non-blocking socket)",
    "makefile": "socket.makefile() wraps the socket in blocking "
    "file I/O",
}
# ``.get()`` is deliberately NOT here: a bare no-arg ``get`` is the
# metric-family child accessor (``FAMILY.get().inc()``) all over the
# loop's hot paths; a blocking queue read would be ``get(timeout=…)``,
# which no list can tell from ``dict.get(k, d)`` by name alone.
_UNTIMED_METHODS = {
    "acquire": "un-timed Lock.acquire() can stall the loop forever "
    "(pass timeout= or blocking=False)",
    "join": "un-timed join() blocks the loop (pass timeout=)",
}


def _decorations(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    out = set()
    for dec in fn.decorator_list:
        node = dec.func if isinstance(dec, ast.Call) else dec
        name = node.attr if isinstance(node, ast.Attribute) else (
            node.id if isinstance(node, ast.Name) else None
        )
        if name in ("loop_only", "cross_thread"):
            out.add(name)
    return out


def _is_bounded(call: ast.Call, meth: str) -> bool:
    """True when an acquire()/join() call provably cannot block forever:
    a ``timeout=`` keyword, or (acquire only) a first argument /
    ``blocking=`` keyword that is literally False. ``acquire(True)`` and
    ``acquire(blocking=True)`` are exactly the un-timed blocking acquire
    the rule exists to ban — an argument's presence is not boundedness."""
    for kw in call.keywords:
        if kw.arg == "timeout":
            return True
        if meth == "acquire" and kw.arg == "blocking":
            return (isinstance(kw.value, ast.Constant)
                    and kw.value.value is False)
    if call.args:
        if meth == "acquire":
            first = call.args[0]
            if isinstance(first, ast.Constant) and first.value is False:
                return True
            # acquire(True, 5) / acquire(False, anything): a second
            # positional is the timeout
            return len(call.args) > 1
        return True  # join(5) — positional timeout
    return False


def _own_body_calls(fn: ast.FunctionDef | ast.AsyncFunctionDef):
    """Call nodes in fn's body, excluding nested function/class defs —
    a closure handed to call_later runs later ON the loop, so its body
    is not this function's thread context."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_loop_only_body(fn, sf_rel: str) -> list[Finding]:
    findings = []
    for call in _own_body_calls(fn):
        chain = dotted(call.func)
        if chain in _BLOCKING_DOTTED:
            findings.append(Finding(
                RULE_ID, sf_rel, call.lineno,
                f"@loop_only {fn.name}: {_BLOCKING_DOTTED[chain]}",
            ))
            continue
        if chain:
            for prefix, why in _BLOCKING_PREFIXES.items():
                if chain == prefix or chain.startswith(prefix + "."):
                    findings.append(Finding(
                        RULE_ID, sf_rel, call.lineno,
                        f"@loop_only {fn.name}: {why}",
                    ))
        if isinstance(call.func, ast.Attribute):
            meth = call.func.attr
            if meth in _BLOCKING_METHODS:
                findings.append(Finding(
                    RULE_ID, sf_rel, call.lineno,
                    f"@loop_only {fn.name}: {_BLOCKING_METHODS[meth]}",
                ))
            elif meth in _UNTIMED_METHODS:
                timed = _is_bounded(call, meth)
                if not timed:
                    findings.append(Finding(
                        RULE_ID, sf_rel, call.lineno,
                        f"@loop_only {fn.name}: {_UNTIMED_METHODS[meth]}",
                    ))
    return findings


def _check_cross_thread_body(fn, loop_only_names: set[str],
                             sf_rel: str) -> list[Finding]:
    findings = []
    for call in _own_body_calls(fn):
        f = call.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if name in loop_only_names:
            findings.append(Finding(
                RULE_ID, sf_rel, call.lineno,
                f"@cross_thread {fn.name} calls @loop_only {name}() "
                "directly; marshal onto the loop (_post / call_later) "
                "instead",
            ))
    return findings


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files():
        if sf.tree is None or "loop_only" not in sf.text:
            continue
        decorated: list[tuple[ast.FunctionDef, set[str]]] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                marks = _decorations(node)
                if marks:
                    decorated.append((node, marks))
        loop_only_names = {
            fn.name for fn, marks in decorated if "loop_only" in marks
        }
        for fn, marks in decorated:
            if marks == {"loop_only", "cross_thread"}:
                findings.append(Finding(
                    RULE_ID, sf.rel, fn.lineno,
                    f"{fn.name} is annotated both @loop_only and "
                    "@cross_thread — a function has one thread contract",
                ))
                continue
            if "loop_only" in marks:
                findings.extend(_check_loop_only_body(fn, sf.rel))
            if "cross_thread" in marks:
                findings.extend(_check_cross_thread_body(
                    fn, loop_only_names - {fn.name}, sf.rel
                ))
    return findings
