"""The repo-specific graftcheck configuration — the checked-in manifests.

This module is the single source of truth for WHICH modules are declared
jax-free (rule ``import-purity``). The prose that used to make that claim
("Deliberately jax-free: a router process starts in milliseconds…") now
cites the rule id; this list is what CI actually proves.
"""

from __future__ import annotations

import os

from analysis.core import Project

PACKAGE = "machine_learning_replications_tpu"

#: Modules whose TRANSITIVE import-time closure must never reach jax or
#: jaxlib. Parent packages count — importing ``a.b.c`` executes
#: ``a/__init__`` and ``a/b/__init__`` first, so an eager re-export in an
#: ``__init__`` breaks the child's purity (exactly how ``score.reader``
#: was found reaching jax through ``data/__init__`` before PR 13).
JAXFREE = (
    # The fleet tier: a router/autoscaler process starts in milliseconds
    # on hosts with no accelerator stack (docs/FLEET.md).
    f"{PACKAGE}.fleet",
    f"{PACKAGE}.fleet.autoscale",
    f"{PACKAGE}.fleet.deploy",
    f"{PACKAGE}.fleet.health",
    f"{PACKAGE}.fleet.lifecycle",
    f"{PACKAGE}.fleet.registry",
    f"{PACKAGE}.fleet.router",
    # The continual-learning trigger polls replicas over HTTP; it runs
    # beside the router (docs/CONTINUAL.md).
    f"{PACKAGE}.learn.trigger",
    # Provenance and metrics: bench.py's orchestrator must never touch
    # the TPU plugin (obs/journal.py module docstring).
    f"{PACKAGE}.obs.journal",
    f"{PACKAGE}.obs.registry",
    # The alerting plane rides router and replica processes alike; the
    # router side must stay accelerator-free (docs/OBSERVABILITY.md
    # "Alerting & incidents").
    f"{PACKAGE}.obs.timeseries",
    f"{PACKAGE}.obs.alerts",
    f"{PACKAGE}.obs.incident",
    # Bulk-score input parsing: the reader side of the score pipeline
    # (host-only parse/validate/quarantine) stays importable without jax.
    f"{PACKAGE}.score.reader",
    # Ops tooling that must run against live processes from bare hosts.
    "tools.loadgen",
    "tools.chaos_drill",
    "tools.obs_report",
    "tools.validate_metrics",
    "tools.fleet_bench",
    "tools.graftcheck",
    "tools.incident_report",
)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_project(root: str | None = None) -> Project:
    """The Project describing this repository."""
    return Project(
        root=root or repo_root(),
        package=PACKAGE,
        tool_dirs=("tools", "analysis"),
        jaxfree=JAXFREE,
        # flax is forbidden alongside jax: importing flax imports jax
        # unconditionally (flax.core pulls jax at its own import time),
        # so a flax edge IS a jax edge — the empirically traced chain
        # score/__init__ -> … -> models/scaler.py -> flax -> jax was
        # invisible until flax joined this set.
        forbidden_imports=("jax", "jaxlib", "flax"),
        catalog_path=f"{PACKAGE}/obs/catalog.py",
        faults_path=f"{PACKAGE}/resilience/faults.py",
        resilience_doc="docs/RESILIENCE.md",
        observability_doc="docs/OBSERVABILITY.md",
    )


def baseline_path(root: str | None = None) -> str:
    return os.path.join(root or repo_root(), "analysis", "baseline.json")
