#!/usr/bin/env python
"""Population-scale bulk-scoring bench — writes a SCORE_BENCH_*.json artifact.

Measures the `cli score` workload end-to-end (ingest included — the number
request-serving benches cannot produce) on a synthetic JSONL cohort:

  1. **generate** a patient cohort (``data.synthetic.make_cohort`` →
     17-variable contract dicts, the ``loadgen --patients`` format),
     unless ``--cohort`` reuses one;
  2. run ``cli score --sequential`` — the ablation: read → parse →
     device → write strictly serialized;
  3. run the overlapped pipeline (reader + parse workers + double-
     buffered device stage + ordered writer) on the same input;
  4. assert the two outputs are byte-identical (overlap must be a pure
     optimization) and record rows/s + the per-stage busy-seconds split
     from each run's ``summary.json``;
  5. optionally (``--resume-check``) SIGKILL an overlapped run partway
     through — a real kill -9, not a simulated exception — rerun it to
     completion, and assert the resumed output's sha256 equals the
     uninterrupted run's.

Every `cli score` invocation is a fresh subprocess (cold jax, honest
end-to-end wall clock) with ``--journal``; the artifact embeds each run's
manifest digest so the BENCH.md cell names exactly what produced it.

Run from the repo root::

    JAX_PLATFORMS=cpu python tools/score_bench.py --model /path/to/ckpt \\
        --rows 1000000 --resume-check --out SCORE_BENCH_r13_cpu.json
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)


def say(msg: str) -> None:
    print(f"[score_bench] {msg}", file=sys.stderr, flush=True)


def generate_cohort(path: str, rows: int, seed: int) -> float:
    """Write ``rows`` patient dicts as JSONL; returns generation seconds."""
    import numpy as np  # noqa: F401 — make_cohort's dependency

    from machine_learning_replications_tpu.data import make_cohort
    from machine_learning_replications_tpu.data.schema import (
        SELECTED_17,
        selected_indices,
    )

    t0 = time.perf_counter()
    say(f"generating {rows}-row cohort -> {path}")
    X64, _, _ = make_cohort(n=rows, seed=seed, missing_rate=0.0)
    C = X64[:, selected_indices()]
    with open(path, "w") as f:
        for row in C:
            f.write(json.dumps(
                {k: float(v) for k, v in zip(SELECTED_17, row)}
            ) + "\n")
    dt = time.perf_counter() - t0
    say(f"cohort generated in {dt:.1f}s "
        f"({os.path.getsize(path) / 1e6:.1f} MB)")
    return dt


def score_cmd(args, out_dir: str, sequential: bool) -> list[str]:
    cmd = [
        sys.executable, "-m", "machine_learning_replications_tpu", "score",
        "--cohort", args.cohort, "--out", out_dir,
        "--chunk-rows", str(args.chunk_rows),
        "--parse-workers", str(args.parse_workers),
        "--parse-procs", str(args.parse_procs),
        "--prefetch", str(args.prefetch),
        "--journal", os.path.join(out_dir, "journal.jsonl"),
    ]
    if args.model:
        cmd += ["--model", args.model]
    if args.pkl:
        cmd += ["--pkl", args.pkl]
    if sequential:
        cmd += ["--sequential"]
    if args.no_quality:
        cmd += ["--no-quality"]
    return cmd


def run_score(args, out_dir: str, sequential: bool) -> dict:
    """One leg, best-of-``--repeats`` (the BENCH.md convention: this
    sandbox class sees ~0.5 s co-tenant stalls, and a single 1M-row wall
    clock can swing ±25%): each repeat is a fresh subprocess into a fresh
    directory; the best pipeline wall is the quoted cell, every repeat's
    rows/s is recorded as the range."""
    label = "sequential" if sequential else "overlapped"
    best, rates = None, []
    for rep in range(max(1, args.repeats)):
        rep_dir = out_dir if args.repeats <= 1 else f"{out_dir}_r{rep}"
        os.makedirs(rep_dir, exist_ok=True)
        say(f"{label} run {rep + 1}/{args.repeats} -> {rep_dir}")
        t0 = time.perf_counter()
        subprocess.run(
            score_cmd(args, rep_dir, sequential), check=True,
            stdout=subprocess.DEVNULL,
        )
        wall = time.perf_counter() - t0
        with open(os.path.join(rep_dir, "summary.json")) as f:
            summary = json.load(f)
        say(
            f"{label}: {summary['rows']} rows at "
            f"{summary['rows_per_second']} rows/s (pipeline wall "
            f"{summary['wall_seconds']}s, process wall {wall:.1f}s incl. "
            "jax start)"
        )
        rates.append(summary["rows_per_second"])
        cell = {
            "rows": summary["rows"],
            "chunks": summary["chunks"],
            "bad_rows": summary["bad_rows"],
            "wall_seconds": summary["wall_seconds"],
            "process_wall_seconds": round(wall, 3),
            "rows_per_second": summary["rows_per_second"],
            "stage_seconds": summary["stage_seconds"],
            "output_sha256": summary["output_sha256"],
            "jax_compiles": summary.get("jax_compiles"),
            "run_id": (summary.get("manifest") or {}).get("run_id"),
            "config_hash": (summary.get("manifest") or {}).get("config_hash"),
        }
        if best is None or cell["wall_seconds"] < best["wall_seconds"]:
            best = cell
    best["rows_per_second_runs"] = rates
    return best


def resume_check(args, golden_sha: str, workdir: str) -> dict:
    """Kill -9 an overlapped run partway, resume it, compare output."""
    out_dir = os.path.join(workdir, "resume")
    os.makedirs(out_dir, exist_ok=True)
    progress_path = os.path.join(out_dir, "progress.json")
    say("resume check: starting run to be killed")
    proc = subprocess.Popen(
        score_cmd(args, out_dir, sequential=False),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    # Kill once real progress is committed (≥ 2 chunks) — mid-stream, not
    # at the edges.
    killed_after = None
    t0 = time.perf_counter()
    while proc.poll() is None:
        time.sleep(0.25)
        try:
            with open(progress_path) as f:
                chunks = json.load(f).get("chunks", 0)
        except (OSError, json.JSONDecodeError):
            chunks = 0
        if chunks >= max(2, args.kill_after_chunks):
            proc.send_signal(signal.SIGKILL)
            killed_after = chunks
            break
    proc.wait()
    if killed_after is None:
        return {"ok": False, "error": "run finished before the kill fired"}
    say(f"killed (SIGKILL) after ~{killed_after} committed chunks "
        f"({time.perf_counter() - t0:.1f}s in); resuming")
    subprocess.run(
        score_cmd(args, out_dir, sequential=False), check=True,
        stdout=subprocess.DEVNULL,
    )
    with open(os.path.join(out_dir, "summary.json")) as f:
        summary = json.load(f)
    identical = summary["output_sha256"] == golden_sha
    say(f"resumed at chunk {summary['resumed_chunks']}; output "
        + ("IDENTICAL to uninterrupted run" if identical else "DIFFERS"))
    return {
        "ok": identical,
        "killed_after_chunks": killed_after,
        "resumed_chunks": summary["resumed_chunks"],
        "resumed_rows": summary["resumed_rows"],
        "rows": summary["rows"],
        "output_sha256": summary["output_sha256"],
        "identical_to_uninterrupted": identical,
    }


def shard_sha256(out_dir: str) -> str:
    h = hashlib.sha256()
    for name in sorted(os.listdir(out_dir)):
        if name.startswith("scores-") and name.endswith(".jsonl"):
            with open(os.path.join(out_dir, name), "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
    return h.hexdigest()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--model", help="Orbax checkpoint dir")
    ap.add_argument("--pkl", help="legacy sklearn pickle")
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--seed", type=int, default=2020)
    ap.add_argument(
        "--cohort", default=None,
        help="existing JSONL cohort (skips generation)",
    )
    ap.add_argument("--chunk-rows", type=int, default=2048)
    ap.add_argument("--parse-workers", type=int, default=2)
    ap.add_argument("--parse-procs", type=int, default=0)
    ap.add_argument("--prefetch", type=int, default=4)
    ap.add_argument(
        "--no-quality", action="store_true",
        help="skip the cohort quality monitor in the timed runs",
    )
    ap.add_argument(
        "--resume-check", action="store_true",
        help="also run the SIGKILL + resume verification leg",
    )
    ap.add_argument("--kill-after-chunks", type=int, default=2)
    ap.add_argument(
        "--repeats", type=int, default=1,
        help="repeats per timed leg; the best wall is quoted, all rows/s "
        "recorded (best-of-N, the BENCH.md noise convention)",
    )
    ap.add_argument(
        "--workdir", default="score_bench_work",
        help="scratch dir for cohort + run outputs",
    )
    ap.add_argument("--out", default=None, help="artifact path (JSON)")
    args = ap.parse_args(argv)

    from machine_learning_replications_tpu.obs.journal import run_manifest

    os.makedirs(args.workdir, exist_ok=True)
    gen_seconds = None
    if args.cohort is None:
        args.cohort = os.path.join(args.workdir, f"cohort_{args.rows}.jsonl")
        if os.path.exists(args.cohort):
            say(f"reusing cohort {args.cohort}")
        else:
            gen_seconds = generate_cohort(args.cohort, args.rows, args.seed)

    seq = run_score(args, os.path.join(args.workdir, "seq"), sequential=True)
    ovl = run_score(args, os.path.join(args.workdir, "ovl"), sequential=False)
    outputs_identical = seq["output_sha256"] == ovl["output_sha256"]
    speedup = (
        round(seq["wall_seconds"] / ovl["wall_seconds"], 2)
        if ovl["wall_seconds"] else None
    )
    say(f"overlap speedup: {speedup}x "
        f"({seq['rows_per_second']} -> {ovl['rows_per_second']} rows/s); "
        f"outputs {'identical' if outputs_identical else 'DIFFER'}")

    resume = None
    if args.resume_check:
        resume = resume_check(args, ovl["output_sha256"], args.workdir)

    artifact = {
        "kind": "score_bench",
        "rows": seq["rows"],
        "chunk_rows": args.chunk_rows,
        "parse_workers": args.parse_workers,
        "prefetch": args.prefetch,
        "quality": not args.no_quality,
        "cohort": os.path.abspath(args.cohort),
        "cohort_bytes": os.path.getsize(args.cohort),
        "generate_seconds": (
            round(gen_seconds, 1) if gen_seconds is not None else None
        ),
        "sequential": seq,
        "overlapped": ovl,
        "overlap_speedup": speedup,
        "outputs_identical": outputs_identical,
        "resume": resume,
        "manifest": run_manifest(command="score_bench"),
    }
    line = json.dumps(artifact, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        say(f"artifact written to {args.out}")
    ok = outputs_identical and (resume is None or resume.get("ok"))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
