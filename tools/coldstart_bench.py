"""Cold-start bench: replica cold-start-to-ready and rolling-deploy hold,
traced vs AOT-restored (docs/AOT.md).

The compile wall is a *fixed* cost every replica start pays — it paces
one-at-a-time deploy holds, the learn loop's promotion window, and the
autoscaler's reaction time. This bench measures exactly the two arcs
that cost shows up in, before/after AOT executable restore, on the same
host with the same config:

  cold start    spawn a real ``cli serve`` subprocess on a published
                checkpoint and time spawn → ``/readyz`` 200. The traced
                leg runs ``--no-aot`` (the escape hatch forces the
                compile path); the AOT leg restores the checkpoint's
                published executable bundle.
  deploy hold   a replica with ``--admin-endpoint`` warm-swaps onto a
                second published version; the hold is the wall time of
                the ``POST /admin/deploy`` (load + build + warm +
                parity + swap — what the fleet controller serializes
                rollouts on).

Both legs assert the parity contract on the way: the traced and AOT
replicas must serve BIT-IDENTICAL probabilities for the same patient
(the tentpole's correctness claim), and the AOT leg must restore with
zero journaled fallbacks.

Usage (CPU sandbox)::

    JAX_PLATFORMS=cpu python tools/coldstart_bench.py \\
        --repeats 3 --out COLDSTART_r18_cpu.json

    # CI smoke: tiny ladder, one repeat, same assertions
    JAX_PLATFORMS=cpu python tools/coldstart_bench.py --tiny --out /tmp/cs.json

The artifact embeds the run manifest (``obs.journal.run_manifest``),
per-leg raw samples with best-of ranges, and the per-bucket
``serve_warmup_seconds`` / ``serve_aot_restore_seconds`` gauges scraped
from the live replicas. ``tools/obs_report.py --coldstart`` renders it.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from chaos_drill import _free_port, make_sklearn_params  # noqa: E402

POLL_S = 0.05


def _serve_cmd(ckpt: str, port: int, buckets: str, no_aot: bool,
               admin: bool = False, journal: str | None = None) -> list[str]:
    cmd = [
        sys.executable, "-m", "machine_learning_replications_tpu",
        "serve", "--model", ckpt, "--port", str(port),
        "--buckets", buckets, "--max-wait-ms", "2",
    ]
    if no_aot:
        cmd.append("--no-aot")
    if admin:
        cmd.append("--admin-endpoint")
    if journal:
        cmd += ["--journal", journal]
    return cmd


def _get_json(base: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _wait_ready(base: str, deadline_s: float) -> float:
    """Poll /readyz until 200; returns the time it first answered ready
    (monotonic). Raises on the deadline."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(base + "/readyz", timeout=2.0):
                return time.monotonic()
        except (urllib.error.URLError, urllib.error.HTTPError, OSError):
            time.sleep(POLL_S)
    raise AssertionError(f"replica at {base} never became ready")


def _predict(base: str) -> float:
    from machine_learning_replications_tpu.data.examples import (
        EXAMPLE_PATIENT,
    )

    req = urllib.request.Request(
        base + "/predict", data=json.dumps(dict(EXAMPLE_PATIENT)).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30.0) as r:
        return json.loads(r.read())["probability"]


_GAUGE_RE = re.compile(
    r'^(serve_(?:warmup|aot_restore)_seconds)\{([^}]*)\}\s+(\S+)$'
)


def _scrape_warmup_gauges(base: str) -> dict:
    """The per-bucket warmup/restore gauges off /metrics — the split the
    deploy controller and autoscaler read (satellite: timings flow
    through stage_scope + gauges, not stderr prints)."""
    with urllib.request.urlopen(base + "/metrics", timeout=10.0) as r:
        page = r.read().decode()
    out: dict[str, dict[str, float]] = {}
    for line in page.splitlines():
        m = _GAUGE_RE.match(line)
        if m:
            out.setdefault(m.group(1), {})[m.group(2)] = float(m.group(3))
    return out


def _journal_kinds(path: str) -> tuple[set, set]:
    """(event kinds, aot_fallback reasons) from one replica journal."""
    kinds, reasons = set(), set()
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                kinds.add(e.get("kind"))
                if e.get("kind") == "aot_fallback":
                    reasons.add(e.get("reason"))
    return kinds, reasons


def _cold_start_leg(ckpt: str, buckets: str, no_aot: bool, repeats: int,
                    workdir: str, ready_deadline_s: float) -> dict:
    """N cold starts of one mode; returns raw samples + the last
    replica's golden probability, warmup gauges, and journal kinds."""
    samples, golden, gauges = [], None, {}
    kinds, reasons = set(), set()
    for i in range(repeats):
        port = _free_port()
        base = f"http://127.0.0.1:{port}"
        jpath = os.path.join(
            workdir, f"cs_{'traced' if no_aot else 'aot'}_{i}.jsonl"
        )
        t0 = time.monotonic()
        proc = subprocess.Popen(
            _serve_cmd(ckpt, port, buckets, no_aot, journal=jpath),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        try:
            t_ready = _wait_ready(base, ready_deadline_s)
            samples.append(round(t_ready - t0, 3))
            golden = _predict(base)
            gauges = _scrape_warmup_gauges(base)
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        k, r = _journal_kinds(jpath)
        kinds |= k
        reasons |= r
    return {
        "ready_s": samples,
        "best_ready_s": min(samples),
        "range_s": [min(samples), max(samples)],
        "golden": golden,
        "warmup_gauges": gauges,
        "journal_kinds": sorted(k for k in kinds if k),
        "fallback_reasons": sorted(r for r in reasons if r),
    }


def _deploy_hold_leg(ckpt_v1: str, ckpt_v2: str, buckets: str,
                     no_aot: bool, repeats: int, workdir: str,
                     ready_deadline_s: float) -> dict:
    """One long-lived replica per mode; N warm-swap deploys onto the v2
    checkpoint, each hold = the POST /admin/deploy wall time."""
    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    jpath = os.path.join(
        workdir, f"dh_{'traced' if no_aot else 'aot'}.jsonl"
    )
    proc = subprocess.Popen(
        _serve_cmd(ckpt_v1, port, buckets, no_aot, admin=True,
                   journal=jpath),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    holds, golden = [], None
    try:
        _wait_ready(base, ready_deadline_s)
        for _ in range(repeats):
            req = urllib.request.Request(
                base + "/admin/deploy",
                data=json.dumps({"model": ckpt_v2}).encode(),
                headers={"Content-Type": "application/json"},
            )
            t0 = time.monotonic()
            with urllib.request.urlopen(req, timeout=600.0) as r:
                report = json.loads(r.read())["deploy"]
            holds.append(round(time.monotonic() - t0, 3))
            assert report["result"] == "ok", report
        golden = _predict(base)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
    return {
        "hold_s": holds,
        "best_hold_s": min(holds),
        "range_s": [min(holds), max(holds)],
        "golden": golden,
        "journal_kinds": sorted(
            k for k in _journal_kinds(jpath)[0] if k
        ),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default=None, help="artifact path (JSON)")
    ap.add_argument(
        "--repeats", type=int, default=3,
        help="cold starts (and deploys) per mode; best-of reported with "
        "the full range",
    )
    ap.add_argument(
        "--buckets", default="1,8,32,64,128,256,512",
        help="serving ladder under test (the checkpoint's AOT bundle "
        "always covers the default ladder + host ladder)",
    )
    ap.add_argument(
        "--tiny", action="store_true",
        help="CI smoke mode: 1,8 ladder, one repeat — exercises the "
        "whole publish→restore→parity arc in seconds, asserts the same "
        "contracts, proves nothing about speed",
    )
    ap.add_argument(
        "--ready-deadline", type=float, default=600.0,
        help="seconds a spawned replica may take to become ready",
    )
    ap.add_argument("--keep-workdir", action="store_true")
    args = ap.parse_args(argv)
    if args.tiny:
        args.repeats, args.buckets = 1, "1,8"

    from machine_learning_replications_tpu.obs import journal
    from machine_learning_replications_tpu.persist import orbax_io

    t_start = time.monotonic()
    workdir = tempfile.mkdtemp(prefix="coldstart_bench_")
    try:
        # One checkpoint with the AOT bundle serves BOTH legs: the
        # traced leg is `serve --no-aot` over the same bytes — same
        # model, same config, the only variable is restore vs compile.
        ckpt_v1 = os.path.join(workdir, "model_v1")
        ckpt_v2 = os.path.join(workdir, "model_v2")
        print("publishing checkpoints (with AOT bundles)…",
              file=sys.stderr)
        t0 = time.monotonic()
        orbax_io.save_model(ckpt_v1, make_sklearn_params(seed=7), aot=True)
        orbax_io.save_model(ckpt_v2, make_sklearn_params(seed=11), aot=True)
        publish_s = round(time.monotonic() - t0, 3)

        legs = {}
        for mode, no_aot in (("traced", True), ("aot", False)):
            print(f"cold start × {args.repeats} [{mode}]…", file=sys.stderr)
            legs[mode] = _cold_start_leg(
                ckpt_v1, args.buckets, no_aot, args.repeats, workdir,
                args.ready_deadline,
            )
            print(f"  ready_s={legs[mode]['ready_s']}", file=sys.stderr)
        holds = {}
        for mode, no_aot in (("traced", True), ("aot", False)):
            print(f"deploy hold × {args.repeats} [{mode}]…",
                  file=sys.stderr)
            holds[mode] = _deploy_hold_leg(
                ckpt_v1, ckpt_v2, args.buckets, no_aot, args.repeats,
                workdir, args.ready_deadline,
            )
            print(f"  hold_s={holds[mode]['hold_s']}", file=sys.stderr)

        # The contracts the speedup is worthless without.
        bit_identical = legs["traced"]["golden"] == legs["aot"]["golden"]
        deploy_bit_identical = (
            holds["traced"]["golden"] == holds["aot"]["golden"]
        )
        aot_restored = "aot_restore" in legs["aot"]["journal_kinds"]
        # missing_bucket is excluded from the cleanliness contract: a
        # caller-supplied --buckets value outside the published ladder
        # legitimately traces that bucket (correct, fails-open) — the
        # contract is about BAD artifacts (corrupt/mismatched blobs),
        # and the full reason list rides the artifact either way.
        aot_clean = not (
            set(legs["aot"]["fallback_reasons"]) - {"missing_bucket"}
        )

        config = {
            "buckets": args.buckets, "repeats": args.repeats,
            "tiny": args.tiny,
        }
        artifact = {
            "kind": "coldstart_bench",
            "manifest": journal.run_manifest(
                command="coldstart_bench",
                config_json=json.dumps(config, sort_keys=True),
            ),
            "config": config,
            "publish_with_aot_s": publish_s,
            "cold_start": {
                **legs,
                "speedup_best": round(
                    legs["traced"]["best_ready_s"]
                    / legs["aot"]["best_ready_s"], 2,
                ),
                "saved_s_best": round(
                    legs["traced"]["best_ready_s"]
                    - legs["aot"]["best_ready_s"], 3,
                ),
            },
            "deploy_hold": {
                **holds,
                "speedup_best": round(
                    holds["traced"]["best_hold_s"]
                    / holds["aot"]["best_hold_s"], 2,
                ),
                "saved_s_best": round(
                    holds["traced"]["best_hold_s"]
                    - holds["aot"]["best_hold_s"], 3,
                ),
            },
            "contracts": {
                "bit_identical_cold_start": bit_identical,
                "bit_identical_post_deploy": deploy_bit_identical,
                "aot_restored": aot_restored,
                "aot_zero_fallbacks": aot_clean,
            },
            "duration_s": round(time.monotonic() - t_start, 3),
        }
        line = json.dumps(artifact, indent=1)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
            print(f"artifact written to {args.out}", file=sys.stderr)
        ok = (
            bit_identical and deploy_bit_identical
            and aot_restored and aot_clean
        )
        if not ok:
            print("COLDSTART CONTRACTS VIOLATED", file=sys.stderr)
            return 1
        print(
            "cold start best-of: traced "
            f"{legs['traced']['best_ready_s']}s vs aot "
            f"{legs['aot']['best_ready_s']}s "
            f"({artifact['cold_start']['speedup_best']}×); deploy hold "
            f"{holds['traced']['best_hold_s']}s vs "
            f"{holds['aot']['best_hold_s']}s "
            f"({artifact['deploy_hold']['speedup_best']}×); outputs "
            "bit-identical",
            file=sys.stderr,
        )
        return 0
    finally:
        if args.keep_workdir:
            print(f"workdir kept at {workdir}", file=sys.stderr)
        else:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
