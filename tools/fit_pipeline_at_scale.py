#!/usr/bin/env python
"""End-to-end `fit_pipeline` at production scale on one chip.

Evidence runner for the scale contract (SURVEY.md §2.5 "Rows of the cohort
... all fits"; reference program `train_ensemble_public.py:33-62`): generate
an n-row 64-variable cohort with missingness, run the FULL pipeline —
impute → select → stack (SVC / GBDT / L1-LR members + 5-fold stacking CV +
meta-LR) — and score a held-out slice through the fitted transforms, the
way the reference scores its model_select cohort. Round 3's measured
ceiling was 400k rows (the select stage OOMed beyond); the covariance-form
LassoCV removed that wall, and this script is the proof. Per-stage wall
clock comes from the pipeline's own stage logging on stderr.

Prints ONE JSON line: {"rows": n, "total_s": ..., "phases_s": {...},
"auc_holdout": ..., "device": ...}.

Usage: python tools/fit_pipeline_at_scale.py --rows 4000000
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4_000_000)
    ap.add_argument("--missing-rate", type=float, default=0.02,
                    help="MCAR NaN fraction in continuous columns "
                         "(exercises the imputer at scale)")
    ap.add_argument("--holdout-rows", type=int, default=200_000)
    ap.add_argument("--seed", type=int, default=2020)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="durable per-stage checkpoints: a preempted run "
                         "re-entered with the same args resumes finished "
                         "stages instead of recomputing")
    ap.add_argument("--max-donors", type=int, default=None,
                    help="imputer donor cap (ImputerConfig.max_donors; "
                         "default keeps the config default). The donor "
                         "distance matrix is O(incomplete_rows x donors), "
                         "the dominant impute cost at multi-million rows; "
                         "1-NN fill quality saturates far below 10^5 donors")
    args = ap.parse_args()

    import dataclasses

    import jax
    import numpy as np

    from machine_learning_replications_tpu.config import ExperimentConfig
    from machine_learning_replications_tpu.data import make_cohort
    from machine_learning_replications_tpu.models import pipeline
    from machine_learning_replications_tpu.utils import metrics
    from machine_learning_replications_tpu.utils.trace import PhaseTimer

    cfg = ExperimentConfig()
    if args.max_donors is not None:
        # replace() on the EXISTING imputer config: a fresh ImputerConfig
        # would silently reset chunk_rows/n_neighbors to class defaults if
        # a non-default config is ever threaded through here (ADVICE r4).
        cfg = dataclasses.replace(
            cfg, imputer=dataclasses.replace(
                cfg.imputer, max_donors=args.max_donors
            )
        )

    d = jax.devices()[0]
    device = f"{d.platform}:{d.device_kind}"
    print(f"[scale] device {device}, rows {args.rows}", file=sys.stderr,
          flush=True)

    timer = PhaseTimer()
    t0 = time.perf_counter()
    with timer.phase("make_cohort"):
        X, y, _ = make_cohort(
            n=args.rows + args.holdout_rows, seed=args.seed,
            missing_rate=args.missing_rate,
        )
        X_fit, y_fit = X[: args.rows], y[: args.rows]
        X_hold, y_hold = X[args.rows:], y[args.rows:]

    with timer.phase("fit_pipeline") as ph:
        params, info = pipeline.fit_pipeline(
            X_fit, y_fit, cfg, checkpoint_dir=args.checkpoint_dir
        )
        ph.block(params.ensemble.meta.coef)

    with timer.phase("holdout_predict") as ph:
        proba = ph.block(pipeline.pipeline_predict_proba1(params, X_hold))

    import jax.numpy as jnp

    with timer.phase("holdout_auc") as ph:
        auc = float(ph.block(jax.jit(metrics.roc_auc)(
            jnp.asarray(np.asarray(y_hold, dtype=np.float32)), proba
        )))
    total = time.perf_counter() - t0

    rec = {
        "rows": args.rows,
        "missing_rate": args.missing_rate,
        "max_donors": cfg.imputer.max_donors,
        "total_s": round(total, 2),
        "phases_s": {k: round(v, 2) for k, v in timer.seconds.items()},
        "n_selected": info["n_selected"],
        "auc_holdout": round(auc, 6),
        "device": device,
    }
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
