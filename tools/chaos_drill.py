#!/usr/bin/env python
"""Chaos drill — drive the fault matrix against a live server and assert
the degradation contract; writes a CHAOS_*.json artifact.

The resilience layer's claim (docs/RESILIENCE.md) is a single invariant:

    Under every injected fault class, a client receives either a
    CORRECT answer or an EXPLICIT failure (503/504/500 or a closed
    connection) — never a wrong answer, never a hang.

This tool is the claim's executable form. It stands up a real serving
process (sklearn-imported ensemble, the same route the tests use), arms
each fault class through the guarded ``/debug/faults`` endpoint, drives
requests through the public HTTP surface, and classifies every outcome.
Any 200 whose probability differs from the pre-chaos golden reply is a
wrong answer; any request exceeding the hard client timeout is a hang;
either fails the drill (non-zero exit). The journal and ``/metrics`` are
then checked for the breaker/restart/rollback evidence, and the metrics
page must pass the strict Prometheus validator.

Scenarios:

  compute_fault     ``engine.compute:raise`` — failing device computes:
                    500s feed the breaker, it opens, requests shed 503 +
                    ``Retry-After``; a ``tools/loadgen.py --retries`` run
                    rides the degraded window; disarm -> supervised
                    restart -> 200s resume. Quantifies client impact via
                    the loadgen retry block.
  wedged_compute    ``engine.compute:delay`` past the flush deadline —
                    the watchdog abandons the compute (504 in bounded
                    time), the breaker opens, restart recovers.
  flush_delay       ``batcher.flush:delay`` — a slow flush answers late
                    but correctly (graceful latency fault, no breaker).
  edge_faults       ``server.parse:raise`` (explicit 500, body unread)
                    and ``server.respond:raise`` (connection dropped with
                    nothing written — never a partial 200).
  dual_path_routing the drill runs with the dual-path router enabled
                    (the ``cli serve`` default): both scoring paths
                    serve the golden bits, and a one-shot host-path
                    fault is absorbed by the transparent device
                    fallback — the client sees a correct 200, the
                    fallback counter moves.
  corrupt_restore   offline: a corrupted checkpoint rolls back to the
                    retained last-known-good (journaled), and the
                    rolled-back params serve the previous model's exact
                    predictions.
  save_interrupted  offline: ``persist.save:raise`` mid-publish leaves
                    the previous checkpoint fully intact and loadable.

Run from the repo root (CPU is fine)::

    JAX_PLATFORMS=cpu python tools/chaos_drill.py --out CHAOS_r10_cpu.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request
import warnings

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

HARD_TIMEOUT_S = 10.0  # any request slower than this counts as a HANG


class Outcomes:
    """Per-scenario outcome ledger; the invariant is computed over these."""

    def __init__(self) -> None:
        self.counts: dict[str, int] = {}
        self.wrong_answers = 0
        self.hangs = 0

    def add(self, kind: str) -> None:
        self.counts[kind] = self.counts.get(kind, 0) + 1

    def as_dict(self) -> dict:
        return {
            "outcomes": dict(sorted(self.counts.items())),
            "wrong_answers": self.wrong_answers,
            "hangs": self.hangs,
        }


def post_predict(base: str, patient: dict, golden: float | None,
                 out: Outcomes, pin: str | None = None) -> tuple[str, dict]:
    """One /predict request, classified. Returns (kind, info). ``pin``
    routes the request to a specific scoring path (X-Serve-Path) —
    scenarios asserting supervised-engine semantics (watchdog, flush
    faults) pin ``device`` so the probe exercises the batcher even when
    the dual-path router would answer it from the host."""
    body = json.dumps(patient).encode()
    headers = {"Content-Type": "application/json"}
    if pin:
        headers["X-Serve-Path"] = pin
    req = urllib.request.Request(
        base + "/predict", data=body, headers=headers,
    )
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=HARD_TIMEOUT_S) as resp:
            payload = json.loads(resp.read())
        prob = payload["probability"]
        if golden is not None and prob != golden:
            out.wrong_answers += 1
            out.add("wrong_200")
            return "wrong_200", {"probability": prob}
        out.add("ok")
        return "ok", {"probability": prob}
    except urllib.error.HTTPError as exc:
        exc.read()
        kind = f"http_{exc.code}"
        out.add(kind)
        return kind, {"retry_after": exc.headers.get("Retry-After")}
    except Exception as exc:
        if time.monotonic() - t0 >= HARD_TIMEOUT_S - 0.05:
            out.hangs += 1
            out.add("hang")
            return "hang", {"error": f"{type(exc).__name__}: {exc}"}
        out.add("conn_err")  # explicit transport failure — not a hang
        return "conn_err", {"error": f"{type(exc).__name__}: {exc}"}


def get_json(base: str, path: str):
    req = urllib.request.Request(base + path)
    try:
        with urllib.request.urlopen(req, timeout=HARD_TIMEOUT_S) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read() or b"{}")


def post_faults(base: str, op: dict):
    data = json.dumps(op).encode()
    req = urllib.request.Request(
        base + "/debug/faults", data=data,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=HARD_TIMEOUT_S) as resp:
        return json.loads(resp.read())


def wait_until(pred, timeout_s: float, what: str, poll_s: float = 0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll_s)
    raise AssertionError(f"timed out waiting for {what}")


def make_sklearn_params(seed: int):
    import numpy as np
    from sklearn.ensemble import (
        GradientBoostingClassifier, StackingClassifier,
    )
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler
    from sklearn.svm import SVC

    from machine_learning_replications_tpu.persist import import_stacking

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(160, 17))
    y = (X @ rng.normal(size=17) > 0).astype(float)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clf = StackingClassifier(
            estimators=[
                ("svc", make_pipeline(
                    StandardScaler(), SVC(probability=True, random_state=0))),
                ("gbc", GradientBoostingClassifier(
                    n_estimators=5, max_depth=1, random_state=0)),
                ("lg", LogisticRegression()),
            ],
            final_estimator=LogisticRegression(),
        ).fit(X, y)
    return import_stacking(clf)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--out", default=None, help="artifact path (JSON)")
    ap.add_argument(
        "--journal", default=None,
        help="journal path (default: a temp file, embedded in the artifact)",
    )
    args = ap.parse_args(argv)

    t_start = time.monotonic()
    from machine_learning_replications_tpu.data.examples import EXAMPLE_PATIENT
    from machine_learning_replications_tpu.obs import journal
    from machine_learning_replications_tpu.persist import orbax_io
    from machine_learning_replications_tpu.resilience import lastgood
    from machine_learning_replications_tpu.serve import make_server

    journal_path = args.journal or os.path.join(
        tempfile.mkdtemp(prefix="chaos_"), "chaos_journal.jsonl"
    )
    jrn = journal.RunJournal(journal_path, command="chaos_drill")
    journal.set_journal(jrn)

    params = make_sklearn_params(seed=7)
    patient = dict(EXAMPLE_PATIENT)
    scenarios: dict[str, dict] = {}

    # -- live-server scenarios ---------------------------------------------
    handle = make_server(
        params, port=0, buckets=(1, 8), max_wait_ms=2.0,
        supervise=True, flush_deadline_s=0.6, breaker_failures=2,
        restart_backoff_s=0.25, restart_backoff_max_s=2.0,
        fault_endpoint=True,
        # Routing ON for the whole drill (the cli serve default): the
        # degradation contract must hold with the dual-path router in
        # the loop — host-path failures fall back through the supervised
        # device path, so the breaker arc below is unchanged.
        host_path=True,
    ).start_background()
    host, port = handle.address
    base = f"http://{host}:{port}"
    try:
        # Golden reply: every later 200 must carry this exact probability.
        warm = Outcomes()
        kind, info = post_predict(base, patient, None, warm)
        assert kind == "ok", f"pre-chaos request failed: {kind} {info}"
        golden = info["probability"]

        # The endpoint guard is real: the snapshot works because this
        # server opted in (fault_endpoint=True).
        code, snap = get_json(base, "/debug/faults")
        assert code == 200 and snap["endpoint_enabled"], snap

        # --- scenario: compute_fault --------------------------------------
        out = Outcomes()
        post_faults(base, {"arm": "engine.compute:raise"})
        seen = {"http_500": 0, "http_503": 0}

        def breaker_is_open():
            k, info = post_predict(base, patient, golden, out)
            if k in seen:
                seen[k] += 1
            if k == "http_503":
                assert info["retry_after"] is not None, \
                    "degraded 503 must carry Retry-After"
            return k == "http_503"

        wait_until(breaker_is_open, 15.0, "breaker open (503 shed)")
        # The progression matters, not just the endpoint: the breaker
        # needs breaker_failures=2 explicit 500s before the first shed.
        assert seen["http_500"] >= 2, seen
        code, health = get_json(base, "/healthz")
        assert code == 200 and health["status"] == "degraded", health
        assert health["ready"] is False
        code, ready = get_json(base, "/readyz")
        assert code == 503 and "degraded: circuit breaker open" in \
            ready["reasons"], ready

        # Patient clients ride the degraded window: loadgen retries with
        # backoff + Retry-After while we disarm mid-run.
        lg_out = os.path.join(os.path.dirname(journal_path), "lg_chaos.json")
        lg = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "loadgen.py"),
             "--url", base, "--mode", "closed", "--concurrency", "2",
             "--duration", "5", "--retries", "8", "--retry-base-ms", "50",
             "--out", lg_out],
            stdout=subprocess.DEVNULL,
        )
        # Leave the fault armed until loadgen's workers have demonstrably
        # taken degraded-mode sheds (the counter only moves for breaker-
        # open 503s), so the retry policy provably rides the window —
        # a fixed timer would race the subprocess interpreter startup.
        def sheds(base=base):
            _, m = get_json(base, "/metrics?format=json")
            return m["runtime"].get("resilience_degraded_sheds_total", 0)

        sheds0 = sheds()
        try:
            wait_until(lambda: sheds() >= sheds0 + 2, 8.0,
                       "loadgen rides the degraded window")
        except AssertionError:
            pass  # breaker-flap timing; the retry block just reads 0
        post_faults(base, {"disarm": "engine.compute"})
        assert lg.wait(timeout=60) == 0
        with open(lg_out) as f:
            lg_art = json.load(f)

        def recovered():
            k, _ = post_predict(base, patient, golden, out)
            return k == "ok"

        wait_until(recovered, 20.0, "breaker close (200 resumes)")
        code, health = get_json(base, "/healthz")
        assert health["status"] == "ok" and health["ready"] is True, health
        scenarios["compute_fault"] = {
            **out.as_dict(),
            "loadgen_retry": lg_art.get("retry"),
            "loadgen_ok": lg_art.get("n_ok"),
            "loadgen_shed_final": lg_art.get("n_shed"),
        }

        # --- scenario: wedged_compute -------------------------------------
        # Pinned to the device path: the watchdog under test lives in the
        # supervised engine (an unpinned single would route host, where
        # the 2 s stall is just a slow-but-bounded correct answer).
        out = Outcomes()
        post_faults(base, {"arm": "engine.compute:delay=2.0@n=1"})
        kind, info = post_predict(base, patient, golden, out, pin="device")
        # The wedge is detected at the 0.6 s flush deadline: the client
        # gets an explicit 504 (or a 503 if a concurrent probe opened the
        # breaker first) in bounded time — never the 2 s injected stall.
        assert kind in ("http_504", "http_503"), (kind, info)
        wait_until(recovered, 20.0, "recovery after wedge")
        scenarios["wedged_compute"] = out.as_dict()

        # --- scenario: flush_delay ----------------------------------------
        out = Outcomes()
        post_faults(base, {"arm": "batcher.flush:delay=0.8@n=1"})
        t0 = time.monotonic()
        kind, _ = post_predict(base, patient, golden, out, pin="device")
        dt = time.monotonic() - t0
        assert kind == "ok" and dt >= 0.8, (kind, dt)
        scenarios["flush_delay"] = {**out.as_dict(),
                                    "delayed_seconds": round(dt, 3)}

        # --- scenario: dual_path_routing ----------------------------------
        # Routing itself under chaos: both paths serve the golden bits,
        # and a one-shot host-path fault is absorbed by the transparent
        # device fallback (200, correct, client never sees it).
        out = Outcomes()
        for pin in ("host", "device", None):
            kind, info = post_predict(base, patient, golden, out, pin=pin)
            assert kind == "ok", (pin, kind, info)
        post_faults(base, {"arm": "engine.compute:raise@count=1"})
        kind, info = post_predict(base, patient, golden, out, pin="host")
        assert kind == "ok", (kind, info)  # fallback answered correctly
        _, m = get_json(base, "/metrics?format=json")
        paths = m["runtime"].get("serve_path_total", {})
        assert paths.get("path=host", 0) >= 1 and \
            paths.get("path=device", 0) >= 1, paths
        assert m["runtime"].get("serve_host_fallback_total", 0) >= 1, \
            m["runtime"].get("serve_host_fallback_total")
        scenarios["dual_path_routing"] = {**out.as_dict(), "paths": paths}

        # --- scenario: edge_faults ----------------------------------------
        out = Outcomes()
        post_faults(base, {"arm": "server.parse:raise@n=1"})
        kind, _ = post_predict(base, patient, golden, out)
        assert kind == "http_500", kind
        post_faults(base, {"arm": "server.respond:raise@n=1"})
        kind, _ = post_predict(base, patient, golden, out)
        assert kind == "conn_err", kind  # dropped, nothing written
        kind, _ = post_predict(base, patient, golden, out)
        assert kind == "ok", kind
        scenarios["edge_faults"] = out.as_dict()

        # Metrics evidence + strict exposition.
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=HARD_TIMEOUT_S) as resp:
            page = resp.read().decode()
        for family in ("fault_injected_total", "resilience_breaker_state",
                       "resilience_breaker_transitions_total",
                       "resilience_engine_restarts_total",
                       "resilience_degraded_sheds_total"):
            assert family in page, f"{family} missing from /metrics"
        from validate_metrics import validate  # noqa: E402 (tools/ sibling)

        errs = validate(page)
        assert not errs, f"/metrics failed strict validation: {errs[:5]}"
    finally:
        handle.shutdown()

    # -- offline checkpoint scenarios --------------------------------------
    ckpt_root = tempfile.mkdtemp(prefix="chaos_ckpt_")
    ckpt = os.path.join(ckpt_root, "model")
    import numpy as np

    from machine_learning_replications_tpu.data.examples import patient_row
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.resilience import faults

    params_v2 = make_sklearn_params(seed=11)
    p_v1 = float(np.asarray(
        stacking.predict_proba1(params, patient_row()))[0])
    p_v2 = float(np.asarray(
        stacking.predict_proba1(params_v2, patient_row()))[0])
    assert p_v1 != p_v2, "the two model versions must be distinguishable"

    # corrupt_restore: v1 then v2 (v1 retained as lastgood); corrupt v2 on
    # disk; the load must roll back to v1 and journal it.
    orbax_io.save_model(ckpt, params)
    orbax_io.save_model(ckpt, params_v2)
    assert os.path.isdir(lastgood.lastgood_path(ckpt))
    faults.arm("persist.restore:corrupt@once")
    rolled = orbax_io.load_model(ckpt)
    p_rolled = float(np.asarray(
        stacking.predict_proba1(rolled, patient_row()))[0])
    assert p_rolled == p_v1, (p_rolled, p_v1)
    scenarios["corrupt_restore"] = {
        "rolled_back_to_lastgood": True,
        "serves_previous_model": p_rolled == p_v1,
    }

    # save_interrupted: a save torn mid-publish must leave the previous
    # checkpoint fully intact (the corrupted primary was consumed above,
    # so rebuild a clean v2 state first).
    orbax_io.save_model(ckpt, params_v2)
    faults.arm("persist.save:raise@once")
    try:
        orbax_io.save_model(ckpt, params)
        raise AssertionError("interrupted save should have raised")
    except faults.InjectedFault:
        pass
    intact = orbax_io.load_model(ckpt)
    p_intact = float(np.asarray(
        stacking.predict_proba1(intact, patient_row()))[0])
    assert p_intact == p_v2, (p_intact, p_v2)
    scenarios["save_interrupted"] = {
        "previous_checkpoint_intact": p_intact == p_v2,
    }

    journal.set_journal(None)
    jrn.close()
    with open(journal_path) as f:
        events = [json.loads(line) for line in f]
    kinds = {e.get("kind") for e in events}
    for needed in ("fault_injected", "breaker_open", "engine_restart",
                   "breaker_close", "checkpoint_rollback"):
        assert needed in kinds, f"journal lacks {needed!r} ({sorted(kinds)})"
    restarts_ok = [
        e for e in events
        if e.get("kind") == "engine_restart" and e.get("ok")
    ]
    assert restarts_ok, "no successful supervised restart journaled"

    total = Outcomes()
    for s in scenarios.values():
        for k, v in s.get("outcomes", {}).items():
            total.counts[k] = total.counts.get(k, 0) + v
        total.wrong_answers += s.get("wrong_answers", 0)
        total.hangs += s.get("hangs", 0)
    artifact = {
        "kind": "chaos_drill",
        "manifest": journal.run_manifest(command="chaos_drill"),
        "invariant": {
            "statement": "every request: correct answer or explicit "
            "failure; zero wrong answers, zero hangs",
            "wrong_answers": total.wrong_answers,
            "hangs": total.hangs,
            "holds": total.wrong_answers == 0 and total.hangs == 0,
        },
        "outcomes_total": dict(sorted(total.counts.items())),
        "scenarios": scenarios,
        "journal_event_kinds": sorted(k for k in kinds if k),
        "successful_restarts": len(restarts_ok),
        "duration_s": round(time.monotonic() - t_start, 3),
    }
    line = json.dumps(artifact, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        print(f"artifact written to {args.out}", file=sys.stderr)
    assert artifact["invariant"]["holds"], "CHAOS INVARIANT VIOLATED"
    print("chaos invariant holds: zero wrong answers, zero hangs",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
