#!/usr/bin/env python
"""Chaos drill — drive the fault matrix against a live server and assert
the degradation contract; writes a CHAOS_*.json artifact.

The resilience layer's claim (docs/RESILIENCE.md) is a single invariant:

    Under every injected fault class, a client receives either a
    CORRECT answer or an EXPLICIT failure (503/504/500 or a closed
    connection) — never a wrong answer, never a hang.

This tool is the claim's executable form. It stands up a real serving
process (sklearn-imported ensemble, the same route the tests use), arms
each fault class through the guarded ``/debug/faults`` endpoint, drives
requests through the public HTTP surface, and classifies every outcome.
Any 200 whose probability differs from the pre-chaos golden reply is a
wrong answer; any request exceeding the hard client timeout is a hang;
either fails the drill (non-zero exit). The journal and ``/metrics`` are
then checked for the breaker/restart/rollback evidence, and the metrics
page must pass the strict Prometheus validator.

Scenarios:

  compute_fault     ``engine.compute:raise`` — failing device computes:
                    500s feed the breaker, it opens, requests shed 503 +
                    ``Retry-After``; a ``tools/loadgen.py --retries`` run
                    rides the degraded window; disarm -> supervised
                    restart -> 200s resume. Quantifies client impact via
                    the loadgen retry block.
  wedged_compute    ``engine.compute:delay`` past the flush deadline —
                    the watchdog abandons the compute (504 in bounded
                    time), the breaker opens, restart recovers.
  flush_delay       ``batcher.flush:delay`` — a slow flush answers late
                    but correctly (graceful latency fault, no breaker).
  edge_faults       ``server.parse:raise`` (explicit 500, body unread)
                    and ``server.respond:raise`` (connection dropped with
                    nothing written — never a partial 200).
  dual_path_routing the drill runs with the dual-path router enabled
                    (the ``cli serve`` default): both scoring paths
                    serve the golden bits, and a one-shot host-path
                    fault is absorbed by the transparent device
                    fallback — the client sees a correct 200, the
                    fallback counter moves.
  corrupt_restore   offline: a corrupted checkpoint rolls back to the
                    retained last-known-good (journaled), and the
                    rolled-back params serve the previous model's exact
                    predictions.
  save_interrupted  offline: ``persist.save:raise`` mid-publish leaves
                    the previous checkpoint fully intact and loadable.

``--fleet`` runs the FLEET drill instead (docs/FLEET.md): two real
``cli serve`` replica subprocesses self-registered behind an in-process
front-door router, continuous traffic flowing the whole time, and four
scenarios asserted under it —

  kill_replica      SIGKILL one replica mid-traffic: the router's
                    retry/breaker machinery absorbs it (zero client
                    errors, zero wrong answers, bounded latency), the
                    registry rotates it out, and a respawned replica
                    probes back into rotation.
  rolling_deploy    publish checkpoint v2 and drive ``/fleet/deploy``
                    under load: both replicas warm-swap one at a time,
                    zero failed requests, zero wrong answers (every 200
                    bit-for-bit equal to the CLI golden FOR ITS
                    VERSION), and the traffic log shows the v1→v2
                    crossover.
  corrupt_deploy    corrupt the next checkpoint on disk and deploy: the
                    replica's restore rolls back to last-known-good
                    (journaled ``checkpoint_rollback``), the rollout
                    stops as ``rolled_back``, and the fleet keeps
                    serving the old version — still zero wrong answers.
  aot_corrupt       cold-start a replica on a checkpoint whose AOT
                    executable bundle is corrupt (every blob torn, then
                    re-manifested — bad at publish): the replica
                    journals the fails-open fallback (``aot_fallback``),
                    traces instead, probes ready, and serves bit-correct
                    answers with zero client-visible failures
                    (docs/AOT.md).

``--surge`` runs the ELASTIC-FLEET drill (docs/FLEET.md "Elastic
fleet"): an in-process router + autoscaler daemon + lifecycle manager
over real ``cli serve`` replica subprocesses, driven end-to-end by ONE
``tools/loadgen.py --ramp`` client whose paced rate steps low → burst →
low. The asserted arc, all journaled:

  surge             the burst breaches the autoscaler's queue/latency
                    thresholds for the debounce window → a journaled
                    ``autoscale_decision`` scale-out → a new replica is
                    spawned, warms, and probes into rotation.
  kill mid-burst    one replica is SIGKILLed under load: the router's
                    retry/breaker machinery absorbs it client-side, the
                    manager detects the dead process, deregisters it,
                    and respawns it on the same id/port (journaled
                    ``lifecycle_crash`` → ``lifecycle_spawn``
                    respawn=true → ``lifecycle_ready``).
  quiet             the burst ends → a debounced, cooled-down scale-in
                    retires the surge replica DRAIN-FIRST: rotation
                    hold → queue settle → SIGTERM → clean exit, with no
                    SIGKILL in the arc.
  fail closed       an armed ``lifecycle.spawn:corrupt`` fault makes the
                    next spawn unready-forever: the ready deadline kills
                    it, journals ``lifecycle_spawn_failed``, and the
                    fleet is merely not grown — zero client impact.

The invariant: the loadgen artifact shows ZERO failed client requests
(n_err == 0, zero retry give-ups) across the whole surge → kill →
recover arc, and the router page (which carries the ``autoscale_*`` /
``lifecycle_*`` families — everything control-plane runs in one
process) passes the strict validator.

The router's ``/metrics`` page is strict-validated and written to
``--metrics-out`` for CI to re-validate as an artifact.

Run from the repo root (CPU is fine)::

    JAX_PLATFORMS=cpu python tools/chaos_drill.py --out CHAOS_r10_cpu.json
    JAX_PLATFORMS=cpu python tools/chaos_drill.py --fleet \\
        --out CHAOS_fleet.json --metrics-out fleet_metrics.txt
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
import warnings

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

HARD_TIMEOUT_S = 10.0  # any request slower than this counts as a HANG


class Outcomes:
    """Per-scenario outcome ledger; the invariant is computed over these."""

    def __init__(self) -> None:
        self.counts: dict[str, int] = {}
        self.wrong_answers = 0
        self.hangs = 0

    def add(self, kind: str) -> None:
        self.counts[kind] = self.counts.get(kind, 0) + 1

    def as_dict(self) -> dict:
        return {
            "outcomes": dict(sorted(self.counts.items())),
            "wrong_answers": self.wrong_answers,
            "hangs": self.hangs,
        }


def post_predict(base: str, patient: dict, golden: float | None,
                 out: Outcomes, pin: str | None = None) -> tuple[str, dict]:
    """One /predict request, classified. Returns (kind, info). ``pin``
    routes the request to a specific scoring path (X-Serve-Path) —
    scenarios asserting supervised-engine semantics (watchdog, flush
    faults) pin ``device`` so the probe exercises the batcher even when
    the dual-path router would answer it from the host."""
    body = json.dumps(patient).encode()
    headers = {"Content-Type": "application/json"}
    if pin:
        headers["X-Serve-Path"] = pin
    req = urllib.request.Request(
        base + "/predict", data=body, headers=headers,
    )
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=HARD_TIMEOUT_S) as resp:
            payload = json.loads(resp.read())
        prob = payload["probability"]
        if golden is not None and prob != golden:
            out.wrong_answers += 1
            out.add("wrong_200")
            return "wrong_200", {"probability": prob}
        out.add("ok")
        return "ok", {"probability": prob}
    except urllib.error.HTTPError as exc:
        exc.read()
        kind = f"http_{exc.code}"
        out.add(kind)
        return kind, {"retry_after": exc.headers.get("Retry-After")}
    except Exception as exc:
        if time.monotonic() - t0 >= HARD_TIMEOUT_S - 0.05:
            out.hangs += 1
            out.add("hang")
            return "hang", {"error": f"{type(exc).__name__}: {exc}"}
        out.add("conn_err")  # explicit transport failure — not a hang
        return "conn_err", {"error": f"{type(exc).__name__}: {exc}"}


def get_json(base: str, path: str):
    req = urllib.request.Request(base + path)
    try:
        with urllib.request.urlopen(req, timeout=HARD_TIMEOUT_S) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read() or b"{}")


def post_faults(base: str, op: dict):
    data = json.dumps(op).encode()
    req = urllib.request.Request(
        base + "/debug/faults", data=data,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=HARD_TIMEOUT_S) as resp:
        return json.loads(resp.read())


def wait_until(pred, timeout_s: float, what: str, poll_s: float = 0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll_s)
    raise AssertionError(f"timed out waiting for {what}")


def make_sklearn_params(seed: int):
    import numpy as np
    from sklearn.ensemble import (
        GradientBoostingClassifier, StackingClassifier,
    )
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler
    from sklearn.svm import SVC

    from machine_learning_replications_tpu.persist import import_stacking

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(160, 17))
    y = (X @ rng.normal(size=17) > 0).astype(float)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clf = StackingClassifier(
            estimators=[
                ("svc", make_pipeline(
                    StandardScaler(), SVC(probability=True, random_state=0))),
                ("gbc", GradientBoostingClassifier(
                    n_estimators=5, max_depth=1, random_state=0)),
                ("lg", LogisticRegression()),
            ],
            final_estimator=LogisticRegression(),
        ).fit(X, y)
    return import_stacking(clf)


def _free_port() -> int:
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Traffic:
    """Continuous /predict traffic through the router, every reply
    classified against the per-version golden probabilities. One record
    per logical request: (t_mono, status, version, latency_ms) — the
    scenario assertions slice this log by time."""

    def __init__(self, base: str, patient: dict, goldens: dict) -> None:
        self.base = base
        self.body = json.dumps(patient).encode()
        self.goldens = goldens  # {version int: probability float}
        self.log: list[tuple[float, str, int | None, float]] = []
        # version -> distinct served probabilities: the bit-for-bit
        # evidence — one version must serve exactly one bit pattern,
        # across replicas, kills, and the deploy crossover.
        self.served_bits: dict[int, set] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    def _one(self) -> None:
        req = urllib.request.Request(
            self.base + "/predict", data=self.body,
            headers={"Content-Type": "application/json"},
        )
        t0 = time.monotonic()
        version = None
        try:
            with urllib.request.urlopen(req, timeout=HARD_TIMEOUT_S) as r:
                payload = json.loads(r.read())
                raw_v = r.headers.get("X-Model-Version")
                version = int(raw_v) if raw_v else None
            golden = self.goldens.get(version)
            prob = payload["probability"]
            with self._lock:
                if version is not None:
                    self.served_bits.setdefault(version, set()).add(prob)
            # Correct = the eager golden for the reply's version within
            # the engine parity tolerance (jit vs eager fusion noise:
            # ~1e-7 relative in float32 mode); the versions differ at
            # 1e-1, so a wrong-version or corrupt-weights answer cannot
            # pass. Bit consistency per version is asserted over
            # served_bits.
            status = (
                "ok" if golden is not None
                and abs(prob - golden) <= 1e-6 else "wrong"
            )
        except urllib.error.HTTPError as exc:
            exc.read()
            status = f"http_{exc.code}"
        except Exception:
            status = (
                "hang"
                if time.monotonic() - t0 >= HARD_TIMEOUT_S - 0.05
                else "conn_err"
            )
        with self._lock:
            self.log.append((
                t0, status, version,
                (time.monotonic() - t0) * 1000.0,
            ))

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._one()
            time.sleep(0.02)

    def start(self) -> "_Traffic":
        self._thread = threading.Thread(
            target=self._loop, name="fleet-traffic", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=HARD_TIMEOUT_S + 5)

    def window(self, t_from: float, t_to: float | None = None) -> dict:
        """Outcome counts, version split, and latency p99 over requests
        STARTED in [t_from, t_to)."""
        with self._lock:
            rows = [
                r for r in self.log
                if r[0] >= t_from and (t_to is None or r[0] < t_to)
            ]
        counts: dict[str, int] = {}
        versions: dict[str, int] = {}
        lats = []
        for _, status, version, ms in rows:
            counts[status] = counts.get(status, 0) + 1
            if status == "ok" and version is not None:
                versions[str(version)] = versions.get(str(version), 0) + 1
            lats.append(ms)
        lats.sort()
        p99 = (
            lats[min(len(lats) - 1, round(0.99 * (len(lats) - 1)))]
            if lats else None
        )
        return {
            "requests": len(rows),
            "outcomes": dict(sorted(counts.items())),
            "versions": versions,
            "p99_ms": round(p99, 1) if p99 is not None else None,
        }


def _spawn_replica(rid: str, port: int, ckpt: str, register_url: str,
                   journal_path: str):
    """One real ``cli serve`` replica subprocess: admin endpoint on (the
    rollout target), self-registering with the router."""
    return subprocess.Popen(
        [sys.executable, "-m", "machine_learning_replications_tpu",
         "serve", "--model", ckpt, "--port", str(port),
         "--buckets", "1,8", "--max-wait-ms", "2",
         "--replica-id", rid, "--register", register_url,
         "--admin-endpoint", "--journal", journal_path],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _corrupt_largest_payload(ckpt: str) -> None:
    best, size = None, -1
    for root, _dirs, names in os.walk(ckpt):
        for name in names:
            fp = os.path.join(root, name)
            if name != "integrity.json" and os.path.getsize(fp) > size:
                best, size = fp, os.path.getsize(fp)
    with open(best, "r+b") as f:
        first = f.read(1)
        f.seek(0)
        f.write(bytes([first[0] ^ 0xFF]) if first else b"\x00")


def run_fleet_drill(args) -> int:
    """The fleet drill (see module docstring): two replica subprocesses
    behind an in-process router, traffic flowing throughout."""
    import signal

    import numpy as np

    t_start = time.monotonic()
    from machine_learning_replications_tpu.data.examples import (
        EXAMPLE_PATIENT, patient_row,
    )
    from machine_learning_replications_tpu.fleet import make_router
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.obs import alerts as obs_alerts
    from machine_learning_replications_tpu.obs import journal
    from machine_learning_replications_tpu.persist import orbax_io

    workdir = tempfile.mkdtemp(prefix="chaos_fleet_")
    journal_path = args.journal or os.path.join(workdir, "router.jsonl")
    jrn = journal.RunJournal(journal_path, command="chaos_drill --fleet")
    journal.set_journal(jrn)

    # Alerting arc (docs/OBSERVABILITY.md "Alerting & incidents"): the
    # drill's rules come from a FILE — the operator wire-through path —
    # and are chosen so the healthy baseline is silent and the
    # kill-replica fault deterministically fires. fleet_replicas{state=
    # "out"} is 0 at startup (new replicas are probing, never out), so
    # cold warmup cannot false-positive; the default stale-replica rule
    # is deliberately absent (a killed replica's stale window depends on
    # respawn warmup time — nondeterministic in a drill).
    rules_path = os.path.join(workdir, "alert_rules.json")
    with open(rules_path, "w") as f:
        json.dump([
            {
                "type": "threshold", "name": "fleet_capacity_degraded",
                "severity": "page", "family": "fleet_replicas",
                "labels": {"state": "out"}, "op": ">=", "threshold": 1.0,
                "for_s": 0.75, "resolve_for_s": 0.75,
            },
            {
                "type": "burn_rate", "name": "fleet_error_budget_burn",
                "severity": "page", "family": "fleet_slo_burn_rate",
                "for_s": 1.0, "resolve_for_s": 2.0,
            },
        ], f, indent=1)
    incident_dir = os.path.join(workdir, "incidents")

    ckpt = os.path.join(workdir, "model")
    p_v1, p_v2 = make_sklearn_params(seed=7), make_sklearn_params(seed=11)
    goldens = {
        1: float(np.asarray(stacking.predict_proba1(p_v1, patient_row()))[0]),
        2: float(np.asarray(stacking.predict_proba1(p_v2, patient_row()))[0]),
    }
    assert goldens[1] != goldens[2], "versions must be distinguishable"
    orbax_io.save_model(ckpt, p_v1)  # publishes as version 1

    router = make_router(
        port=0, probe_interval_s=0.2, request_timeout_s=8.0,
        hedge_ms=300.0, max_attempts=3,
        history_interval_s=0.25,
        alert_rules=obs_alerts.load_rules(rules_path),
        incident_dir=incident_dir,
        incident_min_interval_s=0.0,
    ).start_background()
    base = f"http://{router.address[0]}:{router.address[1]}"
    ports = {"r1": _free_port(), "r2": _free_port()}
    replica_journals = {
        rid: os.path.join(workdir, f"replica_{rid}.jsonl") for rid in ports
    }
    procs = {
        rid: _spawn_replica(
            rid, port, ckpt, base, replica_journals[rid]
        )
        for rid, port in ports.items()
    }
    scenarios: dict[str, dict] = {}
    traffic = None
    try:
        wait_until(
            lambda: router.registry.ready_count() == 2, 240.0,
            "both replicas registered, warm, and in rotation",
            poll_s=0.5,
        )
        traffic = _Traffic(base, dict(EXAMPLE_PATIENT), goldens).start()
        time.sleep(2.0)  # a baseline window of healthy two-replica traffic

        # Healthy-baseline alert silence: warmup + the first traffic
        # window must produce zero firing rules and zero journaled
        # transitions — an alerting plane that cries during a normal
        # cold start would be ignored by the third incident.
        with urllib.request.urlopen(
            base + "/fleet/alerts", timeout=HARD_TIMEOUT_S
        ) as resp:
            baseline_alerts = json.loads(resp.read())
        assert baseline_alerts["enabled"], baseline_alerts
        assert not baseline_alerts["active"], (
            "alerts fired during the healthy baseline",
            baseline_alerts["active"],
        )
        if args.metrics_early_out:
            with urllib.request.urlopen(
                base + "/metrics", timeout=HARD_TIMEOUT_S
            ) as resp:
                with open(args.metrics_early_out, "w") as f:
                    f.write(resp.read().decode())
            print(
                f"baseline metrics written to {args.metrics_early_out}",
                file=sys.stderr,
            )

        # Cross-process joined timeline, captured while both replicas
        # are healthy (the kill/deploy scenarios below legitimately
        # leave unreachable-replica samples in the router's ring): the
        # router fetches each tail-sampled request's replica-side trace
        # by id and offset-corrects it into the upstream span.
        with urllib.request.urlopen(
            base + "/fleet/trace?n=256", timeout=HARD_TIMEOUT_S
        ) as resp:
            fleet_trace = json.loads(resp.read())
        trace_other = fleet_trace["otherData"]
        assert trace_other["joined"] >= 1, (
            "no cross-hop joined trace in the healthy window",
            trace_other["results"],
        )
        assert trace_other["containment"]["contained"] >= 1, (
            "no joined trace showed replica-inside-upstream containment",
            trace_other["containment"],
        )
        if args.fleet_trace_out:
            with open(args.fleet_trace_out, "w") as f:
                json.dump(fleet_trace, f)
            print(f"fleet trace written to {args.fleet_trace_out}",
                  file=sys.stderr)

        # --- scenario: kill_replica ---------------------------------------
        t0 = time.monotonic()
        procs["r1"].send_signal(signal.SIGKILL)
        procs["r1"].wait()
        wait_until(
            lambda: not (router.registry.get("r1") or {}).get(
                "in_rotation", True
            ),
            30.0, "killed replica rotated out", poll_s=0.2,
        )
        time.sleep(2.0)  # single-replica traffic window
        win = traffic.window(t0)
        scenarios["kill_replica"] = win
        assert win["requests"] > 0, win
        assert set(win["outcomes"]) <= {"ok"}, (
            "kill-replica window saw client-visible failures", win,
        )

        # The fault must FIRE the capacity rule (rotation-out drives
        # fleet_replicas{state="out"} to 1, the engine holds it for_s,
        # then journals alert_fired) …
        wait_until(
            lambda: any(
                a["rule"] == "fleet_capacity_degraded"
                and a["state"] == "firing"
                for a in router.alerts.active()
            ),
            30.0, "capacity alert fired after the replica kill",
            poll_s=0.2,
        )
        # … and the firing must CAPTURE a complete incident bundle
        # (bundles() lists manifest-complete dirs only — the manifest is
        # written last, so its presence IS the completeness marker).
        wait_until(
            lambda: router.incidents.bundles(), 30.0,
            "incident bundle captured on firing", poll_s=0.2,
        )
        bundle_dir = router.incidents.bundles()[0]
        with open(os.path.join(bundle_dir, "manifest.json")) as f:
            manifest = json.loads(f.read())
        assert manifest["rule"] == "fleet_capacity_degraded", manifest
        for needed in ("alert.json", "history.json", "requests.json",
                       "replicas.json", "journal_tail.jsonl"):
            assert needed in manifest["files"], (needed, manifest)
            assert os.path.exists(os.path.join(bundle_dir, needed)), needed
        assert not manifest["errors"], manifest

        # Respawn: same id + port re-registers idempotently and probes
        # back into rotation.
        procs["r1"] = _spawn_replica(
            "r1", ports["r1"], ckpt, base, replica_journals["r1"] + ".2"
        )
        wait_until(
            lambda: router.registry.ready_count() == 2, 240.0,
            "respawned replica back in rotation", poll_s=0.5,
        )
        # Recovery must RESOLVE the alert (out-count back to 0, held
        # for resolve_for_s, journaled alert_resolved) — the full
        # fault → fire → capture → recover → resolve arc.
        wait_until(
            lambda: not router.alerts.active(), 60.0,
            "capacity alert resolved after the respawn", poll_s=0.2,
        )
        alerting = {
            "baseline_active": 0,
            "fired_rule": "fleet_capacity_degraded",
            "bundle": {
                "dir": os.path.basename(bundle_dir),
                "files": manifest["files"],
                "schema": manifest["schema"],
            },
            "resolved_after_respawn": True,
        }

        # --- scenario: rolling_deploy -------------------------------------
        orbax_io.save_model(ckpt, p_v2)  # publishes as version 2
        t0 = time.monotonic()
        req = urllib.request.Request(
            base + "/fleet/deploy",
            data=json.dumps({"model": ckpt}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=600) as resp:
            report = json.loads(resp.read())["deploy"]
        assert report["result"] == "ok" and \
            report["target_version"] == 2, report
        time.sleep(2.0)  # post-deploy window at v2
        win = traffic.window(t0)
        scenarios["rolling_deploy"] = {**win, "report": report}
        assert set(win["outcomes"]) <= {"ok"}, (
            "rolling deploy dropped or corrupted requests", win,
        )
        assert set(win["versions"]) == {"1", "2"}, (
            "no version crossover observed", win,
        )
        snap = router.registry.snapshot()
        assert all(
            r["version"] == 2 and r["in_rotation"] for r in snap
        ), snap

        # --- scenario: corrupt_deploy -------------------------------------
        orbax_io.save_model(ckpt, p_v1)  # version 3 content…
        _corrupt_largest_payload(ckpt)   # …torn on disk
        t0 = time.monotonic()
        req = urllib.request.Request(
            base + "/fleet/deploy",
            data=json.dumps({"model": ckpt}).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=600) as resp:
                report = json.loads(resp.read())["deploy"]
        except urllib.error.HTTPError as exc:
            report = json.loads(exc.read())["deploy"]
        assert report["result"] == "rolled_back", report
        time.sleep(2.0)
        win = traffic.window(t0)
        scenarios["corrupt_deploy"] = {**win, "report": report}
        assert set(win["outcomes"]) <= {"ok"}, (
            "corrupt-deploy rollback leaked failures to clients", win,
        )
        assert set(win["versions"]) == {"2"}, (
            "fleet left the known-good version during a rolled-back "
            "deploy", win,
        )
        snap = router.registry.snapshot()
        assert all(r["in_rotation"] for r in snap), snap

        # --- scenario: aot_corrupt ----------------------------------------
        # A checkpoint whose AOT executable bundle is bad AT PUBLISH
        # (every blob's bytes torn, then re-manifested — the checkpoint
        # itself stays integrity-clean; the failure is in the serialized
        # executables, not the model). A replica cold-started on it must
        # journal the fails-open fallback, trace instead, become ready,
        # and serve bit-correct answers — zero client-visible failures
        # (docs/AOT.md "Fallback semantics").
        aot_ckpt = os.path.join(workdir, "model_aot")
        orbax_io.save_model(aot_ckpt, p_v1, aot=True)  # its lineage: v1
        aot_dir = os.path.join(aot_ckpt, "aot")
        for name in os.listdir(aot_dir):
            if name.endswith(".bin"):
                with open(os.path.join(aot_dir, name), "r+b") as f:
                    first = f.read(1)
                    f.seek(0)
                    f.write(bytes([first[0] ^ 0xFF]) if first else b"\x00")
        # Re-manifest so integrity verification passes: this simulates a
        # publish that PRODUCED bad blobs, the case the engine-level
        # fallback exists for (bad-on-disk-after-publish is caught
        # earlier, by integrity verification → checkpoint rollback —
        # the corrupt_deploy scenario above).
        orbax_io._write_integrity(
            aot_ckpt, version=orbax_io.checkpoint_version(aot_ckpt)
        )
        ports["r3"] = _free_port()
        replica_journals["r3"] = os.path.join(workdir, "replica_r3.jsonl")
        t0 = time.monotonic()
        procs["r3"] = _spawn_replica(
            "r3", ports["r3"], aot_ckpt, base, replica_journals["r3"]
        )
        wait_until(
            lambda: router.registry.ready_count() == 3, 240.0,
            "AOT-corrupt replica ready via the tracing fallback",
            poll_s=0.5,
        )
        time.sleep(2.0)  # three-replica window including r3's v1 bits
        win = traffic.window(t0)
        scenarios["aot_corrupt"] = win
        assert set(win["outcomes"]) <= {"ok"}, (
            "AOT-fallback replica leaked failures to clients", win,
        )
        with open(replica_journals["r3"]) as f:
            r3_kinds = {json.loads(line).get("kind") for line in f}
        assert "aot_fallback" in r3_kinds, (
            "replica on a corrupted AOT bundle never journaled the "
            f"fallback ({sorted(k for k in r3_kinds if k)})"
        )
        assert "aot_restore" not in r3_kinds, (
            "a corrupted AOT blob must not restore", sorted(r3_kinds),
        )

        traffic.stop()
        overall = traffic.window(0.0)
        # Bit-for-bit per version: every 200 of one version carried the
        # same bits, across replicas, the kill, and both deploys.
        for version, bits in traffic.served_bits.items():
            assert len(bits) == 1, (
                f"version {version} served {len(bits)} distinct bit "
                f"patterns: {sorted(bits)}"
            )

        # Router metrics: evidence + strict exposition.
        with urllib.request.urlopen(
            base + "/metrics", timeout=HARD_TIMEOUT_S
        ) as resp:
            page = resp.read().decode()
        for family in ("fleet_requests_total", "fleet_replicas",
                       "fleet_rotations_total", "fleet_probe_total",
                       "fleet_deploys_total",
                       "fleet_request_latency_seconds"):
            assert family in page, f"{family} missing from router /metrics"
        from validate_metrics import validate  # noqa: E402

        errs = validate(page)
        assert not errs, f"router /metrics failed validation: {errs[:5]}"
        if args.metrics_out:
            with open(args.metrics_out, "w") as f:
                f.write(page)
            print(f"router metrics written to {args.metrics_out}",
                  file=sys.stderr)

        # Aggregated fleet exposition: in-rotation replicas scraped and
        # merged (counters summed, gauges replica-labeled, histograms
        # bucket-merged), router-owned families appended — one page,
        # strict-validator clean, with every replica either merged or
        # marked stale on the page itself.
        with urllib.request.urlopen(
            base + "/fleet/metrics", timeout=HARD_TIMEOUT_S
        ) as resp:
            fleet_page = resp.read().decode()
        errs = validate(fleet_page)
        assert not errs, (
            f"/fleet/metrics failed strict validation: {errs[:5]}"
        )
        for family in ("serve_requests_total", "fleet_scrape_stale",
                       "fleet_slo_requests_total",
                       "fleet_clock_offset_ms"):
            assert family in fleet_page, (
                f"{family} missing from /fleet/metrics"
            )
        if args.fleet_metrics_out:
            with open(args.fleet_metrics_out, "w") as f:
                f.write(fleet_page)
            print(
                f"fleet metrics written to {args.fleet_metrics_out}",
                file=sys.stderr,
            )
        for family in ("alerts_active", "alerts_transitions_total",
                       "incident_captures_total", "history_samples_total"):
            assert family in page, f"{family} missing from router /metrics"

        # The history plane itself, over the live HTTP surface: the
        # drill's whole timeline should be sitting in the ring.
        with urllib.request.urlopen(
            base + "/debug/history?family=fleet_replicas&window=600",
            timeout=HARD_TIMEOUT_S,
        ) as resp:
            history = json.loads(resp.read())
        assert history["series"] and all(
            s["points"] for s in history["series"]
        ), "no fleet_replicas history despite a running sampler"

        fleet_telemetry = {
            "trace": {
                "requests": trace_other["requests"],
                "joined": trace_other["joined"],
                "results": trace_other["results"],
                "containment": trace_other["containment"],
                "clock_offsets": trace_other["clock_offsets"],
            },
            "fleet_metrics_validated": True,
        }
        alerting["final"] = router.alerts.summary()
        alerting["history_series"] = len(history["series"])
        if args.incident_out:
            import shutil

            dst = os.path.join(
                args.incident_out, os.path.basename(bundle_dir)
            )
            if os.path.isdir(dst):
                shutil.rmtree(dst)
            shutil.copytree(bundle_dir, dst)
            print(f"incident bundle copied to {dst}", file=sys.stderr)
    finally:
        if traffic is not None:
            traffic.stop()
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs.values():
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
        router.shutdown()
        journal.set_journal(None)
        jrn.close()

    # Journal evidence: the registration → rotation → deploy arc on the
    # router side, the rollback on the replica side.
    with open(journal_path) as f:
        events = [json.loads(line) for line in f]
    kinds = {e.get("kind") for e in events}
    for needed in ("fleet_router_started", "fleet_replica_registered",
                   "fleet_rotation", "fleet_deploy_start",
                   "fleet_deploy_replica", "fleet_deploy_done",
                   "alert_fired", "alert_resolved", "incident_captured"):
        assert needed in kinds, f"router journal lacks {needed!r}"
    replica_kinds = set()
    for path in list(replica_journals.values()) + [
        replica_journals["r1"] + ".2"
    ]:
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    replica_kinds.add(json.loads(line).get("kind"))
    for needed in ("deploy_start", "deploy_applied", "checkpoint_rollback"):
        assert needed in replica_kinds, (
            f"replica journals lack {needed!r} ({sorted(replica_kinds)})"
        )

    wrong = sum(
        s["outcomes"].get("wrong", 0) for s in scenarios.values()
    )
    hangs = sum(s["outcomes"].get("hang", 0) for s in scenarios.values())
    artifact = {
        "kind": "chaos_drill_fleet",
        "manifest": journal.run_manifest(command="chaos_drill --fleet"),
        "invariant": {
            "statement": "through the router, under replica kill and "
            "good/bad rolling deploys: every request a correct answer "
            "for its version (one bit pattern per version, equal to "
            "the eager CLI golden at the engine parity tolerance) or "
            "an explicit failure; zero wrong answers, zero hangs, "
            "bounded p99",
            "wrong_answers": wrong,
            "hangs": hangs,
            "holds": wrong == 0 and hangs == 0,
        },
        "traffic_total": overall,
        "scenarios": scenarios,
        "fleet_telemetry": fleet_telemetry,
        "alerting": alerting,
        "router_journal_kinds": sorted(k for k in kinds if k),
        "replica_journal_kinds": sorted(
            k for k in replica_kinds if k
        ),
        "duration_s": round(time.monotonic() - t_start, 3),
    }
    line = json.dumps(artifact, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        print(f"artifact written to {args.out}", file=sys.stderr)
    assert artifact["invariant"]["holds"], "FLEET CHAOS INVARIANT VIOLATED"
    print(
        "fleet chaos invariant holds: zero wrong answers, zero hangs, "
        f"p99 {overall['p99_ms']} ms over {overall['requests']} requests",
        file=sys.stderr,
    )
    return 0


def run_surge_drill(args) -> int:
    """The elastic-fleet drill (see module docstring): autoscaler +
    lifecycle manager over real replica subprocesses, one ramped loadgen
    client, surge → scale-out → SIGKILL → replacement → scale-in."""
    import threading

    t_start = time.monotonic()
    from machine_learning_replications_tpu.fleet import (
        AutoscaleDaemon,
        AutoscalePolicy,
        AutoscaleThresholds,
        LifecycleManager,
        ReplicaSpec,
        RouterClient,
        make_router,
    )
    from machine_learning_replications_tpu.fleet.lifecycle import (
        LIFECYCLE_TRANSITIONS,
        kill_replica,
    )
    from machine_learning_replications_tpu.obs import journal
    from machine_learning_replications_tpu.persist import orbax_io
    from machine_learning_replications_tpu.resilience import faults

    workdir = tempfile.mkdtemp(prefix="chaos_surge_")
    journal_path = args.journal or os.path.join(workdir, "surge.jsonl")
    jrn = journal.RunJournal(journal_path, command="chaos_drill --surge")
    journal.set_journal(jrn)
    say = lambda m: print(f"surge: {m}", file=sys.stderr)  # noqa: E731

    ckpt = os.path.join(workdir, "model")
    orbax_io.save_model(ckpt, make_sklearn_params(seed=7))

    # hedge_ms sits well above the burst's saturation-plateau latency:
    # a hedge that fires on EVERY request at the plateau would double
    # the offered load on an already saturated fleet (positive
    # feedback) — hedging is for stragglers; the retry path (not
    # hedging) absorbs the SIGKILL.
    router = make_router(
        port=0, probe_interval_s=0.2, request_timeout_s=10.0,
        hedge_ms=2000.0, max_attempts=4,
    ).start_background()
    base = f"http://{router.address[0]}:{router.address[1]}"
    # The deliberately EXPENSIVE replica configuration. The sandbox
    # model is too cheap to surge: single-row traffic rides the host
    # fast path and batch amortization lets one replica absorb ~1000
    # qps — more than a 2-core box's client can offer, so no reachable
    # burst ever breaches a threshold. Replicas therefore run device-
    # path-only, unbatched, with an armed ``engine.compute:delay``
    # emulating a production-cost model (~10 ms/row → ~95 qps/replica,
    # sleep not CPU, so the client stays honest). The paced closed loop
    # then saturates the fleet for real — in-flight bounded by
    # --connections, so burst latency plateaus at connections/capacity
    # (Little's law) instead of running away into client timeouts.
    spec = ReplicaSpec(
        model=ckpt, register_url=base,
        serve_args=("--buckets", "1", "--max-wait-ms", "0",
                    "--no-host-path", "--xla-intra-op-threads", "1",
                    "--inject",
                    f"engine.compute:delay={args.compute_delay_ms / 1e3:g}"),
        journal_dir=workdir,
    )
    manager = LifecycleManager(
        spec, RouterClient(base),
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        ready_deadline_s=args.ready_deadline, drain_settle_s=8.0,
        term_deadline_s=30.0, respawn_backoff_s=0.5, say=say,
    )
    policy = AutoscalePolicy(
        thresholds=AutoscaleThresholds(
            out_queue_depth=args.out_queue_depth,
            out_latency_ms=args.out_latency_ms,
            out_shed_rate=0.02, out_burn_rate=None,
            in_queue_depth=1.0, in_latency_ms=args.in_latency_ms,
            in_shed_rate=0.0, in_burn_rate=None,
        ),
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        breach_polls=args.breach_polls, idle_polls=args.idle_polls,
        cooldown_s=args.cooldown,
    )
    daemon = AutoscaleDaemon(base, manager, policy, poll_interval_s=1.0,
                             say=say)
    manager.scale_to(args.min_replicas)
    stop = threading.Event()
    daemon_thread = threading.Thread(
        target=lambda: daemon.run(stop_check=stop.is_set),
        name="surge-autoscaler", daemon=True,
    )
    daemon_thread.start()

    lg = None
    lg_path = os.path.join(workdir, "loadgen.json")
    timeline: dict = {}
    try:
        wait_until(
            lambda: router.registry.ready_count() >= args.min_replicas,
            600.0, f"{args.min_replicas} replicas warm and in rotation",
            poll_s=0.5,
        )
        say(f"baseline fleet of {args.min_replicas} ready in "
            f"{time.monotonic() - t_start:.0f}s")
        ramp = (
            f"0:{args.ramp_low:g},{args.burst_start:g}:{args.ramp_high:g},"
            f"{args.burst_end:g}:{args.ramp_low:g}"
        )
        lg = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "loadgen.py"),
             "--url", base, "--connections", str(args.connections),
             "--ramp", ramp, "--duration", str(args.client_duration),
             "--retries", "8", "--retry-base-ms", "50",
             "--timeout", "20", "--out", lg_path],
            stdout=subprocess.DEVNULL,
        )
        t_client0 = time.monotonic()

        # --- surge → journaled scale-out ----------------------------------
        wait_until(
            lambda: router.registry.ready_count() > args.min_replicas,
            args.burst_end + args.ready_deadline,
            "burst-driven scale-out (new replica warm and in rotation)",
            poll_s=0.5,
        )
        timeline["scale_out_ready_s"] = round(
            time.monotonic() - t_client0, 1
        )
        say(f"scale-out landed at {timeline['scale_out_ready_s']}s "
            "into the client run")

        # --- SIGKILL one replica mid-burst → journaled replacement --------
        victim = manager.get("as-1")
        assert victim is not None and victim.proc is not None
        old_pid = victim.proc.pid
        kill_replica(victim)
        timeline["kill_s"] = round(time.monotonic() - t_client0, 1)
        say(f"SIGKILLed replica as-1 (pid {old_pid})")
        wait_until(
            lambda: (
                (rep := manager.get("as-1")) is not None
                and rep.proc is not None and rep.proc.pid != old_pid
                and rep.state == "ready"
            ),
            args.ready_deadline + 60.0,
            "killed replica respawned and ready again", poll_s=0.5,
        )
        timeline["replaced_ready_s"] = round(
            time.monotonic() - t_client0, 1
        )
        say(f"replacement ready at {timeline['replaced_ready_s']}s")
        wait_until(
            lambda: router.registry.ready_count() > args.min_replicas,
            120.0, "replacement back in rotation", poll_s=0.5,
        )

        # --- burst ends → drain-first scale-in ----------------------------
        wait_until(
            lambda: router.registry.ready_count() == args.min_replicas
            and manager.counts()["active"] == args.min_replicas
            and manager.counts()["draining"] == 0
            and manager.counts()["terminating"] == 0,
            args.burst_end + args.client_duration,
            "drain-first scale-in back to the baseline fleet",
            poll_s=0.5,
        )
        timeline["scale_in_done_s"] = round(
            time.monotonic() - t_client0, 1
        )
        say(f"scale-in done at {timeline['scale_in_done_s']}s")

        assert lg.wait(timeout=args.client_duration + 120) == 0, \
            "loadgen client failed"
        with open(lg_path) as f:
            lg_art = json.load(f)

        # --- fault branch: unready spawn fails closed ---------------------
        # The daemon is stopped first so a racing scale-in decision
        # cannot retire the deliberately-corrupt slot before it spawns.
        stop.set()
        daemon_thread.join(timeout=30)
        failed0 = LIFECYCLE_TRANSITIONS.labels(event="spawn_failed").value
        ready_before = router.registry.ready_count()
        faults.arm("lifecycle.spawn:corrupt@once")
        manager.scale_to(args.min_replicas + 1)
        manager.ready_deadline_s = args.spawn_fault_deadline
        deadline = time.monotonic() + args.spawn_fault_deadline + 120
        while LIFECYCLE_TRANSITIONS.labels(
            event="spawn_failed"
        ).value == failed0:
            assert time.monotonic() < deadline, \
                "corrupt spawn never failed closed"
            manager.tick()
            time.sleep(0.5)
        assert router.registry.ready_count() == ready_before, (
            "an unready spawn changed rotation capacity",
            router.registry.snapshot(),
        )
        say("corrupt spawn failed closed (journaled, fleet unchanged)")
        faults.reset()
        manager.scale_to(args.min_replicas)
        manager.tick()  # drops the pending retry slot
        timeline["spawn_fault_s"] = round(
            time.monotonic() - t_client0, 1
        )

        # --- evidence ------------------------------------------------------
        with urllib.request.urlopen(
            base + "/metrics", timeout=HARD_TIMEOUT_S
        ) as resp:
            page = resp.read().decode()
        for family in ("autoscale_decisions_total", "autoscale_signal",
                       "autoscale_streak", "autoscale_desired_replicas",
                       "lifecycle_transitions_total", "lifecycle_replicas",
                       "fleet_requests_total", "fleet_rotations_total"):
            assert family in page, f"{family} missing from /metrics"
        from validate_metrics import validate  # noqa: E402

        errs = validate(page)
        assert not errs, f"/metrics failed validation: {errs[:5]}"
        if args.metrics_out:
            with open(args.metrics_out, "w") as f:
                f.write(page)
            print(f"metrics written to {args.metrics_out}",
                  file=sys.stderr)
    finally:
        stop.set()
        if lg is not None and lg.poll() is None:
            lg.kill()
        daemon_thread.join(timeout=10)
        manager.close()
        router.shutdown()
        journal.set_journal(None)
        jrn.close()

    # -- journal assertions: the whole arc, in order ------------------------
    with open(journal_path) as f:
        events = [json.loads(line) for line in f if line.strip()]
    kinds = {e.get("kind") for e in events}
    for needed in ("autoscale_decision", "lifecycle_spawn",
                   "lifecycle_ready", "lifecycle_crash",
                   "lifecycle_drain", "lifecycle_term", "lifecycle_exit",
                   "lifecycle_spawn_failed", "fault_injected",
                   "fleet_rotation"):
        assert needed in kinds, f"journal lacks {needed!r}"
    fired = [
        e for e in events
        if e.get("kind") == "autoscale_decision" and e.get("decision")
    ]
    assert any(e["decision"] == "scale_out" for e in fired), fired
    assert any(e["decision"] == "scale_in" for e in fired), fired
    respawns = [
        e for e in events
        if e.get("kind") == "lifecycle_spawn" and e.get("respawn")
    ]
    assert respawns, "no journaled crash respawn"
    # Drain-first: the scale-in retirement's drain precedes its term
    # precedes its exit, and that replica was never SIGKILLed.
    drains = [
        e for e in events
        if e.get("kind") == "lifecycle_drain"
        and e.get("reason") == "scale_in"
    ]
    assert drains, "no journaled drain-first scale-in"
    retired = drains[-1]["replica"]
    arc = [
        e["kind"] for e in events
        if e.get("replica") == retired
        and e.get("kind") in ("lifecycle_drain", "lifecycle_term",
                              "lifecycle_kill", "lifecycle_exit")
    ]
    tail = arc[arc.index("lifecycle_drain"):]
    assert tail == ["lifecycle_drain", "lifecycle_term",
                    "lifecycle_exit"], (retired, arc)

    zero_failures = (
        lg_art["n_err"] == 0
        and (lg_art.get("retry") or {}).get("give_ups", 0) == 0
    )
    artifact = {
        "kind": "fleet_scale_drill",
        "manifest": journal.run_manifest(command="chaos_drill --surge"),
        "invariant": {
            "statement": "under a surge → SIGKILL → recover arc driven "
            "by one ramped client: journaled scale-out, automatic "
            "crash replacement, drain-first scale-in (no SIGKILL in "
            "the retirement arc), an injected unready spawn failing "
            "closed — and zero failed client requests end to end",
            "client_errors": lg_art["n_err"],
            "retry_give_ups": (lg_art.get("retry") or {}).get("give_ups"),
            "holds": zero_failures,
        },
        "fleet": {
            "min_replicas": args.min_replicas,
            "max_replicas": args.max_replicas,
            "retired_drain_first": retired,
            "respawned": sorted({e["replica"] for e in respawns}),
        },
        "timeline_s": timeline,
        "client": {
            "ramp": lg_art.get("ramp"),
            "n_ok": lg_art["n_ok"],
            "n_shed": lg_art["n_shed"],
            "n_err": lg_art["n_err"],
            "achieved_qps": lg_art["achieved_qps"],
            "latency_ms": lg_art["latency_ms"],
            "retry": lg_art.get("retry"),
        },
        "autoscale_decisions": [
            {
                "ts": e.get("ts"), "decision": e.get("decision"),
                "target": e.get("target"), "reason": e.get("reason"),
                "signals": e.get("signals"),
            }
            for e in fired
        ],
        "journal_event_kinds": sorted(k for k in kinds if k),
        "duration_s": round(time.monotonic() - t_start, 3),
    }
    line = json.dumps(artifact, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        print(f"artifact written to {args.out}", file=sys.stderr)
    assert zero_failures, "SURGE DRILL INVARIANT VIOLATED"
    print(
        "surge invariant holds: zero failed client requests over "
        f"{lg_art['n_ok']} ok replies; scale-out at "
        f"{timeline['scale_out_ready_s']}s, replacement at "
        f"{timeline['replaced_ready_s']}s, scale-in at "
        f"{timeline['scale_in_done_s']}s",
        file=sys.stderr,
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--out", default=None, help="artifact path (JSON)")
    ap.add_argument(
        "--journal", default=None,
        help="journal path (default: a temp file, embedded in the artifact)",
    )
    ap.add_argument(
        "--fleet", action="store_true",
        help="run the FLEET drill instead: 2 replica subprocesses behind "
        "the front-door router — kill-replica, rolling-deploy, and "
        "corrupt-deploy scenarios under continuous traffic "
        "(docs/FLEET.md)",
    )
    ap.add_argument(
        "--metrics-out", default=None,
        help="(--fleet/--surge) write the router's final /metrics page "
        "here after strict validation",
    )
    ap.add_argument(
        "--fleet-metrics-out", default=None,
        help="(--fleet) write the aggregated /fleet/metrics page "
        "(replicas scraped + merged + router families) here after "
        "strict validation",
    )
    ap.add_argument(
        "--fleet-trace-out", default=None,
        help="(--fleet) write the cross-process joined /fleet/trace "
        "export (Perfetto-loadable) captured during the healthy "
        "two-replica window here",
    )
    ap.add_argument(
        "--incident-out", default=None,
        help="(--fleet) copy the incident bundle captured during the "
        "kill-replica scenario (alert + history window + request tail "
        "+ journal tail, manifest-complete) to this directory",
    )
    ap.add_argument(
        "--metrics-early-out", default=None,
        help="(--fleet) write the router's /metrics page scraped during "
        "the healthy baseline window here — pairs with --metrics-out "
        "for a tools/validate_metrics.py --diff monotonicity check "
        "across the drill",
    )
    ap.add_argument(
        "--surge", action="store_true",
        help="run the ELASTIC-FLEET drill instead: autoscaler + "
        "lifecycle manager over real replica subprocesses under one "
        "ramped loadgen client — journaled scale-out under burst, "
        "SIGKILL mid-burst replaced automatically, drain-first "
        "scale-in, an injected unready spawn failing closed, zero "
        "failed client requests (docs/FLEET.md 'Elastic fleet')",
    )
    ap.add_argument("--connections", type=int, default=128,
                    help="(--surge) loadgen keep-alive connections; the "
                    "closed loop bounds in-flight work at this, so the "
                    "burst's latency plateaus at connections/capacity "
                    "(Little's law) instead of running away into "
                    "client timeouts")
    ap.add_argument("--ramp-low", type=float, default=0.25,
                    help="(--surge) per-connection rps outside the burst")
    ap.add_argument("--compute-delay-ms", type=float, default=8.0,
                    help="(--surge) per-compute delay armed in every "
                    "replica (engine.compute:delay) emulating a "
                    "production-cost model — sets the fleet capacity "
                    "the burst must exceed (~1000/(2.5+this) qps per "
                    "replica)")
    ap.add_argument("--ramp-high", type=float, default=6.0,
                    help="(--surge) per-connection rps during the burst "
                    "(offered = connections x this; keep it above the "
                    "fleet's capacity — the pacing degrades to closed-"
                    "loop saturation when the fleet can't keep up)")
    ap.add_argument("--burst-start", type=float, default=15.0,
                    help="(--surge) seconds into the client run the "
                    "burst begins")
    ap.add_argument("--burst-end", type=float, default=210.0,
                    help="(--surge) seconds into the client run the "
                    "burst ends")
    ap.add_argument("--client-duration", type=float, default=330.0,
                    help="(--surge) total loadgen duration")
    ap.add_argument("--min-replicas", type=int, default=2)
    ap.add_argument("--max-replicas", type=int, default=3)
    ap.add_argument("--out-queue-depth", type=float, default=3.0,
                    help="(--surge) scale-out queue-depth threshold")
    ap.add_argument("--out-latency-ms", type=float, default=150.0)
    ap.add_argument("--in-latency-ms", type=float, default=40.0)
    ap.add_argument("--breach-polls", type=int, default=3)
    ap.add_argument("--idle-polls", type=int, default=8)
    ap.add_argument("--cooldown", type=float, default=20.0)
    ap.add_argument("--ready-deadline", type=float, default=360.0,
                    help="(--surge) spawn-to-ready bound for real "
                    "replica warmups")
    ap.add_argument("--spawn-fault-deadline", type=float, default=30.0,
                    help="(--surge) tightened ready deadline for the "
                    "fail-closed corrupt-spawn branch")
    args = ap.parse_args(argv)
    if args.surge:
        return run_surge_drill(args)
    if args.fleet:
        return run_fleet_drill(args)

    t_start = time.monotonic()
    from machine_learning_replications_tpu.data.examples import EXAMPLE_PATIENT
    from machine_learning_replications_tpu.obs import journal
    from machine_learning_replications_tpu.persist import orbax_io
    from machine_learning_replications_tpu.resilience import lastgood
    from machine_learning_replications_tpu.serve import make_server

    journal_path = args.journal or os.path.join(
        tempfile.mkdtemp(prefix="chaos_"), "chaos_journal.jsonl"
    )
    jrn = journal.RunJournal(journal_path, command="chaos_drill")
    journal.set_journal(jrn)

    params = make_sklearn_params(seed=7)
    patient = dict(EXAMPLE_PATIENT)
    scenarios: dict[str, dict] = {}

    # -- live-server scenarios ---------------------------------------------
    handle = make_server(
        params, port=0, buckets=(1, 8), max_wait_ms=2.0,
        supervise=True, flush_deadline_s=0.6, breaker_failures=2,
        restart_backoff_s=0.25, restart_backoff_max_s=2.0,
        fault_endpoint=True,
        # Routing ON for the whole drill (the cli serve default): the
        # degradation contract must hold with the dual-path router in
        # the loop — host-path failures fall back through the supervised
        # device path, so the breaker arc below is unchanged.
        host_path=True,
    ).start_background()
    host, port = handle.address
    base = f"http://{host}:{port}"
    try:
        # Golden reply: every later 200 must carry this exact probability.
        warm = Outcomes()
        kind, info = post_predict(base, patient, None, warm)
        assert kind == "ok", f"pre-chaos request failed: {kind} {info}"
        golden = info["probability"]

        # The endpoint guard is real: the snapshot works because this
        # server opted in (fault_endpoint=True).
        code, snap = get_json(base, "/debug/faults")
        assert code == 200 and snap["endpoint_enabled"], snap

        # --- scenario: compute_fault --------------------------------------
        out = Outcomes()
        post_faults(base, {"arm": "engine.compute:raise"})
        seen = {"http_500": 0, "http_503": 0}

        def breaker_is_open():
            k, info = post_predict(base, patient, golden, out)
            if k in seen:
                seen[k] += 1
            if k == "http_503":
                assert info["retry_after"] is not None, \
                    "degraded 503 must carry Retry-After"
            return k == "http_503"

        wait_until(breaker_is_open, 15.0, "breaker open (503 shed)")
        # The progression matters, not just the endpoint: the breaker
        # needs breaker_failures=2 explicit 500s before the first shed.
        assert seen["http_500"] >= 2, seen
        code, health = get_json(base, "/healthz")
        assert code == 200 and health["status"] == "degraded", health
        assert health["ready"] is False
        code, ready = get_json(base, "/readyz")
        assert code == 503 and "degraded: circuit breaker open" in \
            ready["reasons"], ready

        # Patient clients ride the degraded window: loadgen retries with
        # backoff + Retry-After while we disarm mid-run.
        lg_out = os.path.join(os.path.dirname(journal_path), "lg_chaos.json")
        lg = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "loadgen.py"),
             "--url", base, "--mode", "closed", "--concurrency", "2",
             "--duration", "5", "--retries", "8", "--retry-base-ms", "50",
             "--out", lg_out],
            stdout=subprocess.DEVNULL,
        )
        # Leave the fault armed until loadgen's workers have demonstrably
        # taken degraded-mode sheds (the counter only moves for breaker-
        # open 503s), so the retry policy provably rides the window —
        # a fixed timer would race the subprocess interpreter startup.
        def sheds(base=base):
            _, m = get_json(base, "/metrics?format=json")
            return m["runtime"].get("resilience_degraded_sheds_total", 0)

        sheds0 = sheds()
        try:
            wait_until(lambda: sheds() >= sheds0 + 2, 8.0,
                       "loadgen rides the degraded window")
        except AssertionError:
            pass  # breaker-flap timing; the retry block just reads 0
        post_faults(base, {"disarm": "engine.compute"})
        assert lg.wait(timeout=60) == 0
        with open(lg_out) as f:
            lg_art = json.load(f)

        def recovered():
            k, _ = post_predict(base, patient, golden, out)
            return k == "ok"

        wait_until(recovered, 20.0, "breaker close (200 resumes)")
        code, health = get_json(base, "/healthz")
        assert health["status"] == "ok" and health["ready"] is True, health
        scenarios["compute_fault"] = {
            **out.as_dict(),
            "loadgen_retry": lg_art.get("retry"),
            "loadgen_ok": lg_art.get("n_ok"),
            "loadgen_shed_final": lg_art.get("n_shed"),
        }

        # --- scenario: wedged_compute -------------------------------------
        # Pinned to the device path: the watchdog under test lives in the
        # supervised engine (an unpinned single would route host, where
        # the 2 s stall is just a slow-but-bounded correct answer).
        out = Outcomes()
        post_faults(base, {"arm": "engine.compute:delay=2.0@n=1"})
        kind, info = post_predict(base, patient, golden, out, pin="device")
        # The wedge is detected at the 0.6 s flush deadline: the client
        # gets an explicit 504 (or a 503 if a concurrent probe opened the
        # breaker first) in bounded time — never the 2 s injected stall.
        assert kind in ("http_504", "http_503"), (kind, info)
        wait_until(recovered, 20.0, "recovery after wedge")
        scenarios["wedged_compute"] = out.as_dict()

        # --- scenario: flush_delay ----------------------------------------
        out = Outcomes()
        post_faults(base, {"arm": "batcher.flush:delay=0.8@n=1"})
        t0 = time.monotonic()
        kind, _ = post_predict(base, patient, golden, out, pin="device")
        dt = time.monotonic() - t0
        assert kind == "ok" and dt >= 0.8, (kind, dt)
        scenarios["flush_delay"] = {**out.as_dict(),
                                    "delayed_seconds": round(dt, 3)}

        # --- scenario: dual_path_routing ----------------------------------
        # Routing itself under chaos: both paths serve the golden bits,
        # and a one-shot host-path fault is absorbed by the transparent
        # device fallback (200, correct, client never sees it).
        out = Outcomes()
        for pin in ("host", "device", None):
            kind, info = post_predict(base, patient, golden, out, pin=pin)
            assert kind == "ok", (pin, kind, info)
        post_faults(base, {"arm": "engine.compute:raise@count=1"})
        kind, info = post_predict(base, patient, golden, out, pin="host")
        assert kind == "ok", (kind, info)  # fallback answered correctly
        _, m = get_json(base, "/metrics?format=json")
        paths = m["runtime"].get("serve_path_total", {})
        assert paths.get("path=host", 0) >= 1 and \
            paths.get("path=device", 0) >= 1, paths
        assert m["runtime"].get("serve_host_fallback_total", 0) >= 1, \
            m["runtime"].get("serve_host_fallback_total")
        scenarios["dual_path_routing"] = {**out.as_dict(), "paths": paths}

        # --- scenario: edge_faults ----------------------------------------
        out = Outcomes()
        post_faults(base, {"arm": "server.parse:raise@n=1"})
        kind, _ = post_predict(base, patient, golden, out)
        assert kind == "http_500", kind
        post_faults(base, {"arm": "server.respond:raise@n=1"})
        kind, _ = post_predict(base, patient, golden, out)
        assert kind == "conn_err", kind  # dropped, nothing written
        kind, _ = post_predict(base, patient, golden, out)
        assert kind == "ok", kind
        scenarios["edge_faults"] = out.as_dict()

        # Metrics evidence + strict exposition.
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=HARD_TIMEOUT_S) as resp:
            page = resp.read().decode()
        for family in ("fault_injected_total", "resilience_breaker_state",
                       "resilience_breaker_transitions_total",
                       "resilience_engine_restarts_total",
                       "resilience_degraded_sheds_total"):
            assert family in page, f"{family} missing from /metrics"
        from validate_metrics import validate  # noqa: E402 (tools/ sibling)

        errs = validate(page)
        assert not errs, f"/metrics failed strict validation: {errs[:5]}"
    finally:
        handle.shutdown()

    # -- offline checkpoint scenarios --------------------------------------
    ckpt_root = tempfile.mkdtemp(prefix="chaos_ckpt_")
    ckpt = os.path.join(ckpt_root, "model")
    import numpy as np

    from machine_learning_replications_tpu.data.examples import patient_row
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.resilience import faults

    params_v2 = make_sklearn_params(seed=11)
    p_v1 = float(np.asarray(
        stacking.predict_proba1(params, patient_row()))[0])
    p_v2 = float(np.asarray(
        stacking.predict_proba1(params_v2, patient_row()))[0])
    assert p_v1 != p_v2, "the two model versions must be distinguishable"

    # corrupt_restore: v1 then v2 (v1 retained as lastgood); corrupt v2 on
    # disk; the load must roll back to v1 and journal it.
    orbax_io.save_model(ckpt, params)
    orbax_io.save_model(ckpt, params_v2)
    assert os.path.isdir(lastgood.lastgood_path(ckpt))
    faults.arm("persist.restore:corrupt@once")
    rolled = orbax_io.load_model(ckpt)
    p_rolled = float(np.asarray(
        stacking.predict_proba1(rolled, patient_row()))[0])
    assert p_rolled == p_v1, (p_rolled, p_v1)
    scenarios["corrupt_restore"] = {
        "rolled_back_to_lastgood": True,
        "serves_previous_model": p_rolled == p_v1,
    }

    # save_interrupted: a save torn mid-publish must leave the previous
    # checkpoint fully intact (the corrupted primary was consumed above,
    # so rebuild a clean v2 state first).
    orbax_io.save_model(ckpt, params_v2)
    faults.arm("persist.save:raise@once")
    try:
        orbax_io.save_model(ckpt, params)
        raise AssertionError("interrupted save should have raised")
    except faults.InjectedFault:
        pass
    intact = orbax_io.load_model(ckpt)
    p_intact = float(np.asarray(
        stacking.predict_proba1(intact, patient_row()))[0])
    assert p_intact == p_v2, (p_intact, p_v2)
    scenarios["save_interrupted"] = {
        "previous_checkpoint_intact": p_intact == p_v2,
    }

    journal.set_journal(None)
    jrn.close()
    with open(journal_path) as f:
        events = [json.loads(line) for line in f]
    kinds = {e.get("kind") for e in events}
    for needed in ("fault_injected", "breaker_open", "engine_restart",
                   "breaker_close", "checkpoint_rollback"):
        assert needed in kinds, f"journal lacks {needed!r} ({sorted(kinds)})"
    restarts_ok = [
        e for e in events
        if e.get("kind") == "engine_restart" and e.get("ok")
    ]
    assert restarts_ok, "no successful supervised restart journaled"

    total = Outcomes()
    for s in scenarios.values():
        for k, v in s.get("outcomes", {}).items():
            total.counts[k] = total.counts.get(k, 0) + v
        total.wrong_answers += s.get("wrong_answers", 0)
        total.hangs += s.get("hangs", 0)
    artifact = {
        "kind": "chaos_drill",
        "manifest": journal.run_manifest(command="chaos_drill"),
        "invariant": {
            "statement": "every request: correct answer or explicit "
            "failure; zero wrong answers, zero hangs",
            "wrong_answers": total.wrong_answers,
            "hangs": total.hangs,
            "holds": total.wrong_answers == 0 and total.hangs == 0,
        },
        "outcomes_total": dict(sorted(total.counts.items())),
        "scenarios": scenarios,
        "journal_event_kinds": sorted(k for k in kinds if k),
        "successful_restarts": len(restarts_ok),
        "duration_s": round(time.monotonic() - t_start, 3),
    }
    line = json.dumps(artifact, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        print(f"artifact written to {args.out}", file=sys.stderr)
    assert artifact["invariant"]["holds"], "CHAOS INVARIANT VIOLATED"
    print("chaos invariant holds: zero wrong answers, zero hangs",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
