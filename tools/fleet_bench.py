#!/usr/bin/env python
"""Router bench driver — the first 1000-connection bench of the fleet's
front door; writes a FLEET_BENCH_*.json artifact.

The serving ceiling has been measured replica-side since r6 and the
router had never been pointed at by ``loadgen --connections 1000``
(ROADMAP item 1). This driver stands the whole service up and measures
it as one unit:

  1. publishes a checkpoint (sklearn-imported ensemble, the chaos
     drill's model) — or serves ``--model`` if given;
  2. starts the front-door router in-process (journal + metrics owned
     here) and N real ``cli serve`` replica subprocesses that
     self-register and probe into rotation;
  3. runs ONE ``tools/loadgen.py`` subprocess against the router with
     ``--baseline-url`` pointed at replica 1 — the run interleaves
     through-router and direct-replica slices, so the artifact carries
     throughput AND the router-added overhead deltas
     (``router_overhead_ms``) from the same minutes on the same host;
  4. augments the artifact with the fleet's own view: registry snapshot
     (per-replica load signals the balancer picked on), upstream pool
     connection stats, router config;
  5. strict-validates the router's ``/metrics`` page
     (``--metrics-out``) and enforces the invariants:
     **zero client errors**, ``--assert-qps`` (achieved through-router
     qps floor), and ``--assert-overhead-ms`` (router-added p50
     ceiling) — the CI ``router-bench`` job runs a compressed pass on
     every push.

Run from the repo root (CPU is fine)::

    JAX_PLATFORMS=cpu python tools/fleet_bench.py \\
        --connections 1000 --duration 60 --out FLEET_BENCH_r17_cpu.json \\
        --metrics-out fleet_bench_metrics.txt --journal fleet_bench.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from chaos_drill import make_sklearn_params, _free_port, wait_until  # noqa: E402


def _spawn_replica(rid: str, port: int, ckpt: str, register_url: str,
                   serve_args: list[str], quiet: bool):
    sink = subprocess.DEVNULL if quiet else None
    return subprocess.Popen(
        [sys.executable, "-m", "machine_learning_replications_tpu",
         "serve", "--model", ckpt, "--port", str(port),
         "--replica-id", rid, "--register", register_url] + serve_args,
        stdout=sink, stderr=sink,
    )


def _run_stub_worker(port: int) -> int:
    """``--_stub-worker``: a minimal constant-reply replica on the real
    event-loop transport, in its own process. The ``--stub-replicas``
    mode measures the ROUTER's data plane against these — replica
    compute off the table, every byte of proxy machinery on it."""
    import threading

    from machine_learning_replications_tpu.serve.transport import (
        EventLoopHttpServer,
    )

    body_headers = {"X-Replica": f"stub{port}", "X-Model-Version": "1",
                    "X-Serve-Path": "host"}

    class _StubApp:
        def handle_request(self, req, rsp):
            if req.path == "/readyz":
                rsp.send_json(200, {"ready": True, "version": 1,
                                    "queue_depth": 0})
                return
            rsp.send_json(200, {"probability": 0.25},
                          headers=body_headers,
                          request_id=req.get_header("x-request-id"))

        def handle_protocol_error(self, exc, rsp):
            rsp.send_json(exc.code, {"error": exc.message}, close=True)

    # Backlog sized for the router pool's connect bursts: a stub is a
    # data-plane measurement device, not an admission-control study.
    httpd = EventLoopHttpServer(("127.0.0.1", port), _StubApp(),
                                backlog=1024)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: done.set())
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    done.wait()
    httpd.server_close()
    return 0


def _spawn_stub(port: int):
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--_stub-worker", str(port)],
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--connections", type=int, default=1000)
    ap.add_argument("--duration", type=float, default=60.0,
                    help="total loadgen seconds (split across the "
                    "interleaved router/baseline slices)")
    ap.add_argument("--rate-per-conn", type=float, default=0.0,
                    help="pace each connection (0 = saturation)")
    ap.add_argument("--baseline-segments", type=int, default=3)
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the interleaved direct-replica leg: one "
                    "continuous through-router run. The saturation "
                    "ceiling cell uses this — at saturation a slice "
                    "boundary strands ~connections in-flight requests "
                    "that pollute the next slice, so overhead is "
                    "measured by a separate paced --baseline-url run")
    ap.add_argument("--model", default=None,
                    help="serve an existing checkpoint instead of "
                    "publishing the synthetic bench model")
    ap.add_argument("--stub-replicas", action="store_true",
                    help="replicas are minimal constant-reply stub "
                    "processes on the real transport: the router-data-"
                    "plane ceiling cell — on a small host the full "
                    "stack saturates total CPU long before the router "
                    "does (BENCH.md stage math)")
    ap.add_argument("--_stub-worker", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--serve-arg", action="append", default=None,
                    help="extra cli serve argument (repeatable; "
                    "--serve-arg=--no-quality form for dash-leading)")
    ap.add_argument("--hedge-ms", type=float, default=0.0,
                    help="router hedge delay (0 disables; saturation "
                    "benches must not hedge a fully loaded fleet)")
    ap.add_argument("--router-workers", type=int, default=0,
                    help="run the router as `cli fleet router --workers "
                    "N` SO_REUSEPORT processes instead of in-process — "
                    "the many-core scaling cell (0 = in-process router)")
    ap.add_argument("--request-timeout", type=float, default=30.0)
    ap.add_argument("--warm-s", type=float, default=3.0,
                    help="pre-bench warm traffic seconds (compile/route "
                    "warmup stays out of the measured window)")
    ap.add_argument("--out", default=None, help="artifact path (JSON)")
    ap.add_argument("--metrics-out", default=None,
                    help="write the router's /metrics page here and "
                    "strict-validate it")
    ap.add_argument("--journal", default=None,
                    help="router journal path (obs_report --fleet joins "
                    "it with the artifact)")
    ap.add_argument("--assert-qps", type=float, default=None,
                    help="fail unless through-router achieved qps >= this")
    ap.add_argument("--assert-overhead-ms", type=float, default=None,
                    help="fail unless router-added p50 <= this")
    ap.add_argument("--ready-timeout", type=float, default=300.0)
    ap.add_argument("--history-interval", type=float, default=10.0,
                    help="router history-sampler interval for the "
                    "in-process router (0 disables the history/alerting "
                    "plane — the no-history leg of the sampler-overhead "
                    "comparison)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if getattr(args, "_stub_worker", None):
        return _run_stub_worker(args._stub_worker)

    from machine_learning_replications_tpu.fleet import make_router
    from machine_learning_replications_tpu.obs import journal
    from machine_learning_replications_tpu.obs.registry import REGISTRY

    workdir = tempfile.mkdtemp(prefix="fleet_bench_")
    jrn = None
    if args.journal:
        jrn = journal.RunJournal(args.journal, command="fleet_bench")
        journal.set_journal(jrn)

    ckpt = args.model
    if ckpt is None and not args.stub_replicas:
        from machine_learning_replications_tpu.persist import orbax_io

        ckpt = os.path.join(workdir, "model")
        orbax_io.save_model(ckpt, make_sklearn_params(seed=7))
        print(f"published bench checkpoint at {ckpt}", file=sys.stderr)

    serve_args = list(args.serve_arg or [])
    procs = {}
    router = None          # in-process RouterHandle
    router_proc = None     # `cli fleet router --workers N` subprocess
    rc = 1

    # Stub replicas are spawned before a multi-worker router so their
    # urls can seed EVERY worker's registry statically (stubs do not
    # self-register); real replicas self-register, so they come after
    # the router regardless of its mode.
    stub_members = []
    if args.stub_replicas:
        for i in range(args.replicas):
            rid = f"b{i + 1}"
            port = _free_port()
            procs[rid] = _spawn_stub(port)
            stub_members.append((rid, f"http://127.0.0.1:{port}"))

    if args.router_workers:
        rport = _free_port()
        base = f"http://127.0.0.1:{rport}"
        rcmd = [sys.executable, "-m", "machine_learning_replications_tpu",
                "fleet", "router", "--port", str(rport),
                "--workers", str(args.router_workers),
                "--hedge-ms", str(args.hedge_ms),
                "--request-timeout", str(args.request_timeout)]
        for rid, url in stub_members:
            rcmd += ["--replica", f"{rid}={url}"]
        sink = None if args.verbose else subprocess.DEVNULL
        router_proc = subprocess.Popen(rcmd, stdout=sink, stderr=sink)
    else:
        router = make_router(
            port=0, probe_interval_s=0.5,
            request_timeout_s=args.request_timeout,
            hedge_ms=args.hedge_ms, max_attempts=3,
            history_interval_s=args.history_interval,
        ).start_background()
        base = f"http://{router.address[0]}:{router.address[1]}"
        for rid, url in stub_members:
            router.registry.register(rid, url)

    def http_json(path):
        import urllib.request

        with urllib.request.urlopen(base + path, timeout=10) as resp:
            return json.loads(resp.read())

    def ready_count():
        if router is not None:
            return router.registry.ready_count()
        try:
            return sum(
                1 for r in http_json("/fleet/replicas")["replicas"]
                if r["in_rotation"]
            )
        except Exception:
            return 0

    def registry_snapshot():
        if router is not None:
            return router.registry.snapshot()
        return http_json("/fleet/replicas")["replicas"]

    try:
        if not args.stub_replicas:
            for i in range(args.replicas):
                procs[f"b{i + 1}"] = _spawn_replica(
                    f"b{i + 1}", _free_port(), ckpt, base, serve_args,
                    quiet=not args.verbose,
                )
        # With N SO_REUSEPORT router workers each GET lands on ONE
        # worker: require consecutive all-ready answers so every
        # worker's registry (converging via registration heartbeats)
        # has the fleet before the measured window starts.
        need = max(1, 3 * args.router_workers)
        streak = [0]

        def all_ready():
            if ready_count() == args.replicas:
                streak[0] += 1
            else:
                streak[0] = 0
            return streak[0] >= need

        wait_until(
            all_ready, args.ready_timeout,
            f"all {args.replicas} replicas warm and in rotation "
            "(every router worker)",
            poll_s=0.5,
        )
        snap = registry_snapshot()
        baseline_url = snap[0]["url"]
        print(
            f"fleet ready: router {base}, {args.replicas} replicas, "
            f"baseline {baseline_url}", file=sys.stderr,
        )

        loadgen = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "loadgen.py")
        if args.warm_s > 0:
            subprocess.run(
                [sys.executable, loadgen, "--url", base,
                 "--connections", "32", "--duration", str(args.warm_s)],
                check=True, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
        out_path = args.out or os.path.join(workdir, "fleet_bench.json")
        cmd = [
            sys.executable, loadgen, "--url", base,
            "--connections", str(args.connections),
            "--duration", str(args.duration),
            "--out", out_path,
        ]
        if not args.no_baseline:
            cmd += ["--baseline-url", baseline_url,
                    "--baseline-segments", str(args.baseline_segments)]
        if args.rate_per_conn:
            cmd += ["--rate-per-conn", str(args.rate_per_conn)]
        print("loadgen: " + " ".join(cmd[1:]), file=sys.stderr)
        res = subprocess.run(
            cmd, stdout=subprocess.DEVNULL, timeout=args.duration * 4 + 300,
        )
        if res.returncode != 0:
            raise AssertionError(f"loadgen exited {res.returncode}")

        with open(out_path) as f:
            art = json.load(f)
        art["kind"] = "fleet_bench"
        art["fleet_bench"] = {
            "replicas": args.replicas,
            "serve_args": serve_args,
            "hedge_ms": args.hedge_ms,
            "router_workers": args.router_workers or None,
            "checkpoint": (
                "stub" if args.stub_replicas
                else "synthetic" if args.model is None else args.model
            ),
            # Multi-worker mode: these come over HTTP from whichever
            # worker answered — one worker's view, labeled as such.
            "upstream_pool": (
                router.upstream.stats() if router is not None
                else (http_json("/healthz") or {}).get("upstream")
            ),
            "registry": registry_snapshot(),
        }
        line = json.dumps(art, indent=1)
        with open(out_path, "w") as f:
            f.write(line + "\n")
        print(line)

        if args.metrics_out:
            if router is not None:
                page = REGISTRY.render_prometheus()
            else:
                import urllib.request

                with urllib.request.urlopen(
                    base + "/metrics", timeout=10
                ) as resp:
                    page = resp.read().decode()
            with open(args.metrics_out, "w") as f:
                f.write(page)
            from validate_metrics import validate

            problems = validate(page)
            assert not problems, f"router /metrics invalid: {problems[:5]}"
            print(f"metrics written to {args.metrics_out} "
                  "(strict-validator clean)", file=sys.stderr)

        # -- invariants -----------------------------------------------------
        assert art["n_err"] == 0, (
            f"client errors through the router: {art['n_err']}"
        )
        baseline = art.get("baseline")
        if baseline is not None:
            assert baseline["n_err"] == 0, (
                f"client errors on the direct leg: {baseline['n_err']}"
            )
        qps = art["achieved_qps"]
        overhead = (art.get("router_overhead_ms") or {}).get("p50")
        msg = (
            f"router: {qps} qps over {art['n_ok']} ok "
            f"(p50 {art['latency_ms']['p50']} ms)"
        )
        if baseline is not None:
            msg += (
                f"; direct: {baseline['achieved_qps']} qps (p50 "
                f"{baseline['latency_ms']['p50']} ms); "
                f"router-added p50 {overhead} ms"
            )
        print(msg, file=sys.stderr)
        if args.assert_qps is not None:
            assert qps >= args.assert_qps, (
                f"through-router qps {qps} < floor {args.assert_qps}"
            )
        if args.assert_overhead_ms is not None:
            assert overhead is not None and \
                overhead <= args.assert_overhead_ms, (
                    f"router-added p50 {overhead} ms > ceiling "
                    f"{args.assert_overhead_ms} ms"
                )
        print("FLEET BENCH PASS", file=sys.stderr)
        rc = 0
    finally:
        if router_proc is not None and router_proc.poll() is None:
            router_proc.send_signal(signal.SIGTERM)
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 30
        for proc in list(procs.values()) + (
            [router_proc] if router_proc is not None else []
        ):
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        if router is not None:
            router.shutdown()
        if jrn is not None:
            journal.set_journal(None)
            jrn.close()
            print(f"journal written to {jrn.path}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
