#!/usr/bin/env python
"""Strict Prometheus text-exposition (version 0.0.4) validator.

The pre-PR-1 serving layer shipped an exposition a lenient eyeball passed
and a strict Prometheus scraper rejected wholesale (summary-style quantile
samples inside a histogram family — metadata after samples). This tool is
the regression gate: it parses an exposition page the way a strict scraper
does and fails loudly on anything malformed, so `/metrics` format bugs die
in CI instead of in a monitoring stack that silently drops the whole page.

Checks (text format 0.0.4, plus the grouping rule scrapers enforce):

  * line syntax — `# HELP`/`# TYPE` metadata, comments, samples of the
    form `name{label="value",...} value [timestamp]`;
  * name legality — metric `[a-zA-Z_:][a-zA-Z0-9_:]*`, label
    `[a-zA-Z_][a-zA-Z0-9_]*`, no `__`-reserved labels;
  * metadata discipline — at most one HELP and one TYPE per family, TYPE
    before any of the family's samples, families not interleaved or
    re-opened;
  * sample-name/type agreement — histogram families expose only
    `_bucket`/`_sum`/`_count` (+`le` on buckets), counters and gauges only
    their bare name; unknown suffixed samples start a new (untyped)
    family;
  * value legality — floats, `NaN`, `+Inf`/`-Inf`; counters and histogram
    counts must not be NaN or negative;
  * histogram coherence — a `+Inf` bucket exists, bucket counts are
    monotonically non-decreasing in `le` order, `_count` equals the
    `+Inf` bucket;
  * no duplicate sample (same name + label set) anywhere on the page;
  * the page ends with a newline (the 0.0.4 framing requirement).

Usage:
    python tools/validate_metrics.py [file ...]      # or stdin
    curl -s localhost:8000/metrics | python tools/validate_metrics.py
    python tools/validate_metrics.py --diff A B      # scrape pair

``--diff A B`` compares two exposition snapshots of the SAME process
family-by-family and flags counter regressions: every counter sample
(and every histogram ``_bucket``/``_count``/``_sum`` — cumulative too)
present in both pages must be monotonically non-decreasing from A to B.
A series present only in one page is fine (new work started; a retired
replica's series was dropped) — only a value that went *backwards*
without the process restarting is a lie, and it is exactly the lie that
poisons every rate derivation downstream (the history store's
reset-safe ``rate()`` would silently eat the decrease). Both pages are
also strict-validated first. CI runs this across two scrapes of the
chaos drill's router.

Exit 0 when every input page is valid; 1 otherwise, one error per line on
stderr. Importable: ``validate(text) -> list[str]`` returns the errors,
``diff_counters(a, b) -> list[str]`` the regressions.
"""

from __future__ import annotations

import math
import re
import sys

_METRIC_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")
# name{labels} value [timestamp] — labels parsed separately.
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)"
    r"(?:\s+(?P<ts>-?\d+))?\s*$"
)
_LABEL_PAIR_RE = re.compile(
    r'\s*(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*'
    r'"(?P<value>(?:[^"\\]|\\.)*)"\s*(?P<sep>,|$)'
)
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")
_SUMMARY_SUFFIXES = ("_sum", "_count")
_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}


def _parse_value(tok: str) -> float | None:
    if tok in ("+Inf", "Inf"):
        return math.inf
    if tok == "-Inf":
        return -math.inf
    if tok == "NaN":
        return math.nan
    try:
        return float(tok)
    except ValueError:
        return None


def _escapes_ok(s: str) -> bool:
    """Only \\\\, \\" and \\n are legal escapes in a label value."""
    i = 0
    while i < len(s):
        if s[i] == "\\":
            if i + 1 >= len(s) or s[i + 1] not in '\\"n':
                return False
            i += 2
        else:
            i += 1
    return True


def _parse_labels(raw: str, where: str, errors: list[str]) -> dict | None:
    """The {..} body → dict; None on syntax error. Strict: only
    `name="value"` pairs, comma separated, a trailing comma allowed."""
    labels: dict[str, str] = {}
    pos = 0
    while pos < len(raw):
        m = _LABEL_PAIR_RE.match(raw, pos)
        if not m:
            errors.append(f"{where}: malformed label set {{{raw}}}")
            return None
        name = m.group("name")
        if name.startswith("__"):
            errors.append(f"{where}: reserved label name {name!r}")
            return None
        if name in labels:
            errors.append(f"{where}: duplicate label {name!r}")
            return None
        # Validate escapes: only \\ \" \n are defined for label values
        # (scanned pairwise — a regex can't pair consecutive backslashes).
        if not _escapes_ok(m.group("value")):
            errors.append(
                f"{where}: invalid escape in label value {m.group('value')!r}"
            )
            return None
        labels[name] = m.group("value")
        pos = m.end()
        if m.group("sep") == "" and pos < len(raw):
            errors.append(f"{where}: trailing garbage in label set")
            return None
    return labels


def _base_family(name: str, typed: dict[str, str]) -> str:
    """The family a sample line belongs to, honoring declared types: a
    `x_bucket` sample belongs to histogram family `x` only when `x` is
    declared histogram (summary: `_sum`/`_count` (+quantile on bare name));
    otherwise the sample name IS the family."""
    for suffix in _HIST_SUFFIXES:
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if typed.get(base) == "histogram" and suffix in _HIST_SUFFIXES:
                return base
            if typed.get(base) == "summary" and suffix in _SUMMARY_SUFFIXES:
                return base
    return name


class _Fam:
    __slots__ = ("help", "type", "samples_seen", "closed", "buckets")

    def __init__(self) -> None:
        self.help: str | None = None
        self.type: str | None = None
        self.samples_seen = False
        self.closed = False
        self.buckets: dict[tuple, list[tuple[str, float]]] = {}


def validate(text: str) -> list[str]:
    """Validate one exposition page; returns a list of error strings
    (empty = valid)."""
    errors: list[str] = []
    if text and not text.endswith("\n"):
        errors.append("page must end with a newline (text format 0.0.4)")
    families: dict[str, _Fam] = {}
    typed: dict[str, str] = {}
    current: str | None = None
    seen_samples: set[tuple] = set()

    def fam(name: str) -> _Fam:
        f = families.get(name)
        if f is None:
            f = families[name] = _Fam()
        return f

    def switch_to(name: str, where: str) -> _Fam:
        nonlocal current
        f = fam(name)
        if current is not None and current != name:
            families[current].closed = True
        if f.closed:
            errors.append(
                f"{where}: family {name!r} re-opened — all lines of a "
                "family must form one group"
            )
        current = name
        return f

    for i, line in enumerate(text.splitlines()):
        where = f"line {i + 1}"
        if not line.strip():
            errors.append(f"{where}: blank line (0.0.4 allows none)")
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] in ("HELP", "TYPE"):
                if len(parts) < 3 or not _METRIC_RE.match(parts[2]):
                    errors.append(f"{where}: malformed {parts[1]} line")
                    continue
                name = parts[2]
                f = switch_to(name, where)
                if parts[1] == "HELP":
                    if f.help is not None:
                        errors.append(f"{where}: second HELP for {name!r}")
                    if f.samples_seen:
                        errors.append(
                            f"{where}: HELP for {name!r} after its samples"
                        )
                    f.help = parts[3] if len(parts) > 3 else ""
                else:
                    kind = parts[3].strip() if len(parts) > 3 else ""
                    if kind not in _TYPES:
                        errors.append(
                            f"{where}: unknown TYPE {kind!r} for {name!r}"
                        )
                        continue
                    if f.type is not None:
                        errors.append(f"{where}: second TYPE for {name!r}")
                    if f.samples_seen:
                        errors.append(
                            f"{where}: TYPE for {name!r} after its samples"
                        )
                    f.type = kind
                    typed[name] = kind
            # else: a plain comment — legal anywhere
            continue

        m = _SAMPLE_RE.match(line)
        if not m:
            errors.append(f"{where}: unparseable sample line {line!r}")
            continue
        name = m.group("name")
        labels = _parse_labels(m.group("labels") or "", where, errors)
        if labels is None:
            continue
        value = _parse_value(m.group("value"))
        if value is None:
            errors.append(f"{where}: bad value {m.group('value')!r}")
            continue

        base = _base_family(name, typed)
        f = switch_to(base, where)
        f.samples_seen = True
        kind = f.type or "untyped"

        # sample-name/type agreement
        if kind == "histogram":
            if name == base + "_bucket":
                if "le" not in labels:
                    errors.append(f"{where}: histogram bucket without le")
                key = tuple(sorted(
                    (k, v) for k, v in labels.items() if k != "le"
                ))
                f.buckets.setdefault(key, []).append(
                    (labels.get("le", ""), value)
                )
            elif name not in (base + "_sum", base + "_count"):
                errors.append(
                    f"{where}: sample {name!r} not legal in histogram "
                    f"family {base!r}"
                )
        elif kind in ("counter", "gauge") and name != base:
            errors.append(
                f"{where}: sample {name!r} not legal in {kind} family "
                f"{base!r}"
            )
        if kind == "counter" or (
            kind == "histogram" and name != base + "_sum"
        ):
            if value != value or value < 0:
                errors.append(
                    f"{where}: {kind} sample {name!r} must be a "
                    f"non-negative number, got {m.group('value')}"
                )

        sig = (name, tuple(sorted(labels.items())))
        if sig in seen_samples:
            errors.append(
                f"{where}: duplicate sample {name!r} with labels {labels}"
            )
        seen_samples.add(sig)

        if kind == "histogram" and name == base + "_count":
            key = tuple(sorted(labels.items()))
            f.buckets.setdefault(("__count__", key), []).append(("", value))

    # histogram coherence, per family and label subset
    for name, f in families.items():
        if f.type != "histogram":
            continue
        counts = {
            key[1]: rows[0][1]
            for key, rows in f.buckets.items()
            if isinstance(key, tuple) and key and key[0] == "__count__"
        }
        series = {
            k: v for k, v in f.buckets.items()
            if not (isinstance(k, tuple) and k and k[0] == "__count__")
        }
        if not series and f.samples_seen:
            errors.append(f"family {name!r}: histogram with no buckets")
        for key, rows in series.items():
            les = [le for le, _ in rows]
            if "+Inf" not in les:
                errors.append(
                    f"family {name!r}{dict(key) or ''}: no +Inf bucket"
                )
            # monotone non-decreasing cumulative counts in le order
            def le_val(le: str) -> float:
                v = _parse_value(le)
                return math.inf if v is None else v

            ordered = sorted(rows, key=lambda r: le_val(r[0]))
            vals = [v for _, v in ordered]
            if any(b < a for a, b in zip(vals, vals[1:])):
                errors.append(
                    f"family {name!r}{dict(key) or ''}: bucket counts "
                    "not monotonically non-decreasing"
                )
            if ordered and counts:
                cnt = counts.get(key)
                if cnt is not None and ordered[-1][0] == "+Inf" \
                        and ordered[-1][1] != cnt:
                    errors.append(
                        f"family {name!r}{dict(key) or ''}: _count "
                        f"({cnt}) != +Inf bucket ({ordered[-1][1]})"
                    )
    return errors


def _monotone_samples(text: str) -> dict[tuple, float]:
    """``{(sample_name, ((label, value), ...)): value}`` for every
    sample with counter semantics: TYPE counter families, plus the
    ``_bucket``/``_count``/``_sum`` samples of TYPE histogram families
    (all cumulative; ``_sum`` is monotone because every histogram here
    observes non-negative quantities)."""
    typed: dict[str, str] = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) >= 4:
                typed[parts[2]] = parts[3].strip()
    out: dict[tuple, float] = {}
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name = m.group("name")
        base = _base_family(name, typed)
        kind = typed.get(base)
        monotone = kind == "counter" or (
            kind == "histogram" and name != base
        )
        if not monotone:
            continue
        value = _parse_value(m.group("value"))
        if value is None or math.isnan(value):
            continue
        labels = _parse_labels(m.group("labels") or "", "", []) or {}
        out[(name, tuple(sorted(labels.items())))] = value
    return out


def diff_counters(a_text: str, b_text: str) -> list[str]:
    """Counter-monotonicity regressions from snapshot A to snapshot B
    (A taken first). Empty list = every shared cumulative series is
    non-decreasing."""
    a, b = _monotone_samples(a_text), _monotone_samples(b_text)
    errors = []
    for key in sorted(set(a) & set(b)):
        if b[key] < a[key]:
            name, labels = key
            lab = "{%s}" % ",".join(
                f'{k}="{v}"' for k, v in labels
            ) if labels else ""
            errors.append(
                f"counter regression: {name}{lab} went "
                f"{a[key]:g} -> {b[key]:g}"
            )
    return errors


def _main_diff(path_a: str, path_b: str) -> int:
    with open(path_a) as fh:
        a_text = fh.read()
    with open(path_b) as fh:
        b_text = fh.read()
    rc = 0
    for src, text in ((path_a, a_text), (path_b, b_text)):
        for e in validate(text):
            rc = 1
            print(f"{src}: {e}", file=sys.stderr)
    errs = diff_counters(a_text, b_text)
    for e in errs:
        rc = 1
        print(f"{path_a} -> {path_b}: {e}", file=sys.stderr)
    if rc == 0:
        shared = len(
            set(_monotone_samples(a_text)) & set(_monotone_samples(b_text))
        )
        print(
            f"{path_a} -> {path_b}: diff OK ({shared} cumulative "
            "series monotone)",
            file=sys.stderr,
        )
    return rc


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--diff":
        if len(argv) != 3:
            print("usage: validate_metrics.py --diff A B",
                  file=sys.stderr)
            return 2
        return _main_diff(argv[1], argv[2])
    pages: list[tuple[str, str]] = []
    if argv:
        for path in argv:
            with open(path) as fh:
                pages.append((path, fh.read()))
    else:
        pages.append(("<stdin>", sys.stdin.read()))
    rc = 0
    for src, text in pages:
        errs = validate(text)
        if errs:
            rc = 1
            for e in errs:
                print(f"{src}: {e}", file=sys.stderr)
        else:
            n = sum(
                1 for line in text.splitlines()
                if line and not line.startswith("#")
            )
            print(f"{src}: OK ({n} samples)", file=sys.stderr)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
