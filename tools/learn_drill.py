#!/usr/bin/env python
"""Continual-learning drill — the closed loop live, end to end, with the
whole arc journaled and asserted; writes a LEARN_E2E_*.json artifact.

The continual-learning layer's claim (docs/CONTINUAL.md) is one story:

    When the served population drifts away from the model's training
    reference, the system notices (quality alert), acts (debounced
    trigger -> warm refit on the captured recent cohort), verifies
    (shadow evaluation of the candidate against the live model), and
    recovers (guarded rolling promotion through the fleet deploy rail;
    the rebased quality monitor earns its way back to ok on live
    traffic) — and a candidate that fails its shadow verdict is PARKED
    with the fleet untouched.

This tool is the claim's executable form. It stands up the real stack —
a front-door router with the cohort-capture tap, two real ``cli serve``
replica subprocesses (quality monitoring on, admin deploy endpoint
armed), ONE ``tools/loadgen.py`` client driving cohort traffic for the
whole run — then perturbs the client's cohort mid-run and lets the
``learn`` loop close the loop unattended:

  drift        loadgen ``--perturb`` shifts named variables; the
               replicas' windowed PSI crosses the alert threshold and
               the ok->alert transition is journaled replica-side
  trigger      the ``LearnLoop`` daemon polls ``/debug/quality`` through
               the router's registry, debounces (K consecutive alert
               polls), and fires exactly one journaled ``learn_trigger``
  settle       the loop waits for the capture window to TURN OVER (the
               refit's row budget captured fresh, post-decision) so the
               refit sees only post-drift traffic — a blend profile
               would hold the fleet in alert forever (``learn_settle``)
  retrain      warm-start refit on the captured recent cohort (the
               router's bounded JSONL window), distilled labels,
               published as a versioned candidate checkpoint
  shadow       offline replay of the captured cohort through live AND
               candidate; divergence / flip rate / candidate
               self-quality / disagreement-delta verdict, journaled
  promote      the gate passes -> the candidate is republished into the
               live path and ``POST /fleet/deploy`` rolls it across the
               fleet (replica-side parity probe + lastgood rollback
               untouched underneath)
  recover      each replica's monitor is REBASED to the promoted
               model's own reference profile; the still-perturbed
               traffic now matches it, and the alert->ok transition is
               earned and journaled — the loop is closed
  negative     the superseded v1 checkpoint, evaluated as a candidate
               against the same captured cohort, FAILS its shadow
               verdict (its reference no longer matches live traffic):
               ``learn promote`` refuses, parks it with REFUSED.json,
               and the fleet keeps serving v2 — asserted live
  revert       near the end of the run the drill touches loadgen's
               ``--perturb-revert-file``: the same client ends the
               perturbation and the artifact records the revert index —
               one client drove the whole drift->recovery demo (a
               renewed drift on the reverted cohort would simply be the
               NEXT cycle's work; the drill's cooldown suppresses it)

Every transition must appear in the journals (drill-process journal for
router + learn events, per-replica journals for quality/deploy events),
the traffic log must stay failure-free through the rolling swap, and
the router's /metrics page (fleet_* AND learn_* families, NaN gauges
included) must pass the strict Prometheus validator.

Usage:
    python tools/learn_drill.py --out LEARN_E2E_ci.json \
        --report-out OBS_REPORT_learn.md
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from chaos_drill import _free_port, _spawn_replica, wait_until  # noqa: E402

HARD_TIMEOUT_S = 30.0


def _get_json(base: str, path: str):
    with urllib.request.urlopen(base + path, timeout=HARD_TIMEOUT_S) as r:
        return json.loads(r.read())


def make_live_model(workdir: str, n: int, seed: int):
    """A small jax-fit StackingParams WITH its own training reference
    profile — the live model v1 — plus the training rows as the
    loadgen cohort file (the served population, pre-drift)."""
    import jax.numpy as jnp
    import numpy as np

    from machine_learning_replications_tpu.config import (
        ExperimentConfig, GBDTConfig, SVCConfig,
    )
    from machine_learning_replications_tpu.data import make_cohort
    from machine_learning_replications_tpu.data.schema import (
        SELECTED_17, selected_indices,
    )
    from machine_learning_replications_tpu.models import pipeline as pl
    from machine_learning_replications_tpu.obs import quality

    X64, y, _ = make_cohort(n=n, seed=seed, missing_rate=0.0)
    X17 = np.asarray(X64[:, selected_indices()], np.float64)
    y = np.asarray(y, np.float64)
    cfg = ExperimentConfig(
        gbdt=GBDTConfig(n_estimators=5),
        svc=SVCConfig(platt_cv=2, max_iter=300),
    )
    ens = pl.fit_stacking(X17, y, cfg)
    scores = pl._ensemble_scores(
        ens, X17, chunk_rows=cfg.svc.predict_chunk_rows
    )
    prof = quality.build_reference_profile(X17, scores, y=y)
    live = ens.replace(
        quality={k: jnp.asarray(v) for k, v in prof.items()}
    )
    patients = os.path.join(workdir, "patients.jsonl")
    with open(patients, "w") as f:
        for row in X17:
            f.write(json.dumps(
                {k: float(v) for k, v in zip(SELECTED_17, row)}
            ) + "\n")
    return live, cfg, patients


def run_drill(args) -> int:
    t_start = time.monotonic()
    from machine_learning_replications_tpu.fleet import make_router
    from machine_learning_replications_tpu.learn import (
        capture as capturemod,
    )
    from machine_learning_replications_tpu.learn import promote as promod
    from machine_learning_replications_tpu.learn import shadow as shadowmod
    from machine_learning_replications_tpu.learn.loop import LearnLoop
    from machine_learning_replications_tpu.learn.trigger import (
        TriggerPolicy, poll_quality, replica_urls,
    )
    from machine_learning_replications_tpu.obs import journal
    from machine_learning_replications_tpu.persist import orbax_io

    workdir = tempfile.mkdtemp(prefix="learn_drill_")
    journal_path = args.journal or os.path.join(workdir, "drill.jsonl")
    jrn = journal.RunJournal(journal_path, command="learn_drill")
    journal.set_journal(jrn)

    say = lambda m: print(f"drill: {m}", file=sys.stderr)  # noqa: E731
    say(f"workdir {workdir}")
    ckpt = os.path.join(workdir, "model")
    capture_dir = os.path.join(workdir, "capture")
    candidate_dir = os.path.join(workdir, "candidate")
    neg_dir = os.path.join(workdir, "stale_candidate")
    revert_file = os.path.join(workdir, "revert.now")

    live_v1, cfg, patients = make_live_model(
        workdir, n=args.cohort_rows, seed=7
    )
    orbax_io.save_model(ckpt, live_v1)      # the live path: version 1
    orbax_io.save_model(neg_dir, live_v1)   # the negative-case candidate
    say("live model v1 published (with its own reference profile)")

    router = make_router(
        port=0, probe_interval_s=0.2, request_timeout_s=8.0,
        max_attempts=3, capture_dir=capture_dir,
        capture_rows_per_shard=2048, capture_max_shards=8,
    ).start_background()
    base = f"http://{router.address[0]}:{router.address[1]}"
    ports = {"r1": _free_port(), "r2": _free_port()}
    replica_journals = {
        rid: os.path.join(workdir, f"replica_{rid}.jsonl") for rid in ports
    }
    procs = {
        rid: _spawn_replica(rid, port, ckpt, base, replica_journals[rid])
        for rid, port in ports.items()
    }
    loadgen_art = args.loadgen_out or os.path.join(workdir, "loadgen.json")
    loadgen = None
    arc: dict = {}
    try:
        wait_until(
            lambda: router.registry.ready_count() == 2, 300.0,
            "both replicas registered, warm, and in rotation",
            poll_s=0.5,
        )
        say("fleet ready: 2 replicas in rotation behind the router")

        # ONE client for the whole arc: cohort traffic, a mid-run
        # perturbation, and a file-triggered revert the drill fires
        # after the loop has closed.
        loadgen = subprocess.Popen(
            [sys.executable, os.path.join("tools", "loadgen.py"),
             "--url", base, "--mode", "closed",
             "--concurrency", "4", "--duration", str(args.duration),
             "--patients", patients,
             "--perturb", args.perturb,
             "--perturb-at", "0.02",
             "--perturb-revert-file", revert_file,
             "--out", loadgen_art],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        t_loadgen = time.monotonic()

        # The drill's shadow gate, used for BOTH the promoted candidate
        # and the stale negative case (one gate, not a rigged pair).
        # The divergence-vs-live caps are opened up to demo scale — a
        # correct refit diverges from the stale live model by exactly
        # the drift it repairs (measured here: p95 ~0.37 under the +6
        # one-variable shift), and the distilled-label refit (binarized
        # pseudo-labels; learn/retrain.py) sharpens the score
        # distribution, so score-PSI-vs-live reads ~3.3 even for a good
        # candidate. The load-bearing gates stay at production defaults:
        # decision flips, the candidate's self-quality on its OWN
        # reference profile, and the ensemble-disagreement delta — which
        # is exactly what still refuses the stale candidate below.
        gate = shadowmod.ShadowThresholds(
            max_divergence_mean=0.25,
            max_divergence_p95=0.55,
            max_score_psi=6.0,
        )

        # The closed loop, unattended: poll -> debounce -> fire ->
        # refit -> shadow -> promote -> wait for recovery.
        loop = LearnLoop(
            model_path=ckpt,
            capture_dir=capture_dir,
            candidate_dir=candidate_dir,
            router_url=base,
            policy=TriggerPolicy(
                alert_streak=args.alert_streak, cooldown_s=600.0
            ),
            cfg=cfg,
            thresholds=gate,
            poll_interval_s=1.0,
            max_rows=args.refit_rows,
            min_rows=250,
            recovery_timeout_s=args.recovery_timeout,
            say=lambda m: print(f"learn: {m}", file=sys.stderr),
        )
        cycles = loop.run(max_cycles=1)
        assert len(cycles) == 1, "the loop never fired a cycle"
        cycle = cycles[0]
        assert cycle["outcome"] == "promoted", cycle
        assert cycle["trigger"]["reason"] == "alert", cycle["trigger"]
        assert cycle["recovered"], (
            "fleet quality did not return to ok after the promotion"
        )
        stats = cycle["verdict"]["stats"]
        assert stats["divergence_mean"] > 0.0, (
            "trivial shadow divergence: the refit did not move", stats,
        )
        to_version = cycle["promotion"]["version"]
        say(
            f"cycle closed: v{cycle['from_version']} -> "
            f"v{to_version} promoted, quality recovered"
        )
        snap = router.registry.snapshot()
        assert all(
            r["in_rotation"] and r["version"] == to_version for r in snap
        ), snap
        arc["cycle"] = {
            "outcome": cycle["outcome"],
            "trigger": cycle["trigger"],
            "from_version": cycle["from_version"],
            "to_version": to_version,
            "retrain": cycle["retrain"],
            "shadow": {
                "pass": cycle["verdict"]["pass"],
                "stats": stats,
            },
            "recovered": cycle["recovered"],
            "seconds": cycle["seconds"],
        }

        # Negative case: the SUPERSEDED v1, shadow-evaluated as a
        # candidate on the same captured cohort, must fail (its
        # reference profile no longer matches live traffic) and the
        # gate must park it with the fleet untouched.
        X17, _bad = capturemod.load_recent(
            capture_dir, max_rows=args.refit_rows
        )
        live_now = orbax_io.load_model(ckpt)
        stale = orbax_io.load_model(neg_dir)
        verdict = shadowmod.evaluate(
            live_now, stale, X17,
            thresholds=gate,
            candidate_version=orbax_io.checkpoint_version(neg_dir),
        )
        assert not verdict["pass"], (
            "the stale candidate should fail its shadow verdict",
            verdict,
        )
        refusal = promod.promote(neg_dir, ckpt, base, verdict)
        assert refusal["result"] == "refused", refusal
        assert promod.is_parked(neg_dir), "REFUSED.json missing"
        snap = router.registry.snapshot()
        assert all(
            r["in_rotation"] and r["version"] == to_version for r in snap
        ), ("the refused candidate touched the fleet", snap)
        say(
            "negative case: stale candidate refused "
            f"({'; '.join(verdict['reasons'])[:120]}...), fleet still at "
            f"v{to_version}"
        )
        arc["negative"] = {
            "result": refusal["result"],
            "reasons": verdict["reasons"],
            "fleet_version_after": to_version,
        }

        # End the perturbation under the SAME client, leaving a short
        # tail so the revert lands in the artifact (a renewed drift on
        # the reverted cohort is the next cycle's work — cooldown holds).
        tail_s = 8.0
        wait_s = args.duration - (time.monotonic() - t_loadgen) - tail_s
        if wait_s > 0:
            time.sleep(wait_s)
        with open(revert_file, "w") as f:
            f.write("revert\n")
        say("perturbation revert signalled to the running client")
        loadgen.wait(timeout=args.duration + 120)
        art = json.load(open(loadgen_art))
        assert art["n_err"] == 0, (
            "client saw transport errors through the rolling promotion",
            {k: art[k] for k in ("n_ok", "n_err", "errors")
             if k in art},
        )
        perturb = art["perturb"]
        assert perturb["onset_index"] is not None, perturb
        assert perturb["revert_index"] is not None, (
            "the revert never landed in the client", perturb,
        )
        versions = set(art["fleet"]["versions"])
        assert versions == {"1", str(to_version)}, (
            "client-side version crossover missing", art["fleet"],
        )
        arc["client"] = {
            "n_ok": art["n_ok"], "n_err": art["n_err"],
            "perturb": perturb,
            "versions": art["fleet"]["versions"],
        }

        # Final fleet state, recorded (not asserted: the reverted tail
        # may legitimately begin the NEXT drift story).
        arc["final_quality"] = {
            url: poll_quality(url).get("status")
            for url in replica_urls(base)
        }
        arc["capture"] = _get_json(base, "/healthz")["capture"]

        # Metrics evidence: the fleet_* AND learn_* families on the
        # drill process's router page, strict-validator-clean.
        with urllib.request.urlopen(
            base + "/metrics", timeout=HARD_TIMEOUT_S
        ) as resp:
            page = resp.read().decode()
        for family in ("learn_capture_rows_total", "learn_trigger_total",
                       "learn_retrain_total",
                       "learn_shadow_divergence_mean",
                       "learn_shadow_evaluations_total",
                       "learn_promotions_total", "fleet_deploys_total"):
            assert family in page, f"{family} missing from /metrics"
        from validate_metrics import validate  # noqa: E402

        errs = validate(page)
        assert not errs, f"/metrics failed strict validation: {errs[:5]}"
        if args.metrics_out:
            with open(args.metrics_out, "w") as f:
                f.write(page)
            say(f"router+learn metrics written to {args.metrics_out}")
    finally:
        if loadgen is not None and loadgen.poll() is None:
            loadgen.kill()
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs.values():
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
        router.shutdown()
        journal.set_journal(None)
        jrn.close()

    # Journal evidence: the one joined story, across processes.
    drill_kinds = set()
    with open(journal_path) as f:
        for line in f:
            drill_kinds.add(json.loads(line).get("kind"))
    for needed in ("learn_trigger", "learn_settle", "learn_retrain_start",
                   "learn_retrain_done", "learn_shadow_verdict",
                   "learn_promotion", "learn_candidate_published",
                   "learn_recovery", "learn_cycle_done",
                   "fleet_deploy_start", "fleet_deploy_replica",
                   "fleet_deploy_done"):
        assert needed in drill_kinds, f"drill journal lacks {needed!r}"
    replica_events: list[dict] = []
    for path in replica_journals.values():
        if os.path.exists(path):
            with open(path) as f:
                replica_events.extend(json.loads(line) for line in f)
    replica_kinds = {e.get("kind") for e in replica_events}
    for needed in ("quality_status", "deploy_start", "deploy_applied",
                   "quality_rebased"):
        assert needed in replica_kinds, (
            f"replica journals lack {needed!r} ({sorted(replica_kinds)})"
        )
    # The replica-side transitions must tell drift AND recovery: an
    # ok->... decline into alert before the deploy, and a ...->ok
    # recovery after the rebase.
    trans = [e for e in replica_events if e.get("kind") == "quality_status"]
    assert any(e["to_status"] == "alert" for e in trans), trans
    rebase_ts = min(
        e["ts"] for e in replica_events if e.get("kind") == "quality_rebased"
    )
    recoveries = [
        e for e in trans
        if e["to_status"] == "ok" and e["ts"] > rebase_ts
    ]
    assert recoveries, (
        "no replica journaled an ...->ok recovery after its monitor "
        "was rebased", trans,
    )
    arc["journal"] = {
        "drill_kinds": sorted(k for k in drill_kinds if k),
        "replica_kinds": sorted(k for k in replica_kinds if k),
        "quality_transitions": [
            {k: e.get(k) for k in
             ("ts", "from_status", "to_status", "worst_feature",
              "worst_psi")}
            for e in sorted(trans, key=lambda e: e["ts"])
        ],
    }

    artifact = {
        "kind": "learn_drill",
        "manifest": journal.run_manifest(command="learn_drill"),
        "invariant": {
            "statement": "drift on the served cohort closes the loop "
            "unattended: journaled alert -> debounced trigger -> warm "
            "refit on the captured cohort -> shadow verdict -> rolling "
            "promotion -> rebased quality earns ok on live traffic; a "
            "shadow-failing candidate is parked with the fleet "
            "untouched; the one driving client sees zero errors",
            "holds": True,
        },
        "config": {
            "duration_s": args.duration, "perturb": args.perturb,
            "cohort_rows": args.cohort_rows,
            "refit_rows": args.refit_rows,
            "alert_streak": args.alert_streak,
        },
        "arc": arc,
        "duration_s": round(time.monotonic() - t_start, 3),
    }
    line = json.dumps(artifact, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        say(f"artifact written to {args.out}")

    if args.report_out:
        cmd = [sys.executable, os.path.join("tools", "obs_report.py"),
               "--learn", "--journal", journal_path]
        for path in replica_journals.values():
            if os.path.exists(path):
                cmd += ["--journal", path]
        cmd += ["--bench", loadgen_art, "--out", args.report_out]
        subprocess.run(cmd, check=True)
        say(f"continual-learning report written to {args.report_out}")
    say(
        "continual loop closed: "
        f"v{arc['cycle']['from_version']} -> v{arc['cycle']['to_version']} "
        f"in {arc['cycle']['seconds']}s, recovery journaled, stale "
        "candidate parked, client error-free"
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n\n")[0],
    )
    ap.add_argument("--out", help="artifact JSON path")
    ap.add_argument("--metrics-out", help="save the final /metrics page")
    ap.add_argument(
        "--report-out",
        help="also render tools/obs_report.py --learn to this path",
    )
    ap.add_argument(
        "--loadgen-out", help="where the driving loadgen artifact lands",
    )
    ap.add_argument("--journal", help="drill journal path")
    ap.add_argument(
        "--duration", type=float, default=300.0,
        help="the one client's total run (must cover the whole arc)",
    )
    ap.add_argument(
        "--perturb", default="Max_Wall_Thick+6",
        help="loadgen perturbation spec driving the drift (the default "
        "is a one-variable unit-style shift: strong enough to alert "
        "(live PSI ~1.7), mild enough that the refit stays a "
        "recalibration — zero decision flips — under the drill's "
        "demo-scale shadow gate)",
    )
    ap.add_argument(
        "--cohort-rows", type=int, default=400,
        help="training cohort size for the live v1 model",
    )
    ap.add_argument(
        "--refit-rows", type=int, default=1000,
        help="max captured rows fed to the refit/shadow",
    )
    ap.add_argument(
        "--alert-streak", type=int, default=2,
        help="trigger debounce: consecutive alert polls before firing",
    )
    ap.add_argument(
        "--recovery-timeout", type=float, default=180.0,
        help="bound on the post-promotion wait for fleet quality ok",
    )
    args = ap.parse_args(argv)
    return run_drill(args)


if __name__ == "__main__":
    sys.exit(main())
