#!/usr/bin/env python
"""One observability report from a run's journal, metrics, and request
traces — the artifact a perf investigation (or a future perf PR) cites.

The pieces exist separately: the JSONL run journal says what ran, the
``/metrics`` page says what the counters did, ``/debug/requests`` holds
the tail-sampled per-request phase breakdowns, and a loadgen
``SERVE_BENCH_*.json`` artifact holds the *client's* view with the
server-echoed request ids of its worst requests. This tool joins them
into one human-readable report: run provenance, traffic and latency,
compile/transfer accounting, SLO burn, the slowest sampled requests with
their phase attribution, and — when a bench artifact is given — the
client/server join: each worst-latency request id looked up in the
sampled traces, so "the client saw 480 ms" gets an answer like "430 ms of
it was queue wait behind a cold-bucket flush".

Sources (mix live and file freely; stdlib only):

  --url URL        live server: fetches /healthz, /metrics?format=json,
                   /debug/requests, /debug/quality
  --journal PATH   JSONL run journal (manifest + events)
  --metrics PATH   a saved /metrics?format=json snapshot
  --requests PATH  a saved /debug/requests snapshot
  --quality PATH   a saved /debug/quality snapshot (the "Model quality"
                   section: drift status, worst features, calibration,
                   journaled status transitions)
  --bench PATH     a loadgen SERVE_BENCH_*.json artifact (enables the join)
  --score          render the "Bulk scoring" section: the cli score run's
                   journal (score_resume / score_chunk / score_done), the
                   cohort-level quality snapshot (--quality then points at
                   the run's quality.json), and --score-bench for the
                   SCORE_BENCH_*.json sequential-vs-overlapped cells
  --score-bench PATH  a tools/score_bench.py artifact
  --fleet          render the "Fleet" section instead of the serving
                   sections: the router's replica table, the journal's
                   registration/rotation/deploy arc, and the fleet_*
                   routing counters — --url then points at the ROUTER
                   (fetches /healthz, /metrics?format=json,
                   /fleet/replicas, /debug/requests), or join a saved
                   --metrics snapshot with the router's --journal.
                   When the journal set carries autoscaler/lifecycle
                   events, an "Elastic fleet" section joins autoscale
                   decisions, spawn/ready/drain/kill/respawn arcs, and
                   rotation changes into one timeline (``--journal`` is
                   repeatable — daemon, router, and replica journals
                   merge by timestamp)
  --learn          render the "Continual learning" section
                   (docs/CONTINUAL.md): trigger decisions, refit stage
                   timings, the shadow verdict, the promotion/deploy
                   arc, and the bracketing quality_status transitions.
                   ``--journal`` is repeatable — the arc spans the
                   router's, the replicas', and the learn daemon's
                   journals, merged by timestamp; ``--bench`` joins the
                   driving loadgen run's perturbation onset/revert.
                   Composes with --fleet (arc first, fleet detail after)
  --out PATH       write the report there (default: stdout)

Example:
  python tools/loadgen.py --url http://127.0.0.1:8000 --mode closed \\
      --duration 10 --out SERVE_BENCH.json
  python tools/obs_report.py --url http://127.0.0.1:8000 \\
      --bench SERVE_BENCH.json --out OBS_REPORT.md
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import urllib.error
import urllib.request


def _fetch_json(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def _load_json(path: str):
    with open(path) as f:
        return json.load(f)


def _read_journal(path: str) -> tuple[dict | None, list[dict]]:
    manifest, events = None, []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("kind") == "manifest":
                manifest = rec
            else:
                events.append(rec)
    return manifest, events


def _ms(v) -> str:
    return "-" if v is None else f"{1000.0 * v:.1f} ms"


def _fmt(v, nd: int = 3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


class Report:
    def __init__(self) -> None:
        self.lines: list[str] = []

    def h(self, title: str) -> None:
        if self.lines:
            self.lines.append("")
        self.lines += [f"## {title}", ""]

    def kv(self, key: str, value) -> None:
        self.lines.append(f"- {key}: {value}")

    def row(self, *cells) -> None:
        self.lines.append("| " + " | ".join(str(c) for c in cells) + " |")

    def table(self, header: tuple, rows: list[tuple]) -> None:
        self.row(*header)
        self.row(*["---"] * len(header))
        for r in rows:
            self.row(*r)

    def text(self) -> str:
        return "\n".join(["# Observability report", ""] + self.lines) + "\n"


def _section_run(rep: Report, manifest: dict | None, health: dict | None):
    rep.h("Run")
    if manifest is None and health is None:
        rep.kv("provenance", "unavailable (no --journal / --url)")
        return
    if manifest is not None:
        rep.kv("run id", manifest.get("run_id"))
        rep.kv("command", manifest.get("command"))
        rep.kv("started", manifest.get("ts"))
        sha = manifest.get("git_sha")
        if sha:
            rep.kv("git", sha[:12] + (
                " (dirty)" if manifest.get("git_dirty") else ""
            ))
        versions = manifest.get("versions") or {}
        if versions:
            rep.kv("versions", ", ".join(
                f"{k}={v}" for k, v in versions.items() if v
            ))
        if manifest.get("config_hash"):
            rep.kv("config hash", manifest["config_hash"][:12])
    if health is not None:
        rep.kv("params family", health.get("params"))
        rep.kv("bucket ladder", health.get("buckets"))
        rep.kv("warm", health.get("warm"))
        rep.kv("uptime", _fmt(health.get("uptime_seconds"), 1) + " s")
        if manifest is None and health.get("run_id"):
            rep.kv("run id", health["run_id"])


def _section_traffic(rep: Report, metrics: dict | None):
    rep.h("Traffic")
    if metrics is None:
        rep.kv("metrics", "unavailable (no --metrics / --url)")
        return
    for key in ("requests_total", "shed_total", "errors_total",
                "timeouts_total", "batches_total", "queue_depth"):
        rep.kv(key, metrics.get(key))
    lat = metrics.get("latency_seconds") or {}
    rep.kv("latency p50 / p95 / p99", " / ".join(
        _ms(lat.get(q)) for q in ("p50", "p95", "p99")
    ))
    qw = metrics.get("queue_wait_seconds") or {}
    if qw.get("count"):
        rep.kv(
            "queue wait mean",
            _ms(qw["sum"] / qw["count"]) + f" over {qw['count']} requests",
        )
    batch = metrics.get("batch_size") or {}
    if batch.get("count"):
        rep.kv("mean flushed batch", _fmt(batch["sum"] / batch["count"], 1)
               + " rows")


def _section_runtime(rep: Report, runtime: dict | None):
    rep.h("Runtime (XLA accounting)")
    if not runtime:
        rep.kv("runtime", "unavailable")
        return
    for key in ("jax_compiles_total", "jax_compile_seconds_total",
                "jax_trace_seconds_total"):
        if key in runtime:
            rep.kv(key, _fmt(runtime[key]))
    transfers = runtime.get("jax_transfer_bytes_total")
    if isinstance(transfers, dict):
        for labels, v in sorted(transfers.items()):
            rep.kv(f"transfer bytes ({labels})", v)
    captures = runtime.get("profile_captures_total")
    if isinstance(captures, dict) and captures:
        rep.kv("profile captures", ", ".join(
            f"{k}={v}" for k, v in sorted(captures.items())
        ))


def _section_slo(rep: Report, slos: list | None):
    rep.h("SLO")
    if not slos:
        rep.kv("slo", "none declared (or snapshot unavailable)")
        return
    rep.table(
        ("slo", "target", "requests", "bad", "window good",
         "burn rate", "budget left"),
        [
            (
                s.get("name"), _fmt(s.get("target")),
                s.get("requests_total"), s.get("bad_total"),
                _fmt(s.get("window_good_ratio"), 4),
                _fmt(s.get("burn_rate"), 2),
                _fmt(s.get("error_budget_remaining_ratio"), 3),
            )
            for s in slos
        ],
    )


def _section_quality(
    rep: Report, quality: dict | None, events: list[dict],
    bench: dict | None, n_worst: int = 5,
):
    rep.h("Model quality")
    if quality is None:
        rep.kv("quality", "unavailable (no --quality / --url)")
        return
    if not quality.get("enabled", False):
        rep.kv("quality", f"disabled ({quality.get('reason', 'no reason given')})")
        return
    rep.kv("drift status", quality.get("status"))
    th = quality.get("thresholds") or {}
    rep.kv(
        "thresholds",
        f"warn PSI >= {th.get('warn_psi')}, alert PSI >= {th.get('alert_psi')}",
    )
    rep.kv(
        "window", f"{quality.get('window_rows')} rows "
        f"(of {quality.get('rows_total')} observed; "
        f"min {quality.get('min_rows')} to judge)",
    )
    rep.kv("score-distribution PSI", _fmt(quality.get("score_psi"), 4))
    rep.kv(
        "member disagreement (windowed mean pairwise)",
        _fmt(quality.get("member_disagreement"), 4),
    )
    ref = quality.get("reference") or {}
    if ref:
        rep.kv(
            "reference profile",
            f"{ref.get('n_rows')} training rows, "
            f"{ref.get('feature_bins')} feature bins",
        )
    perturb = (bench or {}).get("perturb")
    if perturb:
        rep.kv(
            "bench perturbation",
            f"{perturb.get('spec')} from request "
            f"{perturb.get('onset_index')} "
            f"({perturb.get('onset_time_s')} s into the run)",
        )
    features = quality.get("features") or []
    if features:
        rep.lines.append("")
        rep.table(
            ("feature", "PSI", "binned KS", "window mean", "training mean"),
            [
                (
                    f.get("name"), _fmt(f.get("psi"), 4),
                    _fmt(f.get("ks"), 4),
                    _fmt(f.get("window_mean_binned"), 3),
                    _fmt(f.get("reference_mean"), 3),
                )
                for f in features[:n_worst]
            ],
        )
    transitions = [e for e in events if e.get("kind") == "quality_status"]
    if transitions:
        rep.lines.append("")
        rep.table(
            ("when", "transition", "worst feature", "PSI", "window rows"),
            [
                (
                    e.get("ts"),
                    f"{e.get('from_status')} → {e.get('to_status')}",
                    e.get("worst_feature"), _fmt(e.get("worst_psi"), 4),
                    e.get("window_rows"),
                )
                for e in transitions
            ],
        )


def _section_score(
    rep: Report, events: list[dict], quality: dict | None,
    score_bench: dict | None,
):
    """The "Bulk scoring" section: one `cli score` run's story — journal
    digest (resume provenance, chunk cadence, end-to-end rows/s), the
    cohort-level quality snapshot, and the sequential-vs-overlapped bench
    cells — in the same shape as the r9 serving quality section."""
    rep.h("Bulk scoring")
    done = next(
        (e for e in reversed(events) if e.get("kind") == "score_done"), None
    )
    resumes = [e for e in events if e.get("kind") == "score_resume"]
    chunks = [e for e in events if e.get("kind") == "score_chunk"]
    if done is None and not chunks and score_bench is None:
        rep.kv("bulk scoring", "unavailable (no score journal / "
               "--score-bench)")
        return
    if done is not None:
        rep.kv(
            "scored",
            f"{done.get('rows')} rows in {done.get('chunks')} chunks "
            f"({done.get('bad_rows')} quarantined)",
        )
        rep.kv("end-to-end rate", f"{done.get('rows_per_second')} rows/s "
               f"over {done.get('wall_seconds')} s")
        sha = done.get("output_sha256")
        if sha:
            rep.kv("output sha256", sha[:16] + "…")
    if resumes:
        for e in resumes:
            rep.kv(
                "resume", f"re-entered at chunk {e.get('chunks')} "
                f"({e.get('rows')} rows already committed) at {e.get('ts')}",
            )
    elif done is not None:
        rep.kv("resume", "none (uninterrupted run)")
    if chunks:
        secs = [e["seconds"] for e in chunks if e.get("seconds") is not None]
        if secs:
            rep.kv(
                "chunk cadence",
                f"{len(chunks)} journaled commits, "
                f"{min(secs) * 1e3:.0f}–{max(secs) * 1e3:.0f} ms "
                f"(mean {sum(secs) / len(secs) * 1e3:.0f} ms)",
            )
    if quality is not None and quality.get("enabled", True) and (
        quality.get("rows_total") is not None
    ):
        rep.kv(
            "cohort quality",
            f"{quality.get('status')} over {quality.get('rows_total')} "
            f"scored rows (score PSI {_fmt(quality.get('score_psi'), 4)})",
        )
        worst = (quality.get("features") or [{}])[0]
        if worst.get("name"):
            rep.kv(
                "worst feature",
                f"{worst['name']} PSI {_fmt(worst.get('psi'), 4)}",
            )
    if score_bench is not None:
        rep.lines.append("")
        rows = []
        for leg in ("sequential", "overlapped"):
            cell = score_bench.get(leg) or {}
            stage = cell.get("stage_seconds") or {}
            rows.append((
                leg, cell.get("rows"), cell.get("rows_per_second"),
                _fmt(cell.get("wall_seconds"), 1),
                ", ".join(f"{k} {v}" for k, v in stage.items()) or "-",
            ))
        rep.table(
            ("mode", "rows", "rows/s", "wall s", "stage busy seconds"),
            rows,
        )
        rep.kv("overlap speedup", f"{score_bench.get('overlap_speedup')}x")
        rep.kv(
            "outputs identical",
            score_bench.get("outputs_identical"),
        )
        resume = score_bench.get("resume")
        if resume:
            rep.kv(
                "kill+resume",
                f"SIGKILL after {resume.get('killed_after_chunks')} chunks "
                f"→ resumed at {resume.get('resumed_chunks')} → output "
                + ("byte-identical"
                   if resume.get("identical_to_uninterrupted")
                   else "DIFFERS"),
            )
        digest = (score_bench.get("manifest") or {}).get("run_id")
        if digest:
            rep.kv("bench manifest run id", digest)


def _section_fleet(
    rep: Report, replicas: list | None, runtime: dict | None,
    events: list[dict],
):
    """The "Fleet" section: the router's rotation table joined with the
    journal's registration/rotation/deploy arc and the fleet_* routing
    counters — one place that answers "what did the fleet do" after a
    drill or a rollout (docs/FLEET.md)."""
    rep.h("Fleet")
    if replicas is None and runtime is None and not events:
        rep.kv("fleet", "unavailable (no --url / --metrics / --journal)")
        return
    if replicas:
        rep.table(
            ("replica", "state", "in rotation", "version", "url"),
            [
                (
                    r.get("id"), r.get("reason") and
                    f"{r.get('state')} ({r.get('reason')})" or
                    r.get("state"),
                    r.get("in_rotation"), r.get("version"), r.get("url"),
                )
                for r in replicas
            ],
        )
        rep.lines.append("")
        # The balancer's own view (docs/FLEET.md "Router data plane"):
        # the load signals least-loaded picking scores on, per replica —
        # operators debug rotation skew from the same numbers the
        # router picks with.
        loads = [
            (r.get("id"), r.get("load"))
            for r in replicas if isinstance(r.get("load"), dict)
        ]
        if loads:
            rep.table(
                ("replica", "ewma latency (ms)", "outstanding",
                 "queue depth", "pick score"),
                [
                    (
                        rid,
                        "-" if ld.get("ewma_latency_ms") is None
                        else f"{ld['ewma_latency_ms']:.3f}",
                        ld.get("outstanding"),
                        "-" if ld.get("last_queue_depth") is None
                        else ld.get("last_queue_depth"),
                        ld.get("score"),
                    )
                    for rid, ld in loads
                ],
            )
            rep.lines.append("")
    runtime = runtime or {}
    outcomes = runtime.get("fleet_requests_total")
    if isinstance(outcomes, dict):
        rep.kv("routed requests", ", ".join(
            f"{k.split('=', 1)[1]}={v}" for k, v in sorted(outcomes.items())
            if v
        ) or "none")
    retries = runtime.get("fleet_retries_total")
    if isinstance(retries, dict) and any(retries.values()):
        rep.kv("retries", ", ".join(
            f"{k.split('=', 1)[1]}={v}" for k, v in sorted(retries.items())
            if v
        ))
    hedges = runtime.get("fleet_hedges_total")
    if hedges:
        rep.kv(
            "hedges",
            f"{hedges} fired, {runtime.get('fleet_hedge_wins_total', 0)} won",
        )
    lat = runtime.get("fleet_request_latency_seconds")
    if isinstance(lat, dict) and lat.get("count"):
        rep.kv(
            "router latency mean",
            _ms(lat["sum"] / lat["count"]) + f" over {lat['count']} requests",
        )
    probes = runtime.get("fleet_probe_total")
    if isinstance(probes, dict) and any(probes.values()):
        rep.kv("probes", ", ".join(
            f"{k.split('=', 1)[1]}={v}" for k, v in sorted(probes.items())
            if v
        ))
    per_replica = runtime.get("fleet_replica_requests_total")
    if isinstance(per_replica, dict) and per_replica:
        rep.kv("per-replica attempts", ", ".join(
            f"{k}={v}" for k, v in sorted(per_replica.items()) if v
        ))
    conns = runtime.get("fleet_upstream_connections_total")
    if isinstance(conns, dict) and any(conns.values()):
        # opened ≈ replica count means keep-alive held across the run;
        # opened ≈ request count means it did not.
        rep.kv("upstream connections", ", ".join(
            f"{k.split('=', 1)[1]}={v}" for k, v in sorted(conns.items())
            if v
        ))
    registrations = [
        e for e in events if e.get("kind") == "fleet_replica_registered"
    ]
    if registrations:
        rep.kv("registrations", ", ".join(
            f"{e.get('replica')} at {e.get('ts')}" for e in registrations
        ))
    rotations = [e for e in events if e.get("kind") == "fleet_rotation"]
    if rotations:
        rep.lines.append("")
        rep.table(
            ("when", "replica", "rotation", "reason", "version"),
            [
                (
                    e.get("ts"), e.get("replica"), e.get("direction"),
                    e.get("reason"), e.get("version"),
                )
                for e in rotations
            ],
        )
    deploys = [
        e for e in events
        if e.get("kind") in ("fleet_deploy_start", "fleet_deploy_replica",
                             "fleet_deploy_done")
    ]
    if deploys:
        rep.lines.append("")
        rows = []
        for e in deploys:
            if e["kind"] == "fleet_deploy_start":
                what = (
                    f"start → version {e.get('target_version')} "
                    f"over {len(e.get('replicas') or [])} replicas"
                )
            elif e["kind"] == "fleet_deploy_replica":
                what = (
                    f"replica {e.get('replica')}: {e.get('result')} "
                    f"(version {e.get('achieved_version')}"
                    + (", ROLLED BACK" if e.get("rolled_back") else "")
                    + ")"
                )
            else:
                what = (
                    f"done: {e.get('result')}"
                    + (f" — {e.get('error')}" if e.get("error") else "")
                )
            rows.append((e.get("ts"), e.get("model"), what))
        rep.table(("when", "model", "deploy arc"), rows)


def _summ_signals(signals: dict | None) -> str:
    if not isinstance(signals, dict):
        return "-"
    return ", ".join(
        f"{k}={v}" for k, v in signals.items() if v is not None
    ) or "-"


def _section_autoscale(rep: Report, events: list[dict]):
    """The "Elastic fleet" section: autoscaler decisions, lifecycle arcs
    (spawn/ready/drain/term/kill/exit/crash), and router rotation
    changes joined into ONE timeline across the daemon, router, and
    replica journals — the answer to "what did the fleet's size do, and
    why, at t" after a surge drill (docs/FLEET.md "Elastic fleet")."""
    decisions = [e for e in events if e.get("kind") == "autoscale_decision"]
    lifecycle = [
        e for e in events if (e.get("kind") or "").startswith("lifecycle_")
    ]
    if not decisions and not lifecycle:
        return
    rep.h("Elastic fleet")
    fired = [e for e in decisions if e.get("decision")]
    rep.kv(
        "autoscale decisions",
        f"{len(fired)} fired, {len(decisions) - len(fired)} suppressed "
        "(journaled)",
    )
    if fired:
        rep.lines.append("")
        rep.table(
            ("when", "decision", "fleet", "reason", "signals"),
            [
                (
                    e.get("ts"), e.get("decision"),
                    f"{e.get('desired')} → {e.get('target')} "
                    f"(ready {e.get('ready')})",
                    e.get("reason"), _summ_signals(e.get("signals")),
                )
                for e in fired
            ],
        )
    rotations = [e for e in events if e.get("kind") == "fleet_rotation"]
    timeline = sorted(
        decisions + lifecycle + rotations, key=lambda e: e.get("ts") or ""
    )
    if timeline:
        rep.lines.append("")
        rows = []
        for e in timeline:
            kind = e.get("kind")
            if kind == "autoscale_decision":
                source = "autoscaler"
                what = (
                    f"{e.get('decision')} → {e.get('target')} replicas "
                    f"({e.get('reason')})"
                    if e.get("decision") else
                    f"suppressed by {e.get('suppressed_by')} "
                    f"({e.get('reason')})"
                )
            elif kind == "fleet_rotation":
                source = "router"
                what = (
                    f"{e.get('replica')} rotated {e.get('direction')} "
                    f"({e.get('reason')})"
                )
            else:
                source = "lifecycle"
                detail = e.get("reason") or e.get("detail") or \
                    e.get("error") or ""
                what = kind.replace("lifecycle_", "") + \
                    f": {e.get('replica')}" + (f" ({detail})" if detail
                                               else "")
                if kind == "lifecycle_ready":
                    what += f" in {e.get('seconds')}s"
                if kind == "lifecycle_exit" and e.get("code") is not None:
                    what += f" exit {e.get('code')}"
            rows.append((e.get("ts"), source, what))
        rep.table(("when", "source", "event"), rows)


def _section_learn(rep: Report, events: list[dict], bench: dict | None):
    """The "Continual learning" section: the closed loop's one joined
    story (docs/CONTINUAL.md) — trigger decisions, the refit's stage
    timings, the shadow verdict, the promotion/deploy arc, and the
    quality transitions that bracket it (ok→alert before, alert→ok
    after), optionally joined against the driving loadgen artifact's
    perturbation onset/revert."""
    rep.h("Continual learning")
    if not events:
        rep.kv("continual learning", "unavailable (no --journal)")
        return

    perturb = (bench or {}).get("perturb")
    if perturb:
        rep.kv(
            "driving perturbation",
            f"{perturb.get('spec')} (onset {perturb.get('onset_time_s')}s"
            + (
                f", reverted {perturb.get('revert_time_s')}s"
                if perturb.get("revert_time_s") is not None else ""
            )
            + ")",
        )

    transitions = [e for e in events if e.get("kind") == "quality_status"]
    if transitions:
        rep.table(
            ("when", "transition", "worst feature", "psi", "window rows"),
            [
                (
                    e.get("ts"),
                    f"{e.get('from_status')} → {e.get('to_status')}",
                    e.get("worst_feature"), _fmt(e.get("worst_psi")),
                    e.get("window_rows"),
                )
                for e in transitions
            ],
        )
        rep.lines.append("")

    triggers = [e for e in events if e.get("kind") == "learn_trigger"]
    if triggers:
        rep.table(
            ("when", "decision", "reason", "streak", "worst feature",
             "psi"),
            [
                (
                    e.get("ts"),
                    "FIRED" if e.get("fired")
                    else f"suppressed ({e.get('suppressed_by')})",
                    e.get("reason"),
                    f"{e.get('streak')}/{e.get('alert_streak_needed')}",
                    e.get("worst_feature"), _fmt(e.get("worst_psi")),
                )
                for e in triggers
            ],
        )
        rep.lines.append("")

    retrain_done = [
        e for e in events
        if e.get("kind") in ("learn_retrain_done", "learn_retrain_failed")
    ]
    for e in retrain_done:
        if e["kind"] == "learn_retrain_done":
            rep.kv(
                "refit",
                f"{e.get('rows')} rows ({e.get('labels_source')} labels) "
                f"→ {e.get('family')} candidate v{e.get('version')} "
                f"in {e.get('seconds')}s",
            )
        else:
            rep.kv(
                "refit FAILED",
                f"{e.get('error')} after {e.get('seconds')}s",
            )
    starts = [e for e in events if e.get("kind") == "learn_retrain_start"]
    if starts:
        # Stage timings between the first retrain_start and its end mark
        # — the StageCheckpointer arc the refit rides.
        t0 = starts[0].get("ts") or ""
        ends = sorted(e.get("ts") or "" for e in retrain_done)
        t1 = ends[0] if ends else None
        stages = [
            e for e in events
            if e.get("kind") == "stage_done"
            and t0 <= (e.get("ts") or "")
            and (t1 is None or (e.get("ts") or "") <= t1)
        ]
        if stages:
            rep.table(
                ("refit stage", "seconds"),
                [(e.get("stage"), _fmt(e.get("seconds"))) for e in stages],
            )
            rep.lines.append("")

    verdicts = [e for e in events if e.get("kind") == "learn_shadow_verdict"]
    for e in verdicts:
        rep.kv(
            "shadow verdict",
            ("PASS" if e.get("passed") else "FAIL")
            + f" (candidate v{e.get('candidate_version')}, "
            f"{e.get('rows')} replay rows)",
        )
        rep.kv(
            "  divergence",
            f"mean {_fmt(e.get('divergence_mean'))}, "
            f"p95 {_fmt(e.get('divergence_p95'))}, "
            f"max {_fmt(e.get('divergence_max'))}, "
            f"flip rate {_fmt(e.get('flip_rate'))}, "
            f"score PSI {_fmt(e.get('score_psi'))}",
        )
        cq = e.get("candidate_quality")
        if cq:
            rep.kv(
                "  candidate self-quality",
                f"{cq.get('status')} (worst PSI {_fmt(cq.get('worst_psi'))} "
                f"over {cq.get('rows')} rows)",
            )
        if e.get("reasons"):
            rep.kv("  refusal reasons", "; ".join(e["reasons"]))

    promotions = [e for e in events if e.get("kind") == "learn_promotion"]
    for e in promotions:
        detail = f"candidate {e.get('candidate')}"
        if e.get("version") is not None:
            detail += f" → live v{e.get('version')}"
        if e.get("reasons"):
            detail += f" — {'; '.join(e['reasons'])}"
        if e.get("deploy_error"):
            detail += f" — {e['deploy_error']}"
        rep.kv(f"promotion {e.get('result')}", detail)

    deploys = [
        e for e in events
        if e.get("kind") in ("fleet_deploy_start", "fleet_deploy_replica",
                             "fleet_deploy_done")
    ]
    if deploys:
        rep.lines.append("")
        rows = []
        for e in deploys:
            if e["kind"] == "fleet_deploy_start":
                what = (
                    f"start → version {e.get('target_version')} "
                    f"over {len(e.get('replicas') or [])} replicas"
                )
            elif e["kind"] == "fleet_deploy_replica":
                what = (
                    f"replica {e.get('replica')}: {e.get('result')} "
                    f"(version {e.get('achieved_version')}"
                    + (", ROLLED BACK" if e.get("rolled_back") else "")
                    + ")"
                )
            else:
                what = (
                    f"done: {e.get('result')}"
                    + (f" — {e.get('error')}" if e.get("error") else "")
                )
            rows.append((e.get("ts"), what))
        rep.table(("when", "deploy arc"), rows)
        rep.lines.append("")

    rebases = [e for e in events if e.get("kind") == "quality_rebased"]
    for e in rebases:
        rep.kv(
            "quality rebased",
            f"{e.get('ts')}: monitor adopted the promoted model's "
            f"reference ({e.get('reference_rows')} training rows)",
        )
    recoveries = [e for e in events if e.get("kind") == "learn_recovery"]
    for e in recoveries:
        rep.kv(
            "recovery",
            ("quality returned to ok" if e.get("recovered")
             else "quality did NOT recover in time")
            + f" ({e.get('ts')})",
        )
    cycles = [e for e in events if e.get("kind") == "learn_cycle_done"]
    for e in cycles:
        rep.kv(
            "cycle",
            f"{e.get('outcome')} (v{e.get('from_version')} → "
            f"v{e.get('to_version')}) in {e.get('seconds')}s",
        )
    if not any((triggers, retrain_done, verdicts, promotions, cycles)):
        rep.kv("continual learning", "no learn_* events in the journal")


def _phase_summary(trace: dict) -> str:
    phases = trace.get("phases") or {}
    parts = []
    for name in ("parse", "queue_wait", "batch_assembly",
                 "device_compute", "host_compute", "upstream", "respond"):
        if name in phases:
            parts.append(f"{name} {_ms(phases[name].get('seconds'))}")
    extra = []
    if trace.get("cold_compile"):
        extra.append("COLD COMPILE")
    if trace.get("bucket") is not None:
        extra.append(f"bucket {trace['bucket']}")
    if trace.get("batch_rows") is not None:
        extra.append(f"{trace['batch_rows']} rows")
    tail = f"  [{', '.join(extra)}]" if extra else ""
    return ", ".join(parts) + tail


def _section_tail(rep: Report, requests: dict | None, n: int = 10):
    rep.h("Tail-sampled requests (slowest first)")
    if requests is None:
        rep.kv("traces", "unavailable (no --requests / --url)")
        return
    stats = requests.get("stats") or {}
    rep.kv(
        "recorder",
        f"{stats.get('kept_total')} kept / {stats.get('dropped_total')} "
        f"dropped (tail threshold "
        f"{_ms(stats.get('tail_threshold_seconds'))})",
    )
    rep.lines.append("")
    samples = sorted(
        requests.get("requests") or [],
        key=lambda t: t.get("total_seconds") or 0.0, reverse=True,
    )[:n]
    if not samples:
        rep.kv("traces", "none sampled yet")
        return
    rep.table(
        ("request id", "status", "total", "phase breakdown"),
        [
            (
                t.get("request_id"), t.get("status"),
                _ms(t.get("total_seconds")), _phase_summary(t),
            )
            for t in samples
        ],
    )


def _section_journal(rep: Report, events: list[dict]):
    rep.h("Journal digest")
    if not events:
        rep.kv("events", "none")
        return
    stages = [e for e in events if e["kind"] == "stage_done"]
    flushes = [e for e in events if e["kind"] == "flush"]
    cold = [e for e in flushes if e.get("cold_compile")]
    captures = [e for e in events if e["kind"] == "profile_capture"]
    done = [e for e in events if e["kind"] in ("run_done", "run_error")]
    rep.kv("events", len(events))
    if stages:
        rep.kv("stages", ", ".join(
            f"{e['stage']} {_fmt(e.get('seconds'), 1)}s" for e in stages
        ))
    if flushes:
        rows = sum(e.get("rows", 0) for e in flushes)
        rep.kv("flushes", f"{len(flushes)} ({rows} rows, "
               f"{len(cold)} cold-compile)")
    if captures:
        rep.kv("profile captures", len(captures))
    for e in done:
        rep.kv(e["kind"], {
            k: v for k, v in e.items() if k not in ("kind", "ts")
        })


def _section_join(rep: Report, bench: dict | None, requests: dict | None):
    if bench is None:
        return
    rep.h("Bench join (client worst requests vs server traces)")
    rep.kv("bench mode", bench.get("mode"))
    rep.kv("achieved qps", bench.get("achieved_qps"))
    lat = bench.get("latency_ms") or {}
    rep.kv("client latency p50 / p95 / p99", " / ".join(
        f"{lat.get(q)} ms" if lat.get(q) is not None else "-"
        for q in ("p50", "p95", "p99")
    ))
    overhead = bench.get("router_overhead_ms")
    if isinstance(overhead, dict):
        # The --baseline-url A/B join (docs/FLEET.md "Router data
        # plane"): through-router vs direct-replica, interleaved in one
        # run — the router-added latency as measured, not inferred.
        base = bench.get("baseline") or {}
        base_lat = base.get("latency_ms") or {}
        rep.kv(
            "direct-replica baseline",
            f"{base.get('url')} — {base.get('achieved_qps')} qps, p50 "
            f"{base_lat.get('p50')} ms over {base.get('n_ok')} ok",
        )
        rep.kv(
            "router-added overhead",
            f"p50 {overhead.get('p50')} ms / p99 {overhead.get('p99')} ms"
            f" / mean {overhead.get('mean')} ms (interleaved, "
            f"{overhead.get('segments_per_target')} segments per target)",
        )
    worst = bench.get("worst_requests") or []
    if not worst:
        rep.kv("worst_requests", "absent (pre-join loadgen artifact?)")
        return
    by_id = {
        t.get("request_id"): t
        for t in (requests or {}).get("requests") or []
    }
    rep.lines.append("")
    rows = []
    for w in worst:
        trace = by_id.get(w.get("request_id"))
        rows.append((
            w.get("request_id"), w.get("status"),
            f"{w.get('latency_ms')} ms",
            _phase_summary(trace) if trace else
            "not sampled (below tail threshold, or evicted)",
        ))
    rep.table(
        ("request id", "client status", "client latency", "server phases"),
        rows,
    )


def _section_fleet_telemetry(
    rep: Report, trace: dict | None, fleet_page: str | None,
):
    """The "Fleet telemetry" section (docs/OBSERVABILITY.md): the
    cross-process joined timeline's accounting (join results per
    tail-sampled request, offset-corrected containment, the live clock
    offsets) and the aggregated /fleet/metrics page's scrape-health and
    fleet-SLO lines — the evidence that the fleet-scoped surfaces were
    produced by a real multi-process run, not assembled by hand."""
    if trace is None and fleet_page is None:
        return
    rep.h("Fleet telemetry")
    if trace is not None:
        other = trace.get("otherData") or {}
        results = other.get("results") or {}
        containment = other.get("containment") or {}
        joined = other.get("joined")
        n = other.get("requests")
        rep.kv(
            "cross-process join",
            f"{joined}/{n} tail-sampled router requests joined with "
            "their replica-side phases",
        )
        misses = {k: v for k, v in results.items()
                  if k != "joined" and v}
        rep.kv(
            "join misses",
            ", ".join(f"{k}={v}" for k, v in sorted(misses.items()))
            or "none",
        )
        rep.kv(
            "offset-corrected containment",
            f"{containment.get('contained')}/{joined} replica spans "
            f"inside their upstream span (ratio "
            f"{containment.get('ratio')}, slack "
            f"{containment.get('slack_ms')} ms, worst excess "
            f"{containment.get('worst_excess_ms')} ms)",
        )
        offsets = other.get("clock_offsets") or {}
        if offsets:
            rep.table(
                ("replica", "offset (ms)", "probe rtt (ms)", "samples"),
                [(rid, o.get("offset_ms"), o.get("rtt_ms"),
                  o.get("samples"))
                 for rid, o in sorted(offsets.items())],
            )
    if fleet_page is not None:
        wanted = ("fleet_scrape_stale", "fleet_slo_good_ratio",
                  "fleet_slo_burn_rate",
                  "fleet_slo_error_budget_remaining_ratio")
        lines = [
            ln for ln in fleet_page.splitlines()
            if ln.startswith(wanted)
        ]
        rep.kv(
            "aggregated /fleet/metrics",
            f"{sum(1 for ln in fleet_page.splitlines() if ln.startswith('# TYPE'))} "
            "families on one strict-validator-clean page",
        )
        if lines:
            rep.lines.append("")
            rep.lines.append("```")
            rep.lines.extend(lines)
            rep.lines.append("```")


def _section_static_analysis(rep: Report, gc: dict | None):
    """The last graftcheck run (docs/ANALYSIS.md), from its --json-out
    artifact: rules run, live findings, baseline debt and its oldest
    expiry — the repo-contract health alongside the runtime story."""
    if gc is None:
        return
    rep.h("Static analysis")
    rep.kv("rules run", ", ".join(gc.get("rules_run", [])) or "none")
    rep.kv("files scanned", gc.get("files_scanned"))
    verdict = "FAILED" if gc.get("failed") else "clean"
    rep.kv("verdict", f"{verdict} ({'strict' if gc.get('strict') else 'report-only'} mode)")
    rep.kv("suppressed (annotated call sites)", gc.get("suppressed"))
    findings = gc.get("findings") or []
    expired = gc.get("expired") or []
    stale = gc.get("unused_baseline") or []
    if findings or expired:
        rep.table(
            ("rule", "location", "finding"),
            [(f["rule"], f"{f['path']}:{f['line']}", f["message"])
             for f in findings]
            + [(e["rule"], f"{e['path']}:{e['line']}",
                f"BASELINE EXPIRED {e['expires']}: {e['message']}")
               for e in expired],
        )
    baselined = gc.get("baselined") or []
    if baselined:
        oldest = min(b["expires"] for b in baselined)
        rep.kv(
            "baseline debt",
            f"{len(baselined)} grandfathered finding(s), oldest expiry "
            f"{oldest}",
        )
    else:
        rep.kv("baseline debt", "none")
    if stale:
        rep.kv(
            "stale baseline entries",
            "; ".join(f"{e['rule']}:{e['path']}" for e in stale)
            + " — remove them",
        )


def _section_coldstart(rep: Report, cs: dict | None):
    """The "Cold start" section (docs/AOT.md): a coldstart_bench
    artifact's replica cold-start-to-ready and rolling-deploy hold,
    traced vs AOT-restored, with the contract verdicts (bit-identical
    outputs, zero fallbacks) the speedup is worthless without."""
    if cs is None:
        return
    rep.h("Cold start")
    cfg = cs.get("config") or {}
    rep.kv("ladder", cfg.get("buckets"))
    rep.kv("repeats per mode", cfg.get("repeats"))
    rep.kv("publish with AOT bundle", f"{cs.get('publish_with_aot_s')} s")
    rows = []
    for arc, key, unit in (
        ("cold start → ready", "cold_start", "best_ready_s"),
        ("deploy hold", "deploy_hold", "best_hold_s"),
    ):
        block = cs.get(key) or {}
        traced = block.get("traced") or {}
        aot = block.get("aot") or {}
        rows.append((
            arc,
            f"{traced.get(unit)} s "
            f"(range {'–'.join(map(str, traced.get('range_s', [])))})",
            f"{aot.get(unit)} s "
            f"(range {'–'.join(map(str, aot.get('range_s', [])))})",
            f"{block.get('speedup_best')}×",
            f"{block.get('saved_s_best')} s",
        ))
    rep.table(
        ("arc", "traced (best-of)", "AOT (best-of)", "speedup", "saved"),
        rows,
    )
    contracts = cs.get("contracts") or {}
    rep.kv(
        "contracts",
        ", ".join(
            f"{k}={'yes' if v else 'NO'}" for k, v in contracts.items()
        ) or "none recorded",
    )
    gauges = ((cs.get("cold_start") or {}).get("aot") or {}).get(
        "warmup_gauges"
    ) or {}
    restore = gauges.get("serve_aot_restore_seconds") or {}
    if restore:
        def _bucket_key(labels: str) -> tuple:
            # Numeric bucket order, not lexicographic (128 after 64,
            # not after 1); path label breaks ties.
            m = re.search(r'bucket="(\d+)"', labels)
            return (int(m.group(1)) if m else 1 << 30, labels)

        rep.table(
            ("bucket", "restore_s"),
            [(labels, f"{restore[labels]:.4f}")
             for labels in sorted(restore, key=_bucket_key)],
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--url", help="live server base URL")
    ap.add_argument(
        "--journal", action="append",
        help="JSONL run journal path (repeatable — the continual-learning "
        "arc spans router, replica, and learn-daemon journals; events "
        "merge sorted by timestamp)",
    )
    ap.add_argument("--metrics", help="saved /metrics?format=json snapshot")
    ap.add_argument("--requests", help="saved /debug/requests snapshot")
    ap.add_argument("--quality", help="saved /debug/quality snapshot")
    ap.add_argument("--bench", help="loadgen SERVE_BENCH_*.json artifact")
    ap.add_argument(
        "--score", action="store_true",
        help="render the 'Bulk scoring' section (joins the score journal, "
        "the cohort quality.json via --quality, and --score-bench)",
    )
    ap.add_argument(
        "--score-bench", help="tools/score_bench.py SCORE_BENCH_*.json "
        "artifact",
    )
    ap.add_argument(
        "--fleet", action="store_true",
        help="render the 'Fleet' section (router replica table + "
        "journal registration/rotation/deploy arc + fleet_* counters); "
        "--url then points at the router",
    )
    ap.add_argument(
        "--fleet-trace",
        help="a /fleet/trace export (chaos_drill --fleet-trace-out): "
        "renders the 'Fleet telemetry' join/containment accounting",
    )
    ap.add_argument(
        "--fleet-metrics",
        help="an aggregated /fleet/metrics page (chaos_drill "
        "--fleet-metrics-out): renders its scrape-health and fleet-SLO "
        "lines in the 'Fleet telemetry' section",
    )
    ap.add_argument(
        "--learn", action="store_true",
        help="render the 'Continual learning' section (trigger decisions "
        "+ refit stage timings + shadow verdict + promotion/deploy arc + "
        "the bracketing quality transitions, joined from the --journal "
        "set; --bench joins the driving loadgen perturbation)",
    )
    ap.add_argument(
        "--graftcheck",
        help="a tools/graftcheck.py --json-out artifact: renders the "
        "'Static analysis' section (rules run, findings, baseline debt "
        "+ oldest expiry)",
    )
    ap.add_argument(
        "--coldstart",
        help="a tools/coldstart_bench.py COLDSTART_*.json artifact: "
        "renders the 'Cold start' section (replica ready time + deploy "
        "hold, traced vs AOT, with the parity contract verdicts)",
    )
    ap.add_argument("--tail", type=int, default=10,
                    help="slowest sampled traces to show")
    ap.add_argument("--out", help="report path (default: stdout)")
    args = ap.parse_args(argv)
    if not (args.url or args.journal or args.metrics or args.requests
            or args.quality or args.score_bench or args.graftcheck
            or args.coldstart or args.fleet_trace or args.fleet_metrics):
        ap.error("nothing to report on: give --url and/or input files")

    health = metrics = requests = quality = fleet_replicas = None
    if args.url:
        base = args.url.rstrip("/")
        metrics = _fetch_json(base + "/metrics?format=json")
        if args.fleet:
            # --url is the ROUTER: its health/debug surface differs from
            # a replica's (no /debug/quality, a registry instead of an
            # engine), so fetch the fleet-specific endpoints.
            fleet_replicas = _fetch_json(
                base + "/fleet/replicas"
            ).get("replicas")
            requests = _fetch_json(base + "/debug/requests?n=1000000")
        else:
            health = _fetch_json(base + "/healthz")
            # Ask for everything the recorder holds (its ring caps the
            # count): the endpoint's n=64 default would silently drop the
            # very samples the Bench join needs.
            requests = _fetch_json(base + "/debug/requests?n=1000000")
            try:
                quality = _fetch_json(base + "/debug/quality")
            except urllib.error.HTTPError:
                quality = None  # pre-quality server: section unavailable
    if args.metrics:
        metrics = _load_json(args.metrics)
    if args.requests:
        requests = _load_json(args.requests)
    if args.quality:
        quality = _load_json(args.quality)
    manifest, events = None, []
    for jpath in args.journal or []:
        m, ev = _read_journal(jpath)
        manifest = manifest or m
        events.extend(ev)
    if len(args.journal or []) > 1:
        events.sort(key=lambda e: e.get("ts") or "")
    bench = _load_json(args.bench) if args.bench else None
    score_bench = _load_json(args.score_bench) if args.score_bench else None
    fleet_trace = _load_json(args.fleet_trace) if args.fleet_trace else None
    fleet_page = None
    if args.fleet_metrics:
        with open(args.fleet_metrics) as f:
            fleet_page = f.read()

    rep = Report()
    _section_run(rep, manifest, health)
    _section_static_analysis(
        rep, _load_json(args.graftcheck) if args.graftcheck else None
    )
    _section_coldstart(
        rep, _load_json(args.coldstart) if args.coldstart else None
    )
    if args.learn:
        # The continual-learning arc leads; the fleet/serving sections
        # below (if requested) then detail the machinery it rode.
        _section_learn(rep, events, bench)
    if args.fleet:
        # The fleet section replaces the replica-side serving sections:
        # a router has rotation state and routing counters, not an
        # engine's traffic/SLO/quality story.
        if fleet_replicas is None and isinstance(metrics, dict):
            fleet_replicas = metrics.get("replicas")
        if fleet_replicas is None and isinstance(bench, dict):
            # A fleet_bench artifact carries the registry snapshot (with
            # the per-replica load signals) taken at the end of its run
            # — the offline stand-in for a live /fleet/replicas.
            fleet_replicas = (bench.get("fleet_bench") or {}).get(
                "registry"
            )
        _section_fleet(
            rep, fleet_replicas, (metrics or {}).get("runtime"), events,
        )
        _section_fleet_telemetry(rep, fleet_trace, fleet_page)
        # The elastic-fleet timeline (autoscaler + lifecycle + rotation
        # events joined) renders whenever the journal set carries it.
        _section_autoscale(rep, events)
        # A router bench artifact joins here too: achieved qps, the
        # --baseline-url overhead deltas, and the worst-request trace
        # join against the ROUTER's own flight recorder.
        _section_join(rep, bench, requests)
        _section_tail(rep, requests, n=args.tail)
        if args.journal:
            _section_journal(rep, events)
    elif args.score or score_bench is not None:
        # Bulk-scoring runs have no serving traffic/SLO story: the score
        # section replaces them, reusing --journal and --quality (pointed
        # at the run's quality.json).
        _section_score(rep, events, quality, score_bench)
        if args.journal:
            _section_journal(rep, events)
    elif args.learn:
        # Learn-only report (journals + bench, no live serving surface):
        # the arc plus the raw journal, nothing replica-specific.
        if args.journal:
            _section_journal(rep, events)
    else:
        _section_traffic(rep, metrics)
        _section_runtime(rep, (metrics or {}).get("runtime"))
        slos = (requests or {}).get("slo")
        _section_slo(rep, slos)
        _section_quality(rep, quality, events, bench)
        _section_fleet_telemetry(rep, fleet_trace, fleet_page)
        _section_tail(rep, requests, n=args.tail)
        if args.journal:
            _section_journal(rep, events)
        _section_join(rep, bench, requests)

    text = rep.text()
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"report written to {args.out}", file=sys.stderr)
    else:
        print(text, end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
