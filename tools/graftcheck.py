#!/usr/bin/env python
"""graftcheck — run the repo's invariant checker (docs/ANALYSIS.md).

The CI gate::

    python tools/graftcheck.py --strict

Exit codes: 0 clean (live findings may exist only in non-strict report
mode), 1 violations (non-baselined findings, expired baseline entries,
or stale baseline entries matching nothing), 2 usage/configuration
errors (unparseable baseline, unknown rule name).

Useful flags::

    --rules import-purity,monotonic-clock   run a subset
    --json-out PATH   machine-readable report (tools/obs_report.py
                      renders it as the "Static analysis" section)
    --baseline PATH   override analysis/baseline.json
    --root PATH       check a different tree (the fixture tests do)

Suppressions and the expiring baseline are documented in
docs/ANALYSIS.md; every suppression names its rule at the site, and
every baseline entry carries a reason and an expiry date that turns it
back into a failure when stale.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from analysis.core import Baseline, BaselineError, run_rules  # noqa: E402
from analysis.project import baseline_path, default_project  # noqa: E402
from analysis.rules import ALL_RULES  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--root", default=None, help="tree to check "
                    "(default: this repository)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any live finding, expired baseline "
                    "entry, or stale baseline entry (the CI mode)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: analysis/baseline.json)")
    ap.add_argument("--json-out", default=None,
                    help="write the machine-readable report here")
    ap.add_argument("--today", default=None,
                    help="override today's date (YYYY-MM-DD; baseline-"
                    "expiry tests)")
    args = ap.parse_args(argv)

    project = default_project(args.root)
    rules = list(ALL_RULES)
    if args.rules:
        wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
        by_id = {r.RULE_ID: r for r in ALL_RULES}
        unknown = [w for w in wanted if w not in by_id]
        if unknown:
            print(f"graftcheck: unknown rule(s) {unknown}; known: "
                  f"{sorted(by_id)}", file=sys.stderr)
            return 2
        rules = [by_id[w] for w in wanted]

    baseline_file = args.baseline or baseline_path(args.root)
    try:
        baseline = Baseline.load(baseline_file)
    except (BaselineError, json.JSONDecodeError) as exc:
        print(f"graftcheck: bad baseline: {exc}", file=sys.stderr)
        return 2
    today = (
        datetime.date.fromisoformat(args.today) if args.today else None
    )

    report = run_rules(project, rules, baseline=baseline, today=today)

    for f in report.findings:
        print(f"{f.location()}: [{f.rule}] {f.message}")
    for f, e in report.expired:
        print(f"{f.location()}: [{f.rule}] BASELINE EXPIRED "
              f"{e['expires']} ({e['reason']}): {f.message}")
    for e in report.unused_baseline:
        print(f"{baseline_file}: [{e['rule']}] stale baseline "
              f"entry for {e['path']} matches nothing — remove it")
    n_live = len(report.findings)
    n_exp = len(report.expired)
    n_stale = len(report.unused_baseline)
    print(
        f"graftcheck: {len(report.rules_run)} rules over "
        f"{report.files_scanned} files — {n_live} finding(s), "
        f"{len(report.baselined)} baselined, {n_exp} expired, "
        f"{n_stale} stale baseline entr(y/ies), "
        f"{report.suppressed_count} suppressed"
    )
    if report.baselined:
        oldest = min(e["expires"] for _, e in report.baselined)
        print(f"graftcheck: baseline debt: {len(report.baselined)} "
              f"grandfathered finding(s), oldest expiry {oldest}")

    if args.json_out:
        payload = report.to_json()
        payload["strict"] = bool(args.strict)
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")

    if report.failed():
        return 1 if args.strict else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
