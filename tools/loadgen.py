#!/usr/bin/env python
"""Load generator for the serving layer — writes a SERVE_BENCH_*.json artifact.

Drives a running ``python -m machine_learning_replications_tpu serve``
instance over HTTP (stdlib urllib + threads, no dependencies) in either of
the two canonical load models:

  closed loop   --concurrency N workers (alias: --connections N), each
                firing its next request the moment the previous reply
                lands — measures sustainable throughput at a fixed
                multiprogramming level. Every worker holds ONE persistent
                keep-alive connection and reuses it across requests (no
                per-request TCP handshake in the measured latency); the
                artifact's ``connections`` block records how well reuse
                held up (connections opened vs requests sent,
                reconnects). This is the high-concurrency mode the
                event-loop transport is benched with — 1000 workers is
                1000 parked sockets on the server, not 1000 threads.
  open loop     --qps R with a global schedule of send times — measures
                behavior under an *offered* rate the server cannot slow
                down, which is what exposes admission-control shedding
                (closed loops self-throttle and hide it). One fresh
                connection per request by construction.

Every request POSTs a 17-variable patient JSON (the ``predict_hf.py:5-27``
example by default, ``--patient`` for a file, ``--patients`` for a JSONL
cohort cycled round-robin — drift monitoring needs *distributed* traffic,
a single repeated patient is a point mass no reference profile matches)
and is counted as ok (HTTP 200), shed (503, the batcher's explicit
overload reply), or error. The artifact records offered/achieved qps,
ok/shed/error counts, shed rate, and ok-latency quantiles — the serving
counterpart of BENCH_*.json.

``--retries N`` makes each worker a *patient* client: a 503 shed is
retried up to N times under capped exponential backoff with jitter,
honoring the server's ``Retry-After`` (the degraded-mode contract —
docs/RESILIENCE.md). Retry counts and give-ups land in the artifact, so a
chaos bench can state client-visible impact as "K sheds absorbed by
retry, M abandoned" instead of a raw shed rate.

``--perturb SPEC`` exercises the server's model-quality monitoring
(``obs.quality``, ``/debug/quality``) end-to-end: from ``--perturb-at``
(fraction of the run, default 0.5) onward, every outgoing patient has the
named variables shifted/scaled — e.g.
``--perturb 'Ejection_Fraction*0.6,Max_Wall_Thick+8'`` — simulating the
upstream unit-conversion bug or cohort shift the drift monitor exists to
catch. The artifact records the spec, the onset request index, and the
onset time, so a ``/debug/quality`` snapshot or journal
``quality_status`` transition can be joined against exactly when the
distribution moved.

The perturbation can also *end* mid-run — ``--perturb-until FRAC``
reverts it at a run fraction, and ``--perturb-revert-file PATH`` reverts
it the moment PATH appears on disk (polled cheaply, ≤4 stats/s). The
revert index/time land in the artifact next to the onset. This is the
continual-learning demo's client (docs/CONTINUAL.md): ONE loadgen run
drives drift → alert → retrain → promote, the demo driver touches the
revert file after the rolling promotion, and the same client's traffic
then proves the promoted model reads the recovered cohort as ``ok``.

Against a fleet (the front-door router or a single identity-carrying
replica — docs/FLEET.md), the echoed ``X-Replica`` / ``X-Model-Version``
headers are tallied into the artifact's ``fleet`` block: ok replies per
replica and per checkpoint version with first/last-seen run offsets —
the zero-downtime rolling-deploy proof reads straight out of one loadgen
artifact (old version last seen at t, new version first seen ≈ t, ok
counts on both sides).

``--baseline-url URL`` measures *router-added overhead* in one artifact:
the run is split into ``2 × --baseline-segments`` alternating slices —
through-router (``--url``), direct-replica (``--baseline-url``), repeat —
so both targets see the same host, the same thermal/noise environment,
and the same client, interleaved in time rather than back to back.
The artifact's primary numbers are the router side; a ``baseline`` block
carries the direct side, and ``router_overhead_ms`` states the p50/p99/
mean deltas as first-class fields — the "≤1 ms added p50" claim becomes
machine-checkable instead of a hand-joined pair of runs
(docs/FLEET.md "Router data plane").

The server echoes (or assigns) an ``X-Request-Id`` on every reply; the
worst-latency request ids land in the artifact (``worst_requests``), so a
bench artifact can be joined against the server's ``/debug/requests``
tail samples — client-measured latency on one side, the server's
per-phase attribution of the same request on the other
(``tools/obs_report.py`` does the join).

Example:
  python tools/loadgen.py --url http://127.0.0.1:8000 \\
      --mode closed --concurrency 8 --duration 10 \\
      --out SERVE_BENCH_r06_cpu.json
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import random
import re
import socket
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

_PERTURB_TERM_RE = re.compile(
    r"^\s*(?P<name>.*?)\s*(?P<op>[*+\-=])\s*"
    r"(?P<val>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)\s*$"
)


def parse_perturb(spec: str) -> list[tuple[str, str, float]]:
    """``NAME*FACTOR`` / ``NAME+DELTA`` / ``NAME-DELTA`` / ``NAME=VALUE``
    terms, comma separated. Variable names may contain spaces
    ("Obstructive HCM"); operands are non-negative literals (use ``-`` to
    subtract rather than adding a negative)."""
    ops = []
    for term in spec.split(","):
        m = _PERTURB_TERM_RE.match(term)
        if not m or not m.group("name"):
            raise ValueError(
                f"bad perturb term {term.strip()!r}: expected "
                "NAME*FACTOR, NAME+DELTA, NAME-DELTA, or NAME=VALUE"
            )
        ops.append((m.group("name"), m.group("op"), float(m.group("val"))))
    return ops


def apply_perturb(
    patient: dict, ops: list[tuple[str, str, float]]
) -> dict:
    out = dict(patient)
    for name, op, val in ops:
        v = out[name]
        out[name] = (
            v * val if op == "*" else v + val if op == "+"
            else v - val if op == "-" else val
        )
    return out


class _Bodies:
    """Per-request POST bodies: the patient cohort cycled round-robin,
    with the perturbation switched on mid-run — and optionally back OFF
    (``until_frac`` run fraction, or the appearance of ``revert_file`` on
    disk, whichever comes first). ``arm(t0)`` fixes the onset clock when
    the load loop starts; the first request issued at or after onset (and
    the first after revert) records its index for the artifact."""

    #: Seconds between ``revert_file`` stat() checks — an os.stat per
    #: request would tax the client at four-digit qps for a signal that
    #: only has to land within a fraction of a second.
    REVERT_POLL_S = 0.25

    def __init__(self, patients: list[dict], perturb_ops, onset_frac,
                 duration: float, until_frac: float | None = None,
                 revert_file: str | None = None) -> None:
        self.patients = patients
        self.ops = perturb_ops
        self.onset_frac = onset_frac
        self.until_frac = until_frac
        self.revert_file = revert_file
        self.duration = duration
        self.onset_at: float | None = None  # monotonic; None = no perturb
        self.onset_index: int | None = None
        self.onset_time_s: float | None = None
        self.revert_at: float | None = None  # monotonic; None = no revert
        self.revert_index: int | None = None
        self.revert_time_s: float | None = None
        self._reverted = False
        self._next_file_check = 0.0
        self._t0 = 0.0
        self._lock = threading.Lock()
        self._i = 0
        if self.ops:
            missing = [
                name for name, _, _ in self.ops
                if any(name not in p for p in patients)
            ]
            if missing:
                raise ValueError(
                    f"perturb names not in every patient: {missing}"
                )

    def arm(self, t0: float) -> None:
        self._t0 = t0
        if self.ops:
            self.onset_at = t0 + self.onset_frac * self.duration
            if self.until_frac is not None:
                self.revert_at = t0 + self.until_frac * self.duration

    def _revert_due_locked(self, now: float) -> bool:
        if self._reverted:
            return True
        if self.revert_at is not None and now >= self.revert_at:
            return True
        if self.revert_file is not None and now >= self._next_file_check:
            self._next_file_check = now + self.REVERT_POLL_S
            return os.path.exists(self.revert_file)
        return False

    def next_body(self) -> bytes:
        now = time.monotonic()
        with self._lock:
            i = self._i
            self._i += 1
            active = self.onset_at is not None and now >= self.onset_at
            if active and self.onset_index is None:
                self.onset_index = i
                self.onset_time_s = now - self._t0
            # Revert is checked only once the perturbation is live: a
            # revert signal can't pre-empt an onset that hasn't happened.
            if active and self._revert_due_locked(now):
                if not self._reverted:
                    self._reverted = True
                    self.revert_index = i
                    self.revert_time_s = now - self._t0
                active = False
        p = self.patients[i % len(self.patients)]
        if active:
            p = apply_perturb(p, self.ops)
        return json.dumps(p).encode()

    def describe(self) -> dict | None:
        if not self.ops:
            return None
        return {
            "spec": ",".join(
                f"{name}{op}{val:g}" for name, op, val in self.ops
            ),
            "at_fraction": self.onset_frac,
            "onset_index": self.onset_index,
            "onset_time_s": (
                None if self.onset_time_s is None
                else round(self.onset_time_s, 3)
            ),
            "until_fraction": self.until_frac,
            "revert_file": self.revert_file,
            "revert_index": self.revert_index,
            "revert_time_s": (
                None if self.revert_time_s is None
                else round(self.revert_time_s, 3)
            ),
        }


class _RateSchedule:
    """A per-connection request-rate schedule for the paced event-loop
    client — the one-client surge→quiet arc (``--ramp``). Parsed from
    ``T:RATE`` points (seconds into the run : requests/s per
    connection), e.g. ``0:1,15:6,75:1`` = 1 rps/conn for 15 s, burst at
    6 rps/conn until 75 s, quiet tail after. ``shape``:

      step      the rate jumps at each point and holds (default)
      linear    the rate interpolates between consecutive points

    Offered qps at time t = connections × ``rate_at(t)``. The schedule
    (and the per-phase offered rates) land in the artifact so a journal
    or metrics timeline can be joined against exactly when the load
    moved."""

    def __init__(self, points: list[tuple[float, float]],
                 shape: str = "step") -> None:
        if shape not in ("step", "linear"):
            raise ValueError(f"ramp shape must be step|linear, got {shape!r}")
        if not points:
            raise ValueError("ramp needs at least one T:RATE point")
        for (t0, _), (t1, _) in zip(points, points[1:]):
            if t1 <= t0:
                raise ValueError(
                    f"ramp times must be strictly ascending ({t0} then {t1})"
                )
        for t, rate in points:
            if t < 0 or rate <= 0:
                raise ValueError(
                    f"ramp points need t >= 0 and rate > 0, got {t}:{rate}"
                )
        self.points = list(points)
        self.shape = shape

    @classmethod
    def parse(cls, spec: str, shape: str = "step") -> "_RateSchedule":
        points = []
        for term in spec.split(","):
            t_s, sep, rate_s = term.strip().partition(":")
            if not sep:
                raise ValueError(
                    f"bad ramp term {term.strip()!r}: expected T:RATE"
                )
            points.append((float(t_s), float(rate_s)))
        return cls(points, shape=shape)

    def rate_at(self, t: float) -> float:
        pts = self.points
        if t <= pts[0][0]:
            return pts[0][1]
        if self.shape == "step":
            rate = pts[0][1]
            for pt, prate in pts:
                if t >= pt:
                    rate = prate
                else:
                    break
            return rate
        for (t0, r0), (t1, r1) in zip(pts, pts[1:]):
            if t <= t1:
                return r0 + (r1 - r0) * (t - t0) / (t1 - t0)
        return pts[-1][1]

    def describe(self, connections: int) -> dict:
        return {
            "spec": ",".join(f"{t:g}:{r:g}" for t, r in self.points),
            "shape": self.shape,
            "points": [
                {
                    "t_s": t, "rate_per_conn": r,
                    "offered_qps": round(connections * r, 1),
                }
                for t, r in self.points
            ],
        }


def _percentiles(xs: list[float], qs=(50, 95, 99)) -> dict[str, float | None]:
    if not xs:
        # None → JSON null: a bare NaN token would make the artifact
        # unparseable to strict JSON consumers.
        return {f"p{q}": None for q in qs} | {"mean": None, "max": None}
    xs = sorted(xs)
    out = {}
    for q in qs:
        # nearest-rank on the sorted sample (no numpy: tools stay stdlib)
        i = min(len(xs) - 1, max(0, round(q / 100 * (len(xs) - 1))))
        out[f"p{q}"] = xs[i]
    out["mean"] = sum(xs) / len(xs)
    out["max"] = xs[-1]
    return out


class _Tally:
    def __init__(self, n_worst: int = 10) -> None:
        self.lock = threading.Lock()
        self.ok_latency_ms: list[float] = []
        self.n_ok = 0
        self.n_shed = 0
        self.n_err = 0
        self.n_retries = 0   # 503 replies retried after backoff
        self.n_gaveup = 0    # logical requests still shed after max retries
        self.n_worst = n_worst
        # Per-path ok counts from the echoed X-Serve-Path header (the
        # dual-path router's split — "unknown" covers pre-dual-path
        # servers that echo nothing).
        self.paths: dict[str, int] = {}
        # Per-path ok latencies, so the artifact can state the host-path
        # p50 next to the device-path p50 in one run.
        self.path_latency_ms: dict[str, list[float]] = {}
        # Fleet identity off the echoed X-Replica / X-Model-Version
        # headers (docs/FLEET.md): ok counts per replica, per version
        # (with first/last-seen run offsets — the rolling-deploy
        # crossover read straight out of the artifact), and the
        # replica × version matrix.
        self.t0 = 0.0  # armed by the run loops; offsets are run-relative
        self.replicas: dict[str, int] = {}
        self.versions: dict[str, dict] = {}
        self.replica_versions: dict[str, dict[str, int]] = {}
        # (latency_ms, request_id, status, replica, version, path) for
        # every id-carrying reply; reduced to the n_worst slowest at
        # artifact time. One tuple per request is fine for bench
        # durations (minutes, not days).
        self.ided: list[
            tuple[float, str, str, str | None, str | None, str | None]
        ] = []

    def record(
        self, status: str, latency_ms: float, request_id: str | None = None,
        path: str | None = None, replica: str | None = None,
        version: str | None = None,
    ) -> None:
        now_s = time.monotonic() - self.t0
        with self.lock:
            if status == "ok":
                self.n_ok += 1
                self.ok_latency_ms.append(latency_ms)
                key = path or "unknown"
                self.paths[key] = self.paths.get(key, 0) + 1
                self.path_latency_ms.setdefault(key, []).append(latency_ms)
                if replica:
                    self.replicas[replica] = \
                        self.replicas.get(replica, 0) + 1
                if version:
                    v = self.versions.setdefault(version, {
                        "n": 0, "first_s": now_s, "last_s": now_s,
                    })
                    v["n"] += 1
                    v["first_s"] = min(v["first_s"], now_s)
                    v["last_s"] = max(v["last_s"], now_s)
                if replica and version:
                    by = self.replica_versions.setdefault(replica, {})
                    by[version] = by.get(version, 0) + 1
            elif status == "shed":
                self.n_shed += 1
            else:
                self.n_err += 1
            if request_id:
                self.ided.append(
                    (latency_ms, request_id, status, replica, version, path)
                )

    def fleet_block(self) -> dict | None:
        """The artifact's ``fleet`` block: ok-reply distribution over the
        replicas and checkpoint versions that answered (echoed
        ``X-Replica`` / ``X-Model-Version`` headers). The per-version
        first/last-seen offsets are the zero-downtime rolling-deploy
        proof: old version last seen at t, new version first seen at t'
        ≈ t, ok counts on both sides, nothing lost between. None against
        a server that predates the fleet tier."""
        with self.lock:
            if not self.replicas and not self.versions:
                return None
            return {
                "source": "reply_headers",
                "replicas": dict(sorted(self.replicas.items())),
                "versions": {
                    k: {
                        "n": v["n"],
                        "first_s": round(v["first_s"], 3),
                        "last_s": round(v["last_s"], 3),
                    }
                    for k, v in sorted(self.versions.items())
                },
                "by_replica_version": {
                    r: dict(sorted(vs.items()))
                    for r, vs in sorted(self.replica_versions.items())
                },
            }

    def paths_block(self) -> dict | None:
        """The artifact's ``paths`` block: ok-reply counts and latency
        quantiles per scoring path. None when no reply carried the
        header (a pre-dual-path server)."""
        with self.lock:
            if set(self.paths) <= {"unknown"}:
                return None
            return {
                "source": "reply_header",
                "counts": dict(sorted(self.paths.items())),
                "latency_ms": {
                    k: {
                        q: None if v is None else round(v, 3)
                        for q, v in _percentiles(xs).items()
                    }
                    for k, xs in sorted(self.path_latency_ms.items())
                },
            }

    def worst_requests(self) -> list[dict]:
        """The slowest server-identified requests — the join keys against
        the server's /debug/requests tail samples. Each entry carries the
        per-reply identity echoes (``X-Replica`` / ``X-Model-Version`` /
        ``X-Serve-Path``, None when the server predates them) so a
        client-observed tail request keys directly into the fleet trace:
        which replica served it, on which checkpoint, via which engine."""
        with self.lock:
            # Key on latency alone: trailing tuple fields may be None,
            # and a latency tie must not compare them.
            worst = sorted(
                self.ided, key=lambda t: t[0], reverse=True,
            )[: self.n_worst]
        return [
            {
                "request_id": rid, "status": status,
                "latency_ms": round(ms, 3),
                "replica": replica, "model_version": version,
                "serve_path": path,
            }
            for ms, rid, status, replica, version, path in worst
        ]


class _RetryPolicy:
    """503-shed retry: capped exponential backoff with jitter, honoring the
    server's ``Retry-After``. Only explicit sheds retry — a 500/504 is a
    served answer about THIS request, and blind re-sends of those would
    double-count against a degraded server. Chaos benches use this to
    quantify client-visible impact: how many sheds a patient client rides
    out (``retries``) vs abandons (``give_ups``)."""

    def __init__(self, retries: int = 0, base_ms: float = 100.0,
                 cap_ms: float = 5000.0, seed: int = 0) -> None:
        self.retries = int(retries)
        self.base_ms = float(base_ms)
        self.cap_ms = float(cap_ms)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def sleep_s(self, attempt: int, retry_after: str | None) -> float:
        backoff_ms = min(self.cap_ms, self.base_ms * (2 ** attempt))
        with self._lock:
            # Full jitter on the backoff half (decorrelates a thundering
            # herd of shed clients) …
            jittered_ms = backoff_ms * (0.5 + self._rng.random())
        try:
            ra_s = float(retry_after) if retry_after else 0.0
        except ValueError:
            ra_s = 0.0
        # … but never retry BEFORE the server's Retry-After: honoring it
        # is the point of the header.
        return max(ra_s, jittered_ms / 1000.0)

    def describe(self) -> dict | None:
        if self.retries <= 0:
            return None
        return {
            "max_retries": self.retries,
            "base_ms": self.base_ms,
            "cap_ms": self.cap_ms,
        }


_NO_RETRY = _RetryPolicy(0)


def _classify(code: int) -> str:
    """HTTP status → the artifact's outcome taxonomy, shared by every
    client engine (thread, keep-alive, event loop): 200 ok, 503 shed
    (the explicit admission/degraded-mode contract), anything else err."""
    return "ok" if code == 200 else "shed" if code == 503 else "err"


def _plan_retry(retry, status, attempt, retry_after, now, stop_at,
                tally) -> float | None:
    """The shed-retry policy, shared by every client engine: returns the
    backoff seconds when the request should be re-attempted, or None when
    the outcome is final — counting the give-up when a retry budget
    existed but was exhausted or the backoff would cross the run deadline
    (retries respect --duration; see _fire)."""
    if status != "shed":
        return None
    if attempt < retry.retries:
        sleep_s = retry.sleep_s(attempt, retry_after)
        if stop_at is None or now + sleep_s <= stop_at:
            with tally.lock:
                tally.n_retries += 1
            return sleep_s
    if retry.retries > 0:
        with tally.lock:
            tally.n_gaveup += 1
    return None


class _KeepAliveClient:
    """One worker's persistent HTTP/1.1 connection, reused across
    requests. A transport-level failure on a REUSED connection gets one
    transparent resend on a fresh connection (the server may have
    legitimately reaped it as idle between requests — and /predict is a
    pure function, so a resend cannot double-apply anything); the
    reconnect is counted so the artifact shows how well reuse held up."""

    def __init__(self, url: str, timeout: float) -> None:
        u = urllib.parse.urlparse(url)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.timeout = timeout
        self.conn: http.client.HTTPConnection | None = None
        self.requests_on_conn = 0
        self.connections_opened = 0
        self.requests_sent = 0
        self.reconnects = 0

    def _open(self) -> None:
        self.conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout
        )
        self.conn.connect()
        self.connections_opened += 1
        self.requests_on_conn = 0

    def close(self) -> None:
        if self.conn is not None:
            try:
                self.conn.close()
            except Exception:
                pass
            self.conn = None

    def _once(self, body: bytes):
        self.conn.request(
            "POST", "/predict", body=body,
            headers={"Content-Type": "application/json"},
        )
        resp = self.conn.getresponse()
        resp.read()  # drain so the connection is reusable
        self.requests_on_conn += 1
        self.requests_sent += 1
        if resp.getheader("Connection", "").lower() == "close" or \
                resp.will_close:
            self.close()
        return resp

    def post_predict(self, body: bytes):
        """(status, x_request_id, retry_after, serve_path, replica,
        version) — raises on transport errors (after the one
        fresh-connection resend)."""
        if self.conn is None:
            self._open()
            resp = self._once(body)
        else:
            try:
                resp = self._once(body)
            except (http.client.HTTPException, OSError):
                # The reused socket died under us (idle reap race, server
                # restart): one resend on a fresh connection.
                self.close()
                self.reconnects += 1
                self._open()
                resp = self._once(body)
        return (
            resp.status,
            resp.getheader("X-Request-Id"),
            resp.getheader("Retry-After"),
            resp.getheader("X-Serve-Path"),
            resp.getheader("X-Replica"),
            resp.getheader("X-Model-Version"),
        )


def _fire_keepalive(
    client: _KeepAliveClient, bodies: _Bodies, tally: _Tally,
    retry: _RetryPolicy = _NO_RETRY, stop_at: float | None = None,
) -> None:
    """One logical request over the worker's persistent connection —
    same outcome taxonomy and retry semantics as ``_fire``."""
    body = bodies.next_body()
    attempt = 0
    t0 = time.monotonic()
    while True:
        rid = retry_after = path = replica = version = None
        try:
            code, rid, retry_after, path, replica, version = \
                client.post_predict(body)
            status = _classify(code)
        except Exception:
            status = "err"
        now = time.monotonic()
        latency_ms = (now - t0) * 1000.0
        sleep_s = _plan_retry(
            retry, status, attempt, retry_after, now, stop_at, tally
        )
        if sleep_s is not None:
            time.sleep(sleep_s)
            attempt += 1
            continue
        tally.record(
            status, latency_ms, rid, path=path, replica=replica,
            version=version,
        )
        return


# ---------------------------------------------------------------------------
# event-loop closed loop (--connections): one thread, N persistent sockets
# ---------------------------------------------------------------------------


class _EvConn:
    """One closed-loop connection driven by the client event loop: fires
    its next request the moment the previous reply lands, parses replies
    incrementally (status line + headers + Content-Length body), and
    carries its own retry/backoff state."""

    __slots__ = (
        "sock", "buf", "t0", "attempt", "body", "requests_done",
        "connections_opened", "reconnects", "deadline", "backoff_until",
        "pending_new", "next_at", "closed",
    )

    def __init__(self) -> None:
        self.sock = None
        self.buf = bytearray()
        self.t0 = 0.0          # first-attempt send time of the logical req
        self.attempt = 0
        self.body = b""
        self.requests_done = 0
        self.connections_opened = 0
        self.reconnects = 0
        self.deadline = 0.0    # per-attempt reply deadline
        self.backoff_until = 0.0
        self.pending_new = False  # the deferred send is a NEW logical req
        self.next_at = 0.0     # paced mode: earliest next logical send
        self.closed = False

    def parse_reply(self):
        """(status, headers) when a complete reply is buffered, else
        None; consumes the reply's bytes. Raises on a garbled stream."""
        end = self.buf.find(b"\r\n\r\n")
        if end < 0:
            return None
        head = bytes(self.buf[:end]).decode("latin-1").split("\r\n")
        status = int(head[0].split()[1])
        headers = {}
        for line in head[1:]:
            name, _, value = line.partition(":")
            headers[name.strip().lower()] = value.strip()
        length = int(headers.get("content-length", "0"))
        if len(self.buf) - (end + 4) < length:
            return None
        del self.buf[:end + 4 + length]
        return status, headers


def run_closed_evloop(url, bodies, duration, connections, timeout, tally,
                      retry=_NO_RETRY, rate_per_conn: float = 0.0,
                      schedule: _RateSchedule | None = None):
    """Closed loop over ``connections`` persistent sockets driven by ONE
    selector thread — the client-side mirror of the server's event-loop
    transport. A thread-per-connection client melts into GIL scheduling
    noise near a thousand threads, inflating measured latency with
    client-side queueing; one loop keeps the client honest at the
    concurrency the transport bench needs. Retry backoff becomes a
    per-connection timer instead of a sleeping thread.

    ``rate_per_conn`` > 0 paces each connection at that many logical
    requests per second (think time), start times staggered across
    connections: the 1000-user SLO scenario — 1000 live keep-alive
    connections offering connections×rate qps — instead of the
    zero-think-time saturation mode, whose latency is pinned at
    N/throughput by Little's law no matter how fast the server is.
    ``schedule`` (``--ramp``) generalizes the constant rate to a
    piecewise step/linear rate over the run — the surge→quiet arc from
    one client."""
    import selectors

    u = urllib.parse.urlparse(url)
    addr = (u.hostname or "127.0.0.1", u.port or 80)
    sel = selectors.DefaultSelector()
    t_start = time.monotonic()
    bodies.arm(t_start)
    tally.t0 = t_start
    stop = t_start + duration
    if schedule is None and rate_per_conn > 0:
        schedule = _RateSchedule([(0.0, rate_per_conn)])
    paced = schedule is not None

    def interval_at(now: float) -> float:
        return 1.0 / schedule.rate_at(now - t_start)

    conns = [_EvConn() for _ in range(connections)]
    if paced:
        first = interval_at(t_start)
        for i, c in enumerate(conns):
            # Staggered starts decorrelate the fleet (no thundering herd
            # at t=0 and none at each subsequent tick).
            c.next_at = t_start + first * i / max(connections, 1)

    def connect(c: _EvConn) -> None:
        c.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        # Blocking connect, non-blocking after: loopback establishment is
        # microseconds, and it keeps the send below well-defined.
        c.sock.settimeout(min(timeout, 10.0))
        c.sock.connect(addr)
        c.sock.setblocking(False)
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        c.connections_opened += 1
        c.buf.clear()

    def unregister(c: _EvConn) -> None:
        if c.sock is not None:
            try:
                sel.unregister(c.sock)
            except (KeyError, ValueError):
                pass

    def drop_socket(c: _EvConn) -> None:
        unregister(c)
        if c.sock is not None:
            c.sock.close()
            c.sock = None

    def send_request(c: _EvConn, new_logical: bool) -> None:
        now = time.monotonic()
        if new_logical:
            c.body = bodies.next_body()
            c.t0 = now
            c.attempt = 0
            if paced:
                c.next_at = max(c.next_at + interval_at(now), now)
        c.deadline = now + timeout
        req = (
            b"POST /predict HTTP/1.1\r\n"
            b"Host: %b\r\nContent-Type: application/json\r\n"
            b"Content-Length: %d\r\n\r\n%b"
            % (addr[0].encode(), len(c.body), c.body)
        )
        # A ~700-byte request fits any socket buffer, so a short write
        # means the connection is effectively dead: one retry on a fresh
        # socket (counted as a reconnect), then give the request up.
        for attempt in range(2):
            try:
                if c.sock is None:
                    connect(c)
                if c.sock.send(req) < len(req):
                    raise OSError("short write")
                sel.register(c.sock, selectors.EVENT_READ, c)
                return
            except KeyError:
                return  # already registered (reused keep-alive socket)
            except OSError:
                drop_socket(c)
                if attempt == 0:
                    c.reconnects += 1
        tally.record("err", (time.monotonic() - c.t0) * 1000.0, None)
        c.requests_done += 1
        c.closed = True

    def finish(c: _EvConn, status: str, rid, retry_after,
               path=None, replica=None, version=None) -> None:
        """A reply (or terminal failure) for the logical request."""
        now = time.monotonic()
        latency_ms = (now - c.t0) * 1000.0
        sleep_s = _plan_retry(
            retry, status, c.attempt, retry_after, now, stop, tally
        )
        if sleep_s is not None:
            # Backoff as a per-connection timer, not a sleeping thread.
            c.attempt += 1
            c.backoff_until = now + sleep_s
            c.pending_new = False
            unregister(c)
            return
        tally.record(
            status, latency_ms, rid, path=path, replica=replica,
            version=version,
        )
        c.requests_done += 1
        if now < stop:
            if paced and c.next_at > now:
                # Paced mode: the connection idles (still connected, still
                # keep-alive) until its next scheduled request.
                c.backoff_until = c.next_at
                c.pending_new = True
            else:
                send_request(c, new_logical=True)
        else:
            unregister(c)
            if c.sock is not None:
                c.sock.close()
                c.sock = None
            c.closed = True

    for c in conns:
        if paced and c.next_at > t_start:
            c.backoff_until = c.next_at
            c.pending_new = True
        else:
            send_request(c, new_logical=True)
    while True:
        now = time.monotonic()
        live = [c for c in conns if not c.closed]
        if not live:
            break
        for key, _ in sel.select(timeout=0.05):
            c = key.data
            try:
                data = c.sock.recv(65536)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                data = b""
            if not data:
                # Server closed (idle-reap race / restart) or the socket
                # died mid-reply: one transparent resend on a fresh
                # socket — /predict is a pure function, a resend cannot
                # double-apply anything. A paced connection reaped while
                # IDLE has nothing in flight: just reconnect at its next
                # scheduled send.
                drop_socket(c)
                c.reconnects += 1
                if not c.backoff_until:
                    send_request(c, new_logical=False)
                continue
            c.buf += data
            try:
                reply = c.parse_reply()
            except (ValueError, IndexError):
                drop_socket(c)
                c.reconnects += 1
                send_request(c, new_logical=False)
                continue
            if reply is None:
                continue
            code, headers = reply
            status = _classify(code)
            if headers.get("connection", "").lower() == "close":
                unregister(c)
                c.sock.close()
                c.sock = None
            finish(
                c, status, headers.get("x-request-id"),
                headers.get("retry-after"),
                path=headers.get("x-serve-path"),
                replica=headers.get("x-replica"),
                version=headers.get("x-model-version"),
            )
        now = time.monotonic()
        for c in conns:
            if c.closed:
                continue
            if c.backoff_until and c.pending_new and now >= stop:
                # Paced connection idling past the end of the run:
                # nothing in flight and nothing more to send — close now
                # instead of sleeping to the next pacing tick, which
                # would inflate the measured wall (and so deflate the
                # reported qps) by up to one think-time interval.
                c.backoff_until = 0.0
                c.pending_new = False
                c.closed = True
                drop_socket(c)
            elif c.backoff_until and now >= c.backoff_until:
                c.backoff_until = 0.0
                new = c.pending_new
                c.pending_new = False
                if new and now >= stop:
                    c.closed = True
                    drop_socket(c)
                    continue
                send_request(c, new_logical=new)
            elif c.sock is not None and not c.backoff_until \
                    and now > c.deadline:
                # Reply deadline missed: an explicit err outcome, never a
                # hang — and the half-dead socket is not reused.
                tally.record("err", (now - c.t0) * 1000.0, None)
                c.requests_done += 1
                drop_socket(c)
                if now < stop:
                    send_request(c, new_logical=True)
                else:
                    c.closed = True
    sel.close()
    wall = time.monotonic() - t_start
    sent = [c.requests_done for c in conns]
    stats = {
        "client": "event-loop",
        "n_connections": connections,
        "opened_total": sum(c.connections_opened for c in conns),
        "reconnects": sum(c.reconnects for c in conns),
        "requests_total": sum(sent),
        "requests_per_connection_mean": (
            round(sum(sent) / max(sum(c.connections_opened
                                      for c in conns), 1), 2)
        ),
        "requests_on_final_connection_max": max(sent, default=0),
    }
    return wall, stats


def _merge_conn_stats(acc: dict | None, cur: dict | None) -> dict | None:
    """Fold one interleave slice's connection stats into the running
    total (reuse accounting stays meaningful per target across slices)."""
    if cur is None:
        return acc
    if acc is None:
        return dict(cur)
    for k in ("opened_total", "reconnects", "requests_total"):
        acc[k] = acc.get(k, 0) + cur.get(k, 0)
    acc["requests_on_final_connection_max"] = max(
        acc.get("requests_on_final_connection_max", 0),
        cur.get("requests_on_final_connection_max", 0),
    )
    acc["requests_per_connection_mean"] = round(
        acc["requests_total"] / max(acc["opened_total"], 1), 2
    )
    return acc


def run_interleaved_baseline(args, bodies, tally, tally_base, retry):
    """The ``--baseline-url`` A/B driver: alternate through-router and
    direct-replica slices of ``duration / (2 × segments)`` each, with
    each target's outcomes accumulating into its own tally. Returns
    (wall_router_s, wall_baseline_s, conn_stats_router,
    conn_stats_baseline)."""
    seg_s = args.duration / (2 * args.baseline_segments)
    wall_r = wall_b = 0.0
    cs_r = cs_b = None
    for _ in range(args.baseline_segments):
        for target, tly, is_router in (
            (args.url, tally, True),
            (args.baseline_url, tally_base, False),
        ):
            if args.connections:
                w, cs = run_closed_evloop(
                    target, bodies, seg_s, args.concurrency,
                    args.timeout, tly, retry=retry,
                    rate_per_conn=args.rate_per_conn,
                )
            else:
                w, cs = run_closed(
                    target, bodies, seg_s, args.concurrency,
                    args.timeout, tly, retry=retry,
                )
            if is_router:
                wall_r += w
                cs_r = _merge_conn_stats(cs_r, cs)
            else:
                wall_b += w
                cs_b = _merge_conn_stats(cs_b, cs)
    return wall_r, wall_b, cs_r, cs_b


def _overhead_block(router_ms: list[float], base_ms: list[float],
                    segments: int) -> dict:
    """``router_overhead_ms``: quantile deltas router-minus-direct from
    the interleaved tallies. Null deltas when either side has no ok
    replies (the claim needs evidence on both sides)."""
    r, b = _percentiles(router_ms), _percentiles(base_ms)
    return {
        "segments_per_target": segments,
        "p50": (
            None if r["p50"] is None or b["p50"] is None
            else round(r["p50"] - b["p50"], 3)
        ),
        "p99": (
            None if r["p99"] is None or b["p99"] is None
            else round(r["p99"] - b["p99"], 3)
        ),
        "mean": (
            None if r["mean"] is None or b["mean"] is None
            else round(r["mean"] - b["mean"], 3)
        ),
    }


def _fire(
    url: str, bodies: _Bodies, timeout: float, tally: _Tally,
    retry: _RetryPolicy = _NO_RETRY, stop_at: float | None = None,
) -> None:
    body = bodies.next_body()  # one patient for every attempt of the request
    attempt = 0
    # Latency is measured from the FIRST attempt: a request that rode out
    # three sheds and two seconds of backoff before its 200 took the
    # client that whole time — recording only the final attempt would
    # make a degraded window look latency-free in the artifact.
    t0 = time.monotonic()
    while True:
        req = urllib.request.Request(
            url + "/predict", data=body,
            headers={"Content-Type": "application/json"},
        )
        rid = retry_after = path = replica = version = None
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                resp.read()
                rid = resp.headers.get("X-Request-Id")
                path = resp.headers.get("X-Serve-Path")
                replica = resp.headers.get("X-Replica")
                version = resp.headers.get("X-Model-Version")
                status = _classify(resp.status)
        except urllib.error.HTTPError as exc:
            exc.read()
            rid = exc.headers.get("X-Request-Id")
            retry_after = exc.headers.get("Retry-After")
            status = _classify(exc.code)
        except Exception:
            status = "err"
        now = time.monotonic()
        latency_ms = (now - t0) * 1000.0
        # Retries respect the run deadline (_plan_retry): a backoff
        # (Retry-After can be tens of seconds under a slow restart
        # schedule) that would sleep past --duration becomes a give-up,
        # or workers could overrun the window by minutes and skew
        # wall/qps.
        sleep_s = _plan_retry(
            retry, status, attempt, retry_after, now, stop_at, tally
        )
        if sleep_s is not None:
            time.sleep(sleep_s)
            attempt += 1
            continue
        tally.record(
            status, latency_ms, rid, path=path, replica=replica,
            version=version,
        )
        return


def run_closed(url, bodies, duration, concurrency, timeout, tally,
               retry=_NO_RETRY):
    """Closed loop over ``concurrency`` persistent keep-alive connections
    (one per worker). Returns (wall_s, connection_stats)."""
    t0 = time.monotonic()
    bodies.arm(t0)
    tally.t0 = t0
    stop = t0 + duration
    clients = [_KeepAliveClient(url, timeout) for _ in range(concurrency)]

    def worker(client):
        try:
            while time.monotonic() < stop:
                _fire_keepalive(
                    client, bodies, tally, retry=retry, stop_at=stop
                )
        finally:
            client.close()

    threads = [
        threading.Thread(target=worker, args=(c,)) for c in clients
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    reused = [c.requests_on_conn for c in clients]
    sent = [c.requests_sent for c in clients]
    stats = {
        "n_connections": concurrency,
        "opened_total": sum(c.connections_opened for c in clients),
        "reconnects": sum(c.reconnects for c in clients),
        "requests_total": sum(sent),
        "requests_per_connection_mean": (
            round(sum(sent) / max(sum(c.connections_opened
                                      for c in clients), 1), 2)
        ),
        "requests_on_final_connection_max": max(reused, default=0),
    }
    return wall, stats


def run_open(url, bodies, duration, qps, timeout, tally):
    # No retry plumbing on purpose: the CLI rejects --retries in open
    # mode (a backing-off generator no longer offers its fixed rate).
    """Fixed-rate schedule; each request gets its own thread so a slow
    server cannot throttle the offered rate (the point of an open loop).
    A bound on in-flight threads keeps a wedged server from spawning
    without limit — beyond it, sends are counted as errors (client-side
    overflow), never silently skipped."""
    interval = 1.0 / qps
    n = int(duration * qps)
    inflight = threading.Semaphore(max(64, int(4 * qps)))
    threads = []
    t0 = time.monotonic()
    bodies.arm(t0)
    tally.t0 = t0
    for i in range(n):
        target = t0 + i * interval
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        if not inflight.acquire(blocking=False):
            tally.record("err", 0.0)
            continue

        def one():
            try:
                _fire(url, bodies, timeout, tally)
            finally:
                inflight.release()

        th = threading.Thread(target=one)
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    return time.monotonic() - t0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--url", default="http://127.0.0.1:8000")
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--duration", type=float, default=10.0, help="seconds")
    ap.add_argument(
        "--concurrency", type=int, default=8,
        help="closed-loop workers (one persistent connection each)",
    )
    ap.add_argument(
        "--connections", type=int, default=None, metavar="N",
        help="high-concurrency closed-loop mode: N persistent keep-alive "
        "connections driven by ONE event-loop thread (overrides "
        "--concurrency; closed mode only) — the 1000-connection "
        "transport bench knob",
    )
    ap.add_argument(
        "--rate-per-conn", type=float, default=0.0, metavar="R",
        help="pace each --connections connection at R requests/s with "
        "staggered starts (think time): offered rate = N x R over N live "
        "keep-alive connections — the SLO scenario; 0 (default) is "
        "zero-think-time saturation, whose latency is pinned at "
        "N/throughput by Little's law",
    )
    ap.add_argument(
        "--ramp", default=None, metavar="SPEC",
        help="per-connection rate SCHEDULE for the paced --connections "
        "client: comma-separated T:RATE points (seconds into the run : "
        "requests/s per connection), e.g. '0:1,15:6,75:1' — one client "
        "drives the whole surge→quiet arc; the schedule lands in the "
        "artifact's ramp block. Requires --connections; mutually "
        "exclusive with --rate-per-conn",
    )
    ap.add_argument(
        "--ramp-shape", choices=("step", "linear"), default="step",
        help="how the rate moves between --ramp points: step jumps and "
        "holds (default), linear interpolates",
    )
    ap.add_argument(
        "--baseline-url", default=None, metavar="URL",
        help="measure router-added overhead: interleave slices against "
        "--url (the router) and URL (a direct replica) in one run; the "
        "artifact gains a baseline block and first-class "
        "router_overhead_ms p50/p99/mean deltas. Closed mode only; "
        "mutually exclusive with --perturb and --ramp",
    )
    ap.add_argument(
        "--baseline-segments", type=int, default=3, metavar="N",
        help="A/B interleave granularity for --baseline-url: the run "
        "splits into 2xN alternating slices (default 3 per target) — "
        "more slices decorrelate host noise/drift from the delta",
    )
    ap.add_argument("--qps", type=float, default=100.0, help="open-loop rate")
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--patient", help="patient JSON file (default: example)")
    ap.add_argument(
        "--patients",
        help="JSONL file of patient dicts, cycled round-robin — the "
        "distributed-traffic mode drift monitoring needs",
    )
    ap.add_argument(
        "--perturb", default=None, metavar="SPEC",
        help="shift/scale patient variables mid-run, e.g. "
        "'Ejection_Fraction*0.6,Max_Wall_Thick+8' (ops: * + - =); the "
        "spec and onset land in the artifact",
    )
    ap.add_argument(
        "--perturb-at", type=float, default=0.5, metavar="FRAC",
        help="fraction of the run after which --perturb activates "
        "(default 0.5; 0 perturbs from the first request)",
    )
    ap.add_argument(
        "--perturb-until", type=float, default=None, metavar="FRAC",
        help="fraction of the run at which --perturb reverts (default: "
        "never) — one run drives a full drift-then-recovery arc",
    )
    ap.add_argument(
        "--perturb-revert-file", default=None, metavar="PATH",
        help="revert --perturb as soon as PATH exists (polled, <=4 "
        "stats/s) — an external driver (e.g. the continual-learning "
        "demo, after its rolling promotion) ends the drift under the "
        "same running client; revert index/time land in the artifact",
    )
    ap.add_argument(
        "--retries", type=int, default=0,
        help="max retries per request on a 503 shed (capped exponential "
        "backoff + jitter, honoring Retry-After); retry counts and "
        "give-ups land in the artifact — chaos benches quantify "
        "client-visible impact with this. Closed loop only: a backing-off "
        "open loop no longer offers its fixed rate",
    )
    ap.add_argument(
        "--retry-base-ms", type=float, default=100.0,
        help="initial retry backoff (doubles per attempt)",
    )
    ap.add_argument(
        "--retry-cap-ms", type=float, default=5000.0,
        help="retry backoff cap",
    )
    ap.add_argument("--out", default=None, help="artifact path (JSON)")
    ap.add_argument(
        "--assert-slo", default=None, metavar="P50:MS,P99:MS,ERR:FRAC",
        help="exit 3 when the artifact violates the stated budget: "
        "comma-separated KEY:BOUND pairs where KEY is a latency "
        "percentile (p50/p95/p99/mean/max, bound in ms, over ok "
        "replies) or 'err' (bound on (n_err+n_shed)/n_sent). Drill and "
        "bench jobs gate on client-observed SLO with this instead of "
        "eyeballing JSON",
    )
    args = ap.parse_args(argv)
    slo_budget = None
    if args.assert_slo:
        try:
            slo_budget = _parse_slo_budget(args.assert_slo)
        except ValueError as exc:
            ap.error(str(exc))
    if args.patient and args.patients:
        ap.error("--patient and --patients are mutually exclusive")
    if not 0.0 <= args.perturb_at <= 1.0:
        ap.error("--perturb-at must be in [0, 1]")
    if args.perturb_until is not None:
        if not 0.0 <= args.perturb_until <= 1.0:
            ap.error("--perturb-until must be in [0, 1]")
        if args.perturb_until <= args.perturb_at:
            ap.error("--perturb-until must be after --perturb-at")
    if args.retries and args.mode == "open":
        # A generator that backs off is no longer offering a fixed rate:
        # retry sleeps would hold in-flight slots and silently throttle
        # the offered qps the open loop exists to guarantee.
        ap.error("--retries requires --mode closed (an open loop that "
                 "backs off is no longer an open loop)")
    if args.connections is not None:
        if args.mode != "closed":
            ap.error("--connections requires --mode closed (the open "
                     "loop opens one connection per request by design)")
        if args.connections < 1:
            ap.error("--connections must be >= 1")
        args.concurrency = args.connections
    if args.rate_per_conn and not args.connections:
        ap.error("--rate-per-conn requires --connections (pacing is a "
                 "property of the event-loop client)")
    if args.baseline_url:
        if args.mode != "closed":
            ap.error("--baseline-url requires --mode closed")
        if args.perturb:
            ap.error("--baseline-url and --perturb are mutually exclusive "
                     "(a drifting cohort would confound the A/B delta)")
        if args.ramp:
            ap.error("--baseline-url and --ramp are mutually exclusive "
                     "(a rate schedule cannot restart per slice)")
        if args.baseline_segments < 1:
            ap.error("--baseline-segments must be >= 1")
    schedule = None
    if args.ramp:
        if not args.connections:
            ap.error("--ramp requires --connections (a ramp is a pacing "
                     "schedule, and pacing is a property of the "
                     "event-loop client)")
        if args.rate_per_conn:
            ap.error("--ramp and --rate-per-conn are mutually exclusive "
                     "(a ramp IS the rate)")
        try:
            schedule = _RateSchedule.parse(args.ramp, shape=args.ramp_shape)
        except ValueError as exc:
            ap.error(f"--ramp: {exc}")

    if args.patients:
        with open(args.patients) as f:
            patients = [json.loads(line) for line in f if line.strip()]
        if not patients:
            ap.error(f"--patients {args.patients}: no patient lines")
        patients_src = args.patients
    elif args.patient:
        with open(args.patient) as f:
            patients = [json.load(f)]
        patients_src = args.patient
    else:
        # Script-relative, not CWD-relative: the tool must find the
        # package when invoked as /path/to/repo/tools/loadgen.py from
        # anywhere.
        sys.path.insert(
            0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
        )
        from machine_learning_replications_tpu.data.examples import (
            EXAMPLE_PATIENT,
        )

        patients = [dict(EXAMPLE_PATIENT)]
        patients_src = "example"
    perturb_ops = parse_perturb(args.perturb) if args.perturb else []
    bodies = _Bodies(
        patients, perturb_ops, args.perturb_at, args.duration,
        until_frac=args.perturb_until,
        revert_file=args.perturb_revert_file,
    )

    retry = _RetryPolicy(
        retries=args.retries, base_ms=args.retry_base_ms,
        cap_ms=args.retry_cap_ms,
    )
    tally = _Tally()
    tally_base = _Tally() if args.baseline_url else None
    baseline = overhead = None
    conn_stats = None
    if args.baseline_url:
        wall, wall_b, conn_stats, cs_b = run_interleaved_baseline(
            args, bodies, tally, tally_base, retry
        )
        offered = (
            round(args.concurrency * args.rate_per_conn, 1)
            if args.connections and args.rate_per_conn else None
        )
        nb = tally_base.n_ok + tally_base.n_shed + tally_base.n_err
        baseline = {
            "url": args.baseline_url,
            "duration_s": round(wall_b, 3),
            "achieved_qps": (
                round(tally_base.n_ok / wall_b, 2) if wall_b > 0 else 0.0
            ),
            "n_sent": nb,
            "n_ok": tally_base.n_ok,
            "n_shed": tally_base.n_shed,
            "n_err": tally_base.n_err,
            "latency_ms": {
                k: None if v is None else round(v, 3)
                for k, v in _percentiles(tally_base.ok_latency_ms).items()
            },
            "connections": cs_b,
        }
        overhead = _overhead_block(
            tally.ok_latency_ms, tally_base.ok_latency_ms,
            args.baseline_segments,
        )
    elif args.mode == "closed":
        # --connections selects the single-threaded event-loop client:
        # at hundreds-to-thousands of connections a thread per worker
        # measures the client's own GIL scheduling, not the server.
        if args.connections:
            wall, conn_stats = run_closed_evloop(
                args.url, bodies, args.duration, args.concurrency,
                args.timeout, tally, retry=retry,
                rate_per_conn=args.rate_per_conn,
                schedule=schedule,
            )
            # Constant-paced mode has ONE definite offered rate; a ramp
            # records its per-phase rates in the ramp block; saturation
            # has none.
            offered = (
                round(args.concurrency * args.rate_per_conn, 1)
                if args.rate_per_conn else None
            )
        else:
            wall, conn_stats = run_closed(
                args.url, bodies, args.duration, args.concurrency,
                args.timeout, tally, retry=retry,
            )
            offered = None
    else:
        wall = run_open(
            args.url, bodies, args.duration, args.qps, args.timeout, tally
        )
        offered = args.qps

    n_sent = tally.n_ok + tally.n_shed + tally.n_err
    artifact = {
        "kind": "serve_bench",
        "url": args.url,
        "mode": args.mode,
        "duration_s": round(wall, 3),
        "concurrency": args.concurrency if args.mode == "closed" else None,
        "offered_qps": offered,
        "achieved_qps": round(tally.n_ok / wall, 2) if wall > 0 else 0.0,
        "n_sent": n_sent,
        "n_ok": tally.n_ok,
        "n_shed": tally.n_shed,
        "n_err": tally.n_err,
        "shed_rate": round(tally.n_shed / n_sent, 4) if n_sent else 0.0,
        "latency_ms": {
            k: None if v is None else round(v, 3)
            for k, v in _percentiles(tally.ok_latency_ms).items()
        },
        "worst_requests": tally.worst_requests(),
        # Dual-path routing split (docs/SERVING.md): per-path ok counts
        # and latency quantiles from the echoed X-Serve-Path header.
        # Null against a server that predates the router.
        "paths": tally.paths_block(),
        # Fleet distribution (docs/FLEET.md): ok replies per replica and
        # per checkpoint version with first/last-seen offsets — the
        # zero-downtime rolling-deploy crossover, client-side. Null
        # against a server that predates the fleet tier.
        "fleet": tally.fleet_block(),
        # Keep-alive reuse accounting (closed loop): opened_total near
        # n_connections means persistent connections really persisted;
        # reconnects counts idle-reap races absorbed by a fresh-socket
        # resend. Null in open-loop mode.
        "connections": conn_stats,
        # The --baseline-url A/B join (docs/FLEET.md "Router data
        # plane"): the direct-replica side of the interleaved run, and
        # the router-added latency deltas as first-class fields. Null
        # without --baseline-url.
        "baseline": baseline,
        "router_overhead_ms": overhead,
        # Client-side resilience: how many sheds the retry policy absorbed
        # (n_shed counts only FINAL sheds — each one a give-up when
        # retries were on). Null when retries are disabled.
        "retry": None if retry.describe() is None else {
            **retry.describe(),
            "retries": tally.n_retries,
            "give_ups": tally.n_gaveup,
        },
        "patients": patients_src,
        "n_patients": len(patients),
        "perturb": bodies.describe(),
        # The --ramp traffic shape (null without one): the schedule the
        # client offered, for joining against journal/metrics timelines.
        "ramp": (
            schedule.describe(args.concurrency)
            if schedule is not None else None
        ),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }
    line = json.dumps(artifact, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        print(f"artifact written to {args.out}", file=sys.stderr)
    if slo_budget is not None:
        violations = _check_slo_budget(artifact, slo_budget)
        if violations:
            for v in violations:
                print(f"SLO VIOLATION: {v}", file=sys.stderr)
            return 3
        print(
            "SLO OK: " + ", ".join(
                f"{k}<={b:g}" for k, b in sorted(slo_budget.items())
            ),
            file=sys.stderr,
        )
    return 0


def _parse_slo_budget(spec: str) -> dict[str, float]:
    """``P50:MS,P99:MS,ERR:FRAC`` → ``{"p50": ms, "p99": ms, "err":
    frac}``. Keys are case-insensitive; any subset of
    p50/p95/p99/mean/max/err is allowed."""
    budget: dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(
                f"--assert-slo: {part!r} is not KEY:BOUND"
            )
        key, _, bound = part.partition(":")
        key = key.strip().lower()
        if key not in ("p50", "p95", "p99", "mean", "max", "err"):
            raise ValueError(
                f"--assert-slo: unknown key {key!r} (know "
                "p50/p95/p99/mean/max/err)"
            )
        if key in budget:
            raise ValueError(f"--assert-slo: duplicate key {key!r}")
        try:
            budget[key] = float(bound)
        except ValueError:
            raise ValueError(
                f"--assert-slo: bound {bound!r} is not a number"
            ) from None
        if budget[key] < 0:
            raise ValueError(f"--assert-slo: {key} bound must be >= 0")
    if not budget:
        raise ValueError("--assert-slo: empty budget")
    return budget


def _check_slo_budget(artifact: dict, budget: dict) -> list[str]:
    """The violations (empty = within budget). A latency percentile
    that is null (zero ok replies) violates any latency bound — a run
    that completed nothing did not meet its SLO."""
    violations = []
    latency = artifact.get("latency_ms") or {}
    for key, bound in sorted(budget.items()):
        if key == "err":
            n_sent = artifact.get("n_sent") or 0
            bad = (artifact.get("n_err") or 0) + \
                (artifact.get("n_shed") or 0)
            frac = bad / n_sent if n_sent else 1.0
            if frac > bound:
                violations.append(
                    f"err rate {frac:.4f} > budget {bound:g} "
                    f"({bad}/{n_sent} shed+err)"
                )
            continue
        got = latency.get(key)
        if got is None:
            violations.append(
                f"{key} latency unavailable (no ok replies) — budget "
                f"{bound:g} ms unmet"
            )
        elif got > bound:
            violations.append(
                f"{key} latency {got:.3f} ms > budget {bound:g} ms"
            )
    return violations


if __name__ == "__main__":
    raise SystemExit(main())
