#!/usr/bin/env python
"""Render an incident flight-recorder bundle for humans.

``obs.incident`` captures machine-readable JSON the moment a rule
fires; this tool turns one bundle (or the newest bundle in a directory
of them) into the markdown summary an on-call human actually reads:
what fired, what the signal looked like around onset, what the fleet
was doing, and the journal context leading up to it.

Usage:
    python tools/incident_report.py INCIDENT_DIR          # one bundle
    python tools/incident_report.py --latest BUNDLES_DIR  # newest
    python tools/incident_report.py INCIDENT_DIR --out report.md

A directory without a ``manifest.json`` is an *incomplete* capture
(crash mid-write) and is refused — the manifest is the completeness
marker, not decoration. Exit 0 on success, 2 on a missing/incomplete
bundle.
"""

from __future__ import annotations

import json
import os
import sys

MANIFEST = "manifest.json"


def _load(bundle: str, name: str):
    path = os.path.join(bundle, name)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def _latest_bundle(parent: str) -> str | None:
    best = None
    for n in sorted(os.listdir(parent)):
        d = os.path.join(parent, n)
        if n.startswith("incident_") and \
                os.path.exists(os.path.join(d, MANIFEST)):
            best = d  # names carry a UTC stamp: sorted == chronological
    return best


def _fmt_series_tail(series: list, limit: int = 6) -> list[str]:
    lines = []
    for s in series:
        pts = s.get("points", [])[-limit:]
        lab = s.get("labels") or {}
        lab_s = ",".join(f"{k}={v}" for k, v in sorted(lab.items()))
        vals = " ".join(
            f"{p[1]:.4g}" if len(p) == 2 else f"n={p[1]:.0f}"
            for p in pts
        )
        lines.append(f"  - `{{{lab_s}}}`: {vals}")
    return lines


def render(bundle: str) -> str:
    manifest = _load(bundle, MANIFEST)
    if manifest is None:
        raise FileNotFoundError(
            f"{bundle}: no {MANIFEST} — incomplete capture (a crashed "
            "capture never writes its manifest)"
        )
    alert = _load(bundle, "alert.json") or {}
    history = _load(bundle, "history.json") or {}
    out = []
    out.append(f"# Incident: {manifest.get('rule')} "
               f"({manifest.get('severity')})")
    out.append("")
    out.append(f"- bundle: `{os.path.basename(bundle)}`")
    out.append(f"- captured: {manifest.get('captured_at')}")
    out.append(f"- schema: {manifest.get('schema')}, files: "
               f"{len(manifest.get('files', []))}, history window: "
               f"{manifest.get('window_s')}s")
    if manifest.get("errors"):
        out.append(f"- collector errors: {manifest['errors']}")
    out.append("")
    out.append("## Triggering rule")
    out.append("")
    out.append(f"- detail: {alert.get('detail')}")
    out.append(f"- value: {alert.get('value')}")
    spec = alert.get("spec") or {}
    if spec:
        out.append(f"- spec: `{json.dumps(spec, sort_keys=True)}`")
    out.append("")

    fam = spec.get("family")
    if fam and fam in history:
        out.append(f"## Signal around onset: `{fam}`")
        out.append("")
        out.extend(_fmt_series_tail(history[fam].get("series", [])))
        out.append("")

    reqs = _load(bundle, "requests.json")
    if isinstance(reqs, list) and reqs:
        out.append(f"## Request tail ({len(reqs)} sampled)")
        out.append("")
        def total(r):
            return r.get("total_s") or 0.0
        slow = sorted(reqs, key=total, reverse=True)[:5]
        for r in slow:
            out.append(
                f"  - `{r.get('request_id', '?')}` "
                f"{1000.0 * total(r):.1f} ms "
                f"status={r.get('status', r.get('outcome', '?'))}"
            )
        out.append("")

    replicas = _load(bundle, "replicas.json")
    if isinstance(replicas, list):
        out.append(f"## Replicas ({len(replicas)})")
        out.append("")
        for rep in replicas:
            out.append(
                f"  - `{rep.get('id')}` state={rep.get('state')} "
                f"in_rotation={rep.get('in_rotation')} "
                f"url={rep.get('url')}"
            )
        out.append("")

    trace = _load(bundle, "fleet_trace.json")
    if isinstance(trace, dict):
        n_ev = len(trace.get("traceEvents", []))
        meta = trace.get("otherData", {})
        out.append(f"## Fleet trace join: {n_ev} events, "
                   f"otherData={json.dumps(meta, sort_keys=True)}")
        out.append("")

    tail_path = os.path.join(bundle, "journal_tail.jsonl")
    if os.path.exists(tail_path):
        interesting = []
        with open(tail_path, encoding="utf-8", errors="replace") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                kind = rec.get("kind", "")
                if kind.startswith(("alert_", "fleet_", "lifecycle_",
                                    "incident_", "autoscale_")):
                    interesting.append(rec)
        out.append(f"## Journal context ({len(interesting)} "
                   "fleet/alert events in tail)")
        out.append("")
        for rec in interesting[-15:]:
            slim = {k: v for k, v in rec.items() if k != "ts"}
            out.append(f"  - {rec.get('ts')} `{json.dumps(slim)}`")
        out.append("")
    return "\n".join(out) + "\n"


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    out_path = None
    if "--out" in argv:
        i = argv.index("--out")
        out_path = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    latest = "--latest" in argv
    if latest:
        argv.remove("--latest")
    if len(argv) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    bundle = argv[0]
    if latest:
        found = _latest_bundle(bundle)
        if found is None:
            print(f"{bundle}: no complete incident bundles",
                  file=sys.stderr)
            return 2
        bundle = found
    try:
        text = render(bundle)
    except FileNotFoundError as exc:
        print(str(exc), file=sys.stderr)
        return 2
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(f"wrote {out_path}", file=sys.stderr)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
