#!/bin/bash
# Queued TPU measurements for the next healthy tunnel window (the axon
# relay wedges for hours at a time — see docs/SCALING.md and the bench
# probe/queue discipline). Run this THE MOMENT a probe answers; order is
# by evidence value per minute:
#   1. full five-config driver-path bench  -> BENCH_manual_r05_tpu.json
#   2. 4M-row end-to-end pipeline          -> SCALE_r05_4m.json
#   3. standalone config-4 re-measure (only if 1 lost its c4 leg)
# Each step tolerates a mid-run wedge: bench.py self-flushes on SIGTERM/
# SIGALRM, and the pipeline runner takes --checkpoint-dir so a re-entry
# resumes finished stages.
set -x
cd "$(dirname "$0")/.."

timeout 90 python -c "import jax; d=jax.devices()[0]; print('PROBE', d.platform, d.device_kind)" || {
    echo "tunnel still wedged; aborting queue"; exit 1; }

# 1. the headline: full five-config run through the exact driver path
timeout 1700 python bench.py --budget 1600 \
    --detail-out BENCH_manual_r05_tpu.json | tee /tmp/bench_r05_tpu_line.txt

# 2. the scale proof: 4M-row end-to-end fit_pipeline (impute->select->stack)
timeout 3000 python tools/fit_pipeline_at_scale.py --rows 4000000 \
    --checkpoint-dir /tmp/scale_r05_ckpt | tee SCALE_r05_4m.json

# 3. config 4 at the post-restructure HEAD (skip if step 1 already has it)
python - <<'EOF'
import json, subprocess, sys
try:
    d = json.load(open("BENCH_manual_r05_tpu.json"))
    c4 = (d.get("configs") or {}).get("4", {})
    # A falsy vs_baseline (errored leg) must never skip the re-measure:
    # the old `A and B or C` parsed as `(A and B) or C` and skipped on any
    # TPU device string alone (ADVICE r5).
    if c4.get("vs_baseline") and "tpu" in str(c4.get("device", "")).lower():
        print("c4 already captured on TPU; skipping standalone leg")
        sys.exit(0)
except Exception as e:
    print("no usable r05 artifact c4 cell:", e)
subprocess.run(["timeout", "900", "python", "bench.py", "--config", "4",
                "--budget", "800", "--detail-out", "BENCH_manual_r05_c4_tpu.json"])
EOF
