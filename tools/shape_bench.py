#!/usr/bin/env python
"""Padded-vs-shaped flush cost bench — writes a SERVE_SHAPE_*.json artifact.

Quantifies the engine's batch shaping (docs/SERVING.md "Batch shaping")
directly, without HTTP noise: for each mid-size batch size it times
``BucketedPredictEngine.predict`` under

  padded   the r6–r11 coarse ladder (1/8/64/512) with splitting disabled
           (``max_split=1``) — every batch pads into its covering bucket,
           exactly the behavior BENCH.md r11 measured wasting up to 6×
           the needed compute on 65–200-row flushes;
  shaped   the ISSUE 7 default ladder (1/8/32/64/128/256/512) with
           best-fit sub-batch decomposition (``plan_batch``).

Both engines are fully warmed first, so every timed call is
steady-state; each cell is the median of ``--repeats`` runs with the
executed plan and pad-row counts recorded next to it. Run from the repo
root::

    JAX_PLATFORMS=cpu python tools/shape_bench.py \
        --model /path/to/ckpt --out SERVE_SHAPE_r12_cpu.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

PADDED_LADDER = (1, 8, 64, 512)  # the pre-ISSUE-7 default
SIZES = (16, 65, 100, 130, 200, 300, 512)


def _time_predict(engine, X, repeats: int) -> float:
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        engine.predict(X)
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--model", help="Orbax checkpoint dir")
    ap.add_argument("--pkl", help="legacy sklearn pickle")
    ap.add_argument("--repeats", type=int, default=9)
    ap.add_argument("--sizes", type=int, nargs="+", default=list(SIZES))
    ap.add_argument("--out", default=None, help="artifact path (JSON)")
    args = ap.parse_args(argv)

    import numpy as np

    from machine_learning_replications_tpu.data.examples import patient_row
    from machine_learning_replications_tpu.persist import (
        load_inference_params,
    )
    from machine_learning_replications_tpu.serve.engine import (
        DEFAULT_BUCKETS,
        BucketedPredictEngine,
    )

    params = load_inference_params(model=args.model, pkl=args.pkl)
    padded = BucketedPredictEngine(
        params, buckets=PADDED_LADDER, max_split=1
    )
    shaped = BucketedPredictEngine(params, buckets=DEFAULT_BUCKETS)
    for eng, name in ((padded, "padded"), (shaped, "shaped")):
        print(f"warming {name} ladder {eng.buckets} ...", file=sys.stderr)
        eng.warmup()

    row = patient_row()
    rows = []
    for n in args.sizes:
        X = np.repeat(row, n, axis=0)
        t_pad = _time_predict(padded, X, args.repeats)
        t_shape = _time_predict(shaped, X, args.repeats)
        cell = {
            "rows": n,
            "padded": {
                "bucket": padded.bucket_for(n),
                "pad_rows": padded.bucket_for(n) - n
                if n <= padded.buckets[-1] else None,
                "median_ms": round(t_pad * 1e3, 3),
            },
            "shaped": {
                "plan": list(shaped.plan_batch(n)),
                "pad_rows": sum(shaped.plan_batch(n)) - n,
                "median_ms": round(t_shape * 1e3, 3),
            },
            "speedup": round(t_pad / t_shape, 2) if t_shape > 0 else None,
        }
        rows.append(cell)
        print(
            f"rows {n:4d}: padded {cell['padded']['median_ms']:8.2f} ms "
            f"(bucket {cell['padded']['bucket']}) vs shaped "
            f"{cell['shaped']['median_ms']:8.2f} ms "
            f"(plan {cell['shaped']['plan']}) = {cell['speedup']}x",
            file=sys.stderr,
        )

    artifact = {
        "kind": "serve_shape_bench",
        "params": type(params).__name__,
        "padded_ladder": list(PADDED_LADDER),
        "shaped_ladder": list(DEFAULT_BUCKETS),
        "split_penalty_rows": shaped.split_penalty_rows,
        "max_split": shaped.max_split,
        "repeats": args.repeats,
        "cells": rows,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }
    line = json.dumps(artifact, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        print(f"artifact written to {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
