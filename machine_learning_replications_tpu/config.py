"""Configuration layer.

The reference hard-codes every knob in source (``train_ensemble_public.py:29-30``
sets ``num_xrsval=10`` / ``init_rs=2020``; hyperparameters inline at ``:43-52``;
paths relative to ``__file__`` at ``:34-39``; the inference input is edited
in-source, ``predict_hf.py:5-27``). SURVEY.md §5 calls for a real config layer
over seed, split, imputer-k, max_features, ensemble hparams, mesh shape, and
the sweep grid — this module is it.

All configs are frozen dataclasses so they are hashable and can be closed over
by ``jax.jit`` as static arguments.
"""

from __future__ import annotations

import dataclasses
import json
import typing
from typing import Any, Mapping, Sequence


@dataclasses.dataclass(frozen=True)
class GBDTConfig:
    """Gradient-boosted trees member (reference: ``train_ensemble_public.py:45``).

    The reference uses 100 depth-1 stumps, lr 0.1, binomial deviance,
    friedman_mse split scoring, no subsampling.
    """

    n_estimators: int = 100
    max_depth: int = 1
    learning_rate: float = 0.1
    # 'exact' enumerates sorted thresholds (parity with sklearn's BestSplitter);
    # 'hist' uses quantile-binned histograms (the scalable TPU path).
    splitter: str = "exact"
    n_bins: int = 256
    min_samples_split: int = 2
    min_samples_leaf: int = 1
    # CV-fold candidate protocol (gbdt.fit_folds / the pipeline's mesh fold
    # loop): False (default) derives split candidates once from the full
    # matrix — cheaper, with a documented <6e-3 meta-feature deviation from
    # sklearn's per-refit enumeration; True re-derives candidates from each
    # fold's own rows (reference-exact, costs a [k, n, F] binned tensor).
    per_fold_binning: bool = False
    # Histogram-statistics backend for the level-wise (depth ≥ 2) tree
    # grower: 'matmul' = per-feature one-hot MXU contractions
    # (ops.histogram.node_histograms_matmul — vmap-composable, exploits
    # per-feature bin widths), 'pallas' = the VMEM-accumulating kernel
    # (ops.pallas_histogram; measured on-chip at ~2× the XLA scatter-add —
    # v5e, 200k rows, K=8; see the bench artifact's pallas_onchip block),
    # 'xla' = segment_sum, 'auto' = matmul on TPU / xla elsewhere.
    histogram_backend: str = "auto"


@dataclasses.dataclass(frozen=True)
class SVCConfig:
    """RBF support-vector member (reference: ``train_ensemble_public.py:44``).

    Scaled-regime policy (SURVEY.md §7 "SVC on TPU"): the kernel matrix is
    O(n²), so above ``max_rows`` fit rows the member either trains on a
    deterministic stratified subsample of ``max_rows`` rows
    (``scale_policy='subsample'`` — the default; the GBDT/LR members still
    see every row, and they dominate the meta weights anyway, SURVEY.md
    §2.3) or refuses with a clear error (``scale_policy='error'``).
    """

    C: float = 1.0
    gamma: str | float = "scale"  # 'scale' → 1 / (n_features * X.var())
    class_weight: str | None = "balanced"
    probability: bool = True
    platt_cv: int = 5
    tol: float = 1e-3
    max_iter: int = 20_000
    # 8192² kernel + dual matrices ≈ 0.5 GB f32 — the 20k default measured
    # as a worker-killing ~3.2 GB+ on the single v5e; the SVC member also
    # carries the smallest meta weight (SURVEY §2.3: 0.41 of 5.13), so the
    # subsample cap costs the least of the three members.
    max_rows: int = 8_192
    scale_policy: str = "subsample"  # 'subsample' | 'error'
    predict_chunk_rows: int = 65_536  # bound the [chunk, n_sv] kernel at predict


@dataclasses.dataclass(frozen=True)
class LogRegConfig:
    """Logistic-regression members (reference: ``train_ensemble_public.py:46,48``)."""

    penalty: str = "l1"  # base member is l1/liblinear; meta learner is l2/lbfgs
    C: float = 1.0
    class_weight: str | None = "balanced"
    tol: float = 1e-5
    max_iter: int = 2_000


@dataclasses.dataclass(frozen=True)
class LassoSelectConfig:
    """LassoCV + SelectFromModel (reference: ``train_ensemble_public.py:51-52``).

    Scaled-regime policy (VERDICT r3 missing #2): the covariance-form CV
    solve is row-free, so the only O(n) footprint is the cohort itself plus
    the per-fold Gram passes. On a single device, above ``max_rows`` the
    stage either fits on a deterministic stratified subsample of
    ``max_rows`` rows (``scale_policy='subsample'``, the default) or
    refuses with a clear error (``'error'``). With a mesh, the Gram passes
    shard over 'data' (``parallel.select_trainer``) and the cap applies to
    the per-device row count instead.
    """

    cv_folds: int = 10  # num_xrsval, train_ensemble_public.py:29
    n_alphas: int = 100
    eps: float = 1e-3
    max_features: int = 17
    max_iter: int = 1_000
    tol: float = 1e-6
    # 20M rows × 64 f32 features ≈ 5.1 GB device-resident — comfortably
    # inside a 16 GB v5e with the [K, F, F] stats and FISTA state on top.
    max_rows: int = 20_000_000
    scale_policy: str = "subsample"  # 'subsample' | 'error'


@dataclasses.dataclass(frozen=True)
class ImputerConfig:
    """KNN imputation (reference: ``train_ensemble_public.py:37``).

    Scaled-regime policy: the donor distance matrix is O(n_query · n_fit),
    so the fit cohort is capped at ``max_donors`` rows (deterministic
    uniform subsample — 1-NN imputation quality saturates long before 10⁵
    donors) and ``transform`` processes queries in ``chunk_rows`` blocks.
    """

    n_neighbors: int = 1
    max_donors: int = 100_000
    chunk_rows: int = 8_192


@dataclasses.dataclass(frozen=True)
class StackingConfig:
    """Stacking orchestration (reference: ``train_ensemble_public.py:48``).

    cv=None in sklearn resolves to 5-fold stratified CV for classifiers; the
    meta learner sees one predict_proba column per binary base member.
    """

    cv_folds: int = 5
    passthrough: bool = False


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout for the TPU build (no reference analogue — SURVEY §2.5).

    Axes:
      data  — cohort rows (data parallelism; histogram partials psum over it)
      model — feature/bin tiles inside split search, and fold/member fan-out
    """

    data: int = 1
    model: int = 1
    axis_names: tuple[str, str] = ("data", "model")


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    """Top-level experiment: seed/split policy + every member's hparams."""

    seed: int = 2020  # init_rs, train_ensemble_public.py:30
    n_features_raw: int = 64
    imputer: ImputerConfig = ImputerConfig()
    select: LassoSelectConfig = LassoSelectConfig()
    gbdt: GBDTConfig = GBDTConfig()
    svc: SVCConfig = SVCConfig()
    logreg: LogRegConfig = LogRegConfig()
    meta: LogRegConfig = LogRegConfig(penalty="l2")
    stacking: StackingConfig = StackingConfig()
    mesh: MeshConfig = MeshConfig()

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, default=str)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ExperimentConfig":
        def build(tp, val):
            if dataclasses.is_dataclass(tp) and isinstance(val, Mapping):
                hints = typing.get_type_hints(tp)
                names = {f.name for f in dataclasses.fields(tp)}
                kwargs = {}
                for k, v in val.items():
                    if k not in names:
                        raise KeyError(f"unknown config key {k!r} for {tp.__name__}")
                    ftype = hints[k]
                    if dataclasses.is_dataclass(ftype):
                        v = build(ftype, v)
                    elif isinstance(v, list):
                        v = tuple(v)  # JSON has no tuples; all sequence fields are tuples
                    kwargs[k] = v
                return tp(**kwargs)
            return val

        return build(cls, dict(d))

    @classmethod
    def from_json(cls, s: str) -> "ExperimentConfig":
        return cls.from_dict(json.loads(s))


@dataclasses.dataclass(frozen=True)
class SweepConfig:
    """5-fold CV hyperparameter sweep grid (BASELINE.json config 4)."""

    n_estimators_grid: Sequence[int] = (25, 50, 100, 200)
    max_depth_grid: Sequence[int] = (1, 2, 3)
    cv_folds: int = 5
