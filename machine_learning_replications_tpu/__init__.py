"""machine_learning_replications_tpu — a TPU-native clinical-ML ensemble framework.

A ground-up JAX / XLA / Pallas re-design of the capabilities of the reference
repository ``PaulTFLi/Machine-Learning-Replications`` (the heart-failure
progression replication package, ``HF/train_ensemble_public.py`` /
``HF/predict_hf.py``): MAT-file ingestion, 1-NN imputation, LassoCV feature
selection, a stacking ensemble (StandardScaler→RBF-SVC, gradient-boosted
stumps, L1 logistic regression, logistic meta-learner), metrics/reporting,
and model persistence — all running on a TPU device mesh.

Nothing here is a port: the compute path is functional JAX (``jit`` /
``vmap`` / ``lax.scan`` / ``shard_map``), hot histogram work is a Pallas
kernel, host-side ingest is native C++ where the reference leaned on
scipy/sklearn's C internals, and persistence is Orbax pytree checkpoints.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

  L6  cli                      — train / predict / sweep entry points
  L5  eval (ops.metrics)       — device-side AUC / PR / report + Wald CI bands
  L4  models.stacking          — the ensemble graph, fit + predict_proba
  L3  models.feature_selection — LassoCV + top-k selection; models.knn_impute
  L2  data                     — .mat / synthetic ingest → sharded DeviceArrays
  L1  persist                  — Orbax pytrees + legacy-pickle import oracle
  L0  ops / native             — Pallas kernels, XLA collectives, C++ runtime
"""

__version__ = "0.4.0"  # major.round (round 4 of the continuous build)

from machine_learning_replications_tpu import config as config  # noqa: F401
