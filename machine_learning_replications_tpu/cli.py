"""Command-line entry points (L6').

The reference's "CLI" is two scripts edited in-source: ``python
train_ensemble_public.py`` (expects ``develop_data.mat`` +
``model_select_data.mat`` beside it, ``train_ensemble_public.py:34-39``)
and ``python predict_hf.py`` (17 variables hard-coded at ``:5-27``, model
path at ``:33``). Here the same flows — plus the framework's sweep and
import tools — are real subcommands of
``python -m machine_learning_replications_tpu``:

  train           load (or synthesize) cohorts → impute → select → fit the
                  stacking ensemble → report/AUC/plots → Orbax checkpoint
  predict         load a model (Orbax dir, or the reference pickle) and
                  print the probability for a patient (JSON or the built-in
                  ``predict_hf.py:5-27`` example)
  serve           micro-batched HTTP inference server over a warm bucketed
                  compile cache (/predict, /healthz, /metrics —
                  docs/SERVING.md)
  sweep           5-fold CV over the n_estimators × max_depth grid
                  (BASELINE.json config 4)
  import-sklearn  decode a legacy sklearn pickle → Orbax checkpoint

Hyperparameters come from an ``ExperimentConfig`` JSON (``--config``);
every flag the reference hard-codes has a config field (SURVEY.md §5
"Config / flag system").
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys

import numpy as np

from machine_learning_replications_tpu import __version__


def _load_cohort(args, which: str):
    """(X64, y) from a .mat path or the synthetic generator."""
    from machine_learning_replications_tpu import data

    path = getattr(args, which, None)
    if path:
        X, y, _ = data.load_data(path)
        return X, y
    n = args.synthetic
    # Two deterministic disjoint halves of n rows each (default 713, the
    # reference's fit-split size — SURVEY.md §2.2).
    X, y, _ = data.make_cohort(
        n=2 * n, seed=args.seed, missing_rate=args.missing_rate
    )
    half = slice(0, n) if which == "develop" else slice(n, 2 * n)
    return X[half], y[half]


def _config(args):
    from machine_learning_replications_tpu.config import ExperimentConfig

    if args.config:
        with open(args.config) as f:
            return ExperimentConfig.from_json(f.read())
    return ExperimentConfig()


def _build_mesh(args):
    """--mesh 'DATA[,MODEL]' or 'auto' → a device mesh (None without the
    flag). --distributed first brings up the multi-host runtime so the mesh
    spans every host's chips (single host: a harmless no-op)."""
    if args.distributed:
        from machine_learning_replications_tpu.parallel.distributed import (
            initialize_distributed,
        )

        up = initialize_distributed()
        print(
            "distributed runtime " + ("up" if up else "unavailable (single host)"),
            file=sys.stderr,
        )
    if not args.mesh:
        return None
    from machine_learning_replications_tpu.parallel import make_mesh

    if args.mesh == "auto":
        return make_mesh()
    parts = [int(p) for p in args.mesh.split(",")]
    if len(parts) == 1:
        parts.append(1)
    if len(parts) != 2:
        raise SystemExit(f"--mesh expects DATA[,MODEL] or 'auto', got {args.mesh!r}")
    return make_mesh(data=parts[0], model=parts[1])


@contextlib.contextmanager
def _observed(
    args, command: str, config_json: str | None = None,
    manifest_extra: dict | None = None,
):
    """Stand up the obs layer for one CLI run (docs/OBSERVABILITY.md):
    jax.monitoring accounting into the global registry, an active tracer
    when ``--trace-dir`` is given (Perfetto-loadable ``trace.json`` written
    on exit), an active journal when ``--journal`` is given (manifest
    first, then structured events, ``run_done``/``run_error`` last), and a
    root span named after the command so every stage nests under it.
    ``manifest_extra`` lands in the journal manifest — multi-worker serve
    stamps its worker id there so per-worker journals stay attributable."""
    from machine_learning_replications_tpu.obs import jaxmon, journal, spans

    tracer = jrn = None
    if getattr(args, "trace_dir", None) or getattr(args, "journal", None):
        jaxmon.install()
    # Construct everything that can fail (journal open) BEFORE touching the
    # process-global tracer/journal slots: a failed setup must not leave a
    # stale global absorbing later spans in in-process callers.
    if getattr(args, "journal", None):
        jrn = journal.RunJournal(
            args.journal, command=command, config_json=config_json,
            extra=manifest_extra,
        )
    if getattr(args, "trace_dir", None):
        tracer = spans.Tracer(process_name=f"mlr-tpu {command}")
    if jrn is not None:
        journal.set_journal(jrn)
    if tracer is not None:
        spans.set_tracer(tracer)
    try:
        with spans.span(command):
            yield
    except BaseException as exc:
        if jrn is not None:
            jrn.event("run_error", error=f"{type(exc).__name__}: {exc}")
        raise
    else:
        if jrn is not None:
            jrn.event(
                "run_done",
                jax_compiles=jaxmon.compile_count(),
                jax_compile_seconds=round(jaxmon.compile_seconds(), 3),
            )
    finally:
        if jrn is not None:
            journal.set_journal(None)
            jrn.close()
            print(f"journal written to {jrn.path}", file=sys.stderr)
        if tracer is not None:
            spans.set_tracer(None)
            path = tracer.write(os.path.join(args.trace_dir, "trace.json"))
            print(
                f"trace written to {path} (load at https://ui.perfetto.dev)",
                file=sys.stderr,
            )


def cmd_train(args) -> int:
    cfg = _config(args)
    with _observed(args, "train", config_json=cfg.to_json()):
        return _run_train(args, cfg)


def _run_train(args, cfg) -> int:
    import jax.numpy as jnp

    from machine_learning_replications_tpu.models import pipeline
    from machine_learning_replications_tpu.obs import spans
    from machine_learning_replications_tpu.utils import metrics

    mesh = _build_mesh(args)
    if mesh is not None:
        print(
            f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}",
            file=sys.stderr,
        )
    X_dev, y_dev = _load_cohort(args, "develop")
    X_sel, y_sel = _load_cohort(args, "select")

    with spans.span("fit_pipeline", rows=int(np.asarray(X_dev).shape[0])):
        params, info = pipeline.fit_pipeline(
            X_dev, y_dev, cfg, mesh=mesh, checkpoint_dir=args.resume_dir
        )
    print(f"selected {info['n_selected']} features", file=sys.stderr)

    with spans.span("evaluate") as sp:
        p1 = sp.block(pipeline.pipeline_predict_proba1(params, X_sel, mesh=mesh))
    p1 = np.asarray(p1)
    yy = (p1 > 0.5).astype(np.float64)  # train_ensemble_public.py:63
    rep = metrics.classification_report(jnp.asarray(y_sel), jnp.asarray(yy))
    print(metrics.report_text(rep))
    auc = float(metrics.roc_auc(jnp.asarray(y_sel), jnp.asarray(p1)))
    ap = float(metrics.average_precision(jnp.asarray(y_sel), jnp.asarray(p1)))
    print(f"AUC-ROC {auc:.4f}   average precision {ap:.4f}")

    if args.plots:
        from machine_learning_replications_tpu.utils import plots

        os.makedirs(args.plots, exist_ok=True)
        plots.roc_figure(
            y_sel, p1, out_path=os.path.join(args.plots, "roc.png")
        )
        plots.pr_figure(
            y_sel, p1, out_path=os.path.join(args.plots, "pr.png")
        )
        print(f"plots written to {args.plots}", file=sys.stderr)

    if args.save:
        from machine_learning_replications_tpu.persist import orbax_io

        orbax_io.save_model(args.save, params, aot=args.aot)
        print(
            "model checkpointed to "
            f"{args.save}{' (with AOT executable bundle)' if args.aot else ''}",
            file=sys.stderr,
        )
    return 0


def _load_patient(path: str | None) -> np.ndarray:
    """Patient JSON path → validated ``(1, 17)`` contract row (the built-in
    ``predict_hf.py:5-27`` example without a path)."""
    from machine_learning_replications_tpu.data.examples import (
        patient_row,
        validate_patient,
    )

    if not path:
        return patient_row()
    with open(path) as f:
        patient = json.load(f)
    try:
        return validate_patient(patient)
    except ValueError as exc:
        # The inference contract takes all 17 variables (predict_hf.py:5-27);
        # silently defaulting clinical inputs would be unsafe.
        raise SystemExit(str(exc))


def cmd_predict(args) -> int:
    with _observed(args, "predict"):
        return _run_predict(args)


def _run_predict(args) -> int:
    from machine_learning_replications_tpu.models import pipeline, stacking, tree
    from machine_learning_replications_tpu.obs import spans
    from machine_learning_replications_tpu.persist import load_inference_params

    x = _load_patient(args.patient)
    with spans.span("load_params") as sp:
        params = load_inference_params(model=args.model, pkl=args.pkl)
        sp.note(family=type(params).__name__)
    with spans.span("predict_proba"):
        if isinstance(params, pipeline.PipelineParams):
            # Full-pipeline checkpoints select their own lasso top-k columns —
            # route the contract row through impute → support mask → ensemble
            # (pipeline.pipeline_predict_proba1_contract).
            prob = float(pipeline.pipeline_predict_proba1_contract(params, x)[0])
        elif isinstance(params, tree.TreeEnsembleParams):
            # `sweep --save` checkpoints: a bare GBDT fit on the contractual
            # 17 columns (models.sweep trains on selected_indices() order).
            prob = float(tree.predict_proba1(params, x)[0])
        else:
            prob = float(stacking.predict_proba1(params, x)[0])

    # Output contract: predict_hf.py:38-40
    print(f"Probability of progressive HF is: {100.0 * prob:.2f} %")
    return 0


def _xla_cpu_intra_op_default(requested: int | None) -> int | None:
    """Satellite (ISSUE 7): a sane XLA intra-op thread default for CPU
    serving. The r11 campaign measured the default Eigen pool bursting
    across every core per flush and starving the event loop — with a
    small explicit pool every repeat holds 950+ qps where the default
    swings 670–1070. Applied via XLA_FLAGS, so it must run BEFORE jax is
    imported (and before the multi-worker fork, so children inherit it);
    returns the thread count actually applied (journaled in the serve
    manifest), or None when it could not or should not be applied — jax
    already imported (in-process callers), the operator already set the
    knobs in XLA_FLAGS, or an explicit 0 asked to leave XLA alone."""
    if requested is not None and requested < 0:
        raise SystemExit("--xla-intra-op-threads must be >= 0")
    if requested == 0:
        return None
    flags = os.environ.get("XLA_FLAGS", "")
    if "intra_op_parallelism_threads" in flags or \
            "xla_cpu_multi_thread_eigen" in flags:
        return None  # operator knows best
    if "jax" in sys.modules:
        # Too late: XLA read its flags at backend init. Honest no-op.
        if requested:
            print(
                "--xla-intra-op-threads ignored: jax already initialized "
                "in this process", file=sys.stderr,
            )
        return None
    cpus = os.cpu_count() or 2
    n = requested if requested else max(1, min(4, cpus // 2))
    # The exact incantation BENCH.md r11 measured: a bounded pool (single
    # thread on small hosts) instead of one burst across every core.
    os.environ["XLA_FLAGS"] = (flags + " " if flags else "") + (
        "--xla_cpu_multi_thread_eigen="
        + ("false" if n == 1 else "true")
        + f" intra_op_parallelism_threads={n}"
    )
    return n


def cmd_serve(args) -> int:
    """Micro-batched HTTP inference serving (docs/SERVING.md)."""
    worker_id = getattr(args, "_worker_id", None)
    if not hasattr(args, "_xla_threads"):
        # Before the fork AND before any jax import: every worker
        # inherits one consistent XLA thread policy.
        args._xla_threads = _xla_cpu_intra_op_default(
            args.xla_intra_op_threads
        )
        if args._xla_threads is not None:
            print(
                f"xla cpu intra-op threads: {args._xla_threads} "
                "(override with --xla-intra-op-threads, 0 leaves XLA "
                "alone)",
                file=sys.stderr,
            )
    if args.workers > 1 and args.admin_endpoint:
        # A deploy POST through the shared SO_REUSEPORT port would land
        # on ONE worker and leave the others on the old version — a
        # silently mixed-version replica. Until the parent fans deploys
        # out to every worker, multi-worker replicas deploy by restart.
        raise SystemExit(
            "--admin-endpoint is incompatible with --workers N: an "
            "in-place deploy would reach only one SO_REUSEPORT worker; "
            "deploy multi-worker replicas by rolling restart instead"
        )
    if args.workers > 1 and args.incident_dir:
        # N worker processes sharing one bundle directory would race the
        # timestamped dir names and each other's retention pruning; the
        # incident recorder stays a single-worker feature (alerts and
        # history themselves are per-process and stay on).
        raise SystemExit(
            "--incident-dir is not supported with --workers > 1: the "
            "capture directory is single-writer (run one worker, or "
            "capture at the router)"
        )
    if args.workers > 1 and worker_id is None:
        return _run_multiworker(args)
    buckets = tuple(int(b) for b in args.buckets.split(","))
    # The serve "config" for the manifest's config_hash: the knobs that
    # shape serving behavior, deterministically serialized. The worker id
    # is NOT part of it — all workers of one deployment share a config
    # hash; identity rides the manifest extra instead.
    serve_cfg = json.dumps({
        "buckets": list(buckets), "max_batch": args.max_batch,
        "max_wait_ms": args.max_wait_ms, "max_queue": args.max_queue,
        "request_timeout_s": args.request_timeout,
        "warmup": not args.no_warmup,
        "model": args.model, "pkl": args.pkl,
        "slo_latency_ms": args.slo_latency_ms,
        "slo_latency_target": args.slo_latency_target,
        "slo_availability_target": args.slo_availability_target,
        "no_slo": args.no_slo,
        "trace_capacity": args.trace_capacity,
        "tail_quantile": args.tail_quantile,
        "profile_dir": args.profile_dir,
        "no_quality": args.no_quality,
        "drift_warn_psi": args.drift_warn_psi,
        "drift_alert_psi": args.drift_alert_psi,
        "supervise": not args.no_supervise,
        "flush_deadline_s": args.flush_deadline_s,
        "breaker_failures": args.breaker_failures,
        "restart_backoff_s": args.restart_backoff_s,
        "restart_backoff_max_s": args.restart_backoff_max_s,
        "inject": sorted(args.inject or []),
        # The journaled audit record must state the ACTUAL exposure:
        # --inject implies the endpoint too.
        "fault_endpoint": bool(args.inject or args.fault_endpoint),
        "workers": args.workers,
        "idle_timeout_s": args.idle_timeout,
        "max_connections": args.max_connections,
        "host_path": not args.no_host_path,
        "host_workers": args.host_workers,
        "no_aot": args.no_aot,
        "replica_id": args.replica_id,
        "register": args.register,
        "admin_endpoint": args.admin_endpoint,
        # The thread count actually applied (None: left to XLA/operator)
        # — the bench-reproducibility knob r11 flagged, journaled so an
        # artifact can state the pool it ran under.
        "xla_intra_op_threads": args._xla_threads,
        "history_interval_s": args.history_interval,
        "alert_rules": args.alert_rules,
        "no_alerts": args.no_alerts,
        "incident_dir": args.incident_dir,
    }, sort_keys=True)
    extra = {}
    if worker_id is not None:
        extra.update(worker=worker_id, workers=args.workers)
    if args._xla_threads is not None:
        # Readable in the manifest, not just folded into config_hash: a
        # bench artifact must be able to STATE the pool it ran under.
        extra["xla_intra_op_threads"] = args._xla_threads
    with _observed(args, "serve", config_json=serve_cfg,
                   manifest_extra=extra or None):
        return _run_serve(args, buckets)


def _run_multiworker(args) -> int:
    """Pre-fork ``SO_REUSEPORT`` multi-worker serving: fork N children
    BEFORE anything touches jax (a forked initialized backend is
    undefined behavior), each binding the same port with ``SO_REUSEPORT``
    and running the full single-worker stack — engine-per-worker over the
    shared on-disk checkpoint. The parent only supervises: it forwards
    SIGTERM/SIGINT (each worker drains gracefully) and tears the fleet
    down if any worker dies unexpectedly, so a half-dead deployment never
    lingers. Per-worker journals get a ``.wK`` suffix and carry the
    worker id in their manifest; ``/metrics`` carries
    ``serve_worker_info{worker=K}``."""
    import signal

    if args.port == 0:
        # Port 0 would give every worker a DIFFERENT ephemeral port;
        # SO_REUSEPORT sharding needs one concrete shared port.
        raise SystemExit("--workers requires a fixed --port (not 0): "
                         "all workers bind the same SO_REUSEPORT port")
    children: list[int] = []
    for k in range(args.workers):
        pid = os.fork()
        if pid == 0:
            # Child: become worker k and run the normal serve path. Exit
            # via os._exit — a worker must never fall back into the
            # parent's supervision loop below.
            rc = 1
            try:
                args._worker_id = k
                if args.journal:
                    args.journal = f"{args.journal}.w{k}"
                if args.trace_dir:
                    args.trace_dir = os.path.join(args.trace_dir, f"w{k}")
                rc = cmd_serve(args)
            except SystemExit as exc:
                rc = exc.code if isinstance(exc.code, int) else 1
            except BaseException:
                import traceback

                traceback.print_exc()
                rc = 1
            finally:
                os._exit(rc or 0)
        children.append(pid)
    print(
        f"serving with {args.workers} SO_REUSEPORT workers on port "
        f"{args.port} (pids {children})",
        file=sys.stderr,
    )

    shutting_down = False

    def _forward(signum, frame):
        nonlocal shutting_down
        shutting_down = True
        for pid in children:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass

    signal.signal(signal.SIGTERM, _forward)
    signal.signal(signal.SIGINT, _forward)
    rc = 0
    alive = set(children)
    while alive:
        try:
            pid, status = os.waitpid(-1, 0)
        except InterruptedError:
            continue  # a forwarded signal interrupted the wait
        except ChildProcessError:
            break
        if pid not in alive:
            continue
        alive.discard(pid)
        code = (
            os.WEXITSTATUS(status) if os.WIFEXITED(status)
            else 128 + os.WTERMSIG(status)
        )
        rc = max(rc, code)
        if code != 0 and not shutting_down and alive:
            # One worker died outside a deliberate shutdown: take the
            # rest down too — a silently shrunken fleet would serve at
            # reduced capacity while looking healthy from the port.
            print(
                f"worker pid {pid} exited {code}; stopping the fleet",
                file=sys.stderr,
            )
            _forward(None, None)
    return rc


def _load_alert_rules(path):
    """Parse a ``--alert-rules`` JSON file, turning the rule engine's
    eager validation errors into the CLI's usage-error exit."""
    from machine_learning_replications_tpu.obs import alerts

    try:
        return alerts.load_rules(path)
    except (OSError, ValueError) as exc:
        raise SystemExit(f"--alert-rules: {exc}")


def _run_serve(args, buckets) -> int:
    import signal

    from machine_learning_replications_tpu.obs import slo
    from machine_learning_replications_tpu.resilience import faults
    from machine_learning_replications_tpu.serve import make_server

    # Arm injections BEFORE the model loads or the engine warms: the
    # persist.restore / engine.warmup faultpoints are part of the chaos
    # surface (docs/RESILIENCE.md).
    for spec in args.inject or []:
        try:
            armed = faults.arm(spec)
        except ValueError as exc:
            raise SystemExit(f"--inject: {exc}")
        print(f"fault armed: {armed.describe()}", file=sys.stderr)
    # The one-way endpoint enable is owned by make_server's fault_endpoint
    # parameter (passed below) — one code path for a security-relevant
    # switch.

    # Fleet identity (docs/FLEET.md): the checkpoint's monotonic version
    # id rides every reply as X-Model-Version; a pickle-imported model is
    # simply unversioned. Version AND bundle come from the directory that
    # ACTUALLY restored: a corrupt target rolls back to the retained
    # last-known-good, and labeling the lastgood's bits with the corrupt
    # target's version (or restoring ITS executables) would break the
    # one-bit-pattern-per-version fleet contract — the same invariant
    # ServerHandle.deploy_model keys on info["path"].
    model_version = None
    aot_bundle = None
    if args.model:
        from machine_learning_replications_tpu.persist import orbax_io

        params, restore_info = orbax_io.load_model_versioned(args.model)
        model_version = restore_info["version"]
        if not args.no_aot:
            # Published AOT executables (docs/AOT.md): warmup restores
            # instead of tracing. A checkpoint without a bundle — or
            # --no-aot — serves exactly as before.
            from machine_learning_replications_tpu.persist import (
                aot as aot_mod,
            )

            aot_bundle = aot_mod.load_bundle(restore_info["path"])
    else:
        from machine_learning_replications_tpu.persist import (
            load_inference_params,
        )

        params = load_inference_params(pkl=args.pkl)
    replica_id = args.replica_id
    handle = make_server(
        params,
        host=args.host,
        port=args.port,
        buckets=buckets,
        max_batch_size=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue,
        warmup=not args.no_warmup,
        request_timeout_s=args.request_timeout,
        quiet=not args.verbose,
        say=lambda m: print(m, file=sys.stderr),
        slos=(
            [] if args.no_slo else slo.default_slos(
                latency_ms=args.slo_latency_ms,
                latency_target=args.slo_latency_target,
                availability_target=args.slo_availability_target,
            )
        ),
        trace_capacity=args.trace_capacity,
        tail_quantile=args.tail_quantile,
        profile_dir=args.profile_dir,
        no_quality=args.no_quality,
        drift_warn_psi=args.drift_warn_psi,
        drift_alert_psi=args.drift_alert_psi,
        supervise=not args.no_supervise,
        flush_deadline_s=args.flush_deadline_s,
        breaker_failures=args.breaker_failures,
        restart_backoff_s=args.restart_backoff_s,
        restart_backoff_max_s=args.restart_backoff_max_s,
        fault_endpoint=bool(args.inject or args.fault_endpoint),
        idle_timeout_s=args.idle_timeout,
        max_connections=args.max_connections,
        # Multi-worker mode: every worker binds the same port with
        # SO_REUSEPORT; the kernel spreads connections across them.
        reuse_port=args.workers > 1,
        worker_id=getattr(args, "_worker_id", None),
        # Dual-path scoring is the production default: singles on an
        # idle server answer from the host fast path at single-digit-ms
        # p50, bursts coalesce into device micro-batches.
        host_path=not args.no_host_path,
        host_workers=args.host_workers,
        model_version=model_version,
        replica_id=replica_id,
        admin_endpoint=args.admin_endpoint,
        aot_bundle=aot_bundle,
        use_aot=not args.no_aot,
        history_interval_s=args.history_interval,
        alert_rules=(
            _load_alert_rules(args.alert_rules) if args.alert_rules
            else None
        ),
        alerts_enabled=not args.no_alerts,
        incident_dir=args.incident_dir,
        incident_min_interval_s=args.incident_min_interval,
        incident_retention=args.incident_retention,
    )
    # Serving-process GC hygiene (the Instagram pre-fork trick): the
    # warm startup heap — jax, XLA executables, the uploaded ensemble —
    # is permanent, and leaving it inside the collector's world makes
    # every gen-2 pass crawl millions of immortal objects mid-traffic.
    # Freeze it out once, after warmup built everything.
    import gc

    gc.collect()
    gc.freeze()

    host, port = handle.address
    if replica_id is None and (args.register or args.advertise):
        # Default id from the BOUND address, not args.port: with
        # --port 0 (ephemeral) every replica would otherwise register
        # as HOST:0 — same id, different urls — and each one's
        # heartbeat would replace the other in the registry forever.
        replica_id = f"{host}:{port}"
        handle.replica_id = replica_id
    wid = getattr(args, "_worker_id", None)
    print(
        f"serving {type(params).__name__} on http://{host}:{port} "
        f"(buckets {buckets}, max_wait {args.max_wait_ms}ms, "
        f"queue bound {args.max_queue}"
        + (f", worker {wid}/{args.workers}" if wid is not None else "")
        + ")",
        file=sys.stderr,
    )

    # Fleet registration: announce this replica to the front-door router
    # (fleet.router POST /fleet/replicas) on a background thread that
    # retries until the router answers — replicas and router may start in
    # any order. Multi-worker serve registers once (worker 0): the
    # SO_REUSEPORT workers share one port and are one logical replica.
    advertise = args.advertise or f"http://{host}:{port}"
    if args.register and getattr(args, "_worker_id", None) in (None, 0):
        import threading
        import time
        import urllib.request

        register_url = args.register.rstrip("/") + "/fleet/replicas"

        def _register_loop():
            # A heartbeat, not a one-shot: registration is idempotent
            # (same id + url keeps the router's rotation state), so
            # re-posting every beat means a RESTARTED router — whose
            # in-memory registry came up empty — repopulates within one
            # interval instead of serving "no ready replicas" until
            # every replica is manually bounced.
            body = json.dumps(
                {"id": replica_id, "url": advertise}
            ).encode()
            registered = False
            while not handle.draining:
                try:
                    urllib.request.urlopen(
                        urllib.request.Request(
                            register_url, data=body,
                            headers={"Content-Type": "application/json"},
                        ),
                        timeout=5,
                    ).read()
                except Exception:
                    registered = False
                    time.sleep(1.0)
                    continue
                if not registered:
                    registered = True
                    from machine_learning_replications_tpu.obs import (
                        journal,
                    )

                    journal.event(
                        "replica_registered", router=args.register,
                        replica=replica_id, url=advertise,
                    )
                    print(
                        f"registered with router {args.register} as "
                        f"{replica_id!r} ({advertise})",
                        file=sys.stderr,
                    )
                time.sleep(10.0)

        threading.Thread(
            target=_register_loop, name="serve-register", daemon=True
        ).start()

    def _graceful(signum, frame):
        print("draining and shutting down ...", file=sys.stderr)
        # shutdown() must not run on the signal-handling main thread while
        # serve_forever is blocked in it — hand it to a helper thread.
        import threading

        threading.Thread(target=handle.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    try:
        handle.serve_forever()
    finally:
        handle.shutdown()
        if args.register and getattr(args, "_worker_id", None) in (None, 0):
            # Best-effort deregistration: a drained replica should leave
            # the rotation table instead of waiting out probe failures.
            import urllib.request

            try:
                urllib.request.urlopen(
                    urllib.request.Request(
                        args.register.rstrip("/") + "/fleet/replicas",
                        data=json.dumps(
                            {"deregister": replica_id}
                        ).encode(),
                        headers={"Content-Type": "application/json"},
                    ),
                    timeout=5,
                ).read()
            except Exception:
                pass
    return 0


def cmd_score(args) -> int:
    """Population-scale bulk scoring (docs/SCORING.md): stream a cohort
    file through the overlapped ingest→device pipeline into sharded,
    resumable output."""
    if not hasattr(args, "_xla_threads"):
        # Unlike `serve` (latency-bound: the measured small-pool default
        # protects the event loop), bulk scoring is throughput-bound and
        # WANTS the whole machine — XLA is left alone unless the operator
        # bounds the pool explicitly. Must run before jax is imported.
        args._xla_threads = (
            _xla_cpu_intra_op_default(args.xla_intra_op_threads)
            if args.xla_intra_op_threads else None
        )
        if args._xla_threads is not None:
            print(
                f"xla cpu intra-op threads: {args._xla_threads}",
                file=sys.stderr,
            )
    score_cfg = json.dumps({
        "cohort": args.cohort, "format": args.format, "out": args.out,
        "model": args.model, "pkl": args.pkl,
        "chunk_rows": args.chunk_rows, "prefetch": args.prefetch,
        "parse_workers": args.parse_workers,
        "parse_procs": args.parse_procs,
        "rows_per_shard": args.rows_per_shard,
        "max_bad_rows": args.max_bad_rows,
        "sequential": args.sequential, "fresh": args.fresh,
        "limit": args.limit, "mesh": args.mesh,
        "no_quality": args.no_quality,
        "quality_window": args.quality_window,
        "drift_warn_psi": args.drift_warn_psi,
        "drift_alert_psi": args.drift_alert_psi,
        "no_fsync": args.no_fsync,
        "xla_intra_op_threads": args._xla_threads,
    }, sort_keys=True)
    with _observed(args, "score", config_json=score_cfg):
        return _run_score(args)


def _run_score(args) -> int:
    from machine_learning_replications_tpu.persist import (
        load_inference_params,
    )
    from machine_learning_replications_tpu.score import (
        ScoreBudgetExceeded,
        ScorePipeline,
        ScoreResumeError,
        open_cohort,
    )
    from machine_learning_replications_tpu.score.progress import params_digest

    mesh = _build_mesh(args)
    source = open_cohort(
        args.cohort, args.chunk_rows, fmt=args.format, limit=args.limit
    )
    params = load_inference_params(model=args.model, pkl=args.pkl)
    pipe = ScorePipeline(
        params,
        source,
        args.out,
        overlap=not args.sequential,
        parse_workers=args.parse_workers,
        parse_procs=args.parse_procs,
        prefetch=args.prefetch,
        rows_per_shard=args.rows_per_shard,
        max_bad_rows=args.max_bad_rows,
        mesh=mesh,
        fresh=args.fresh,
        durable=not args.no_fsync,
        quality=not args.no_quality,
        quality_window=args.quality_window,
        drift_warn_psi=args.drift_warn_psi,
        drift_alert_psi=args.drift_alert_psi,
        model_digest=params_digest(model=args.model, pkl=args.pkl),
    )
    try:
        summary = pipe.run()
    except ScoreResumeError as exc:
        raise SystemExit(f"score: {exc}")
    except ScoreBudgetExceeded as exc:
        print(f"score: ABORTED — {exc}", file=sys.stderr)
        print(
            f"quarantine sidecar: "
            f"{os.path.join(args.out, 'quarantine.jsonl')}",
            file=sys.stderr,
        )
        _write_score_metrics(args)
        return 2
    mode = "sequential" if args.sequential else (
        f"overlapped (parse_workers={args.parse_workers}, "
        f"prefetch={args.prefetch})"
    )
    stage = summary["stage_seconds"]
    print(
        f"scored {summary['rows']} rows in {summary['chunks']} chunks "
        f"({summary['bad_rows']} quarantined) — "
        f"{summary['rows_per_second']} rows/s end-to-end over "
        f"{summary['wall_seconds']}s wall, {mode}",
    )
    print(
        "stage busy seconds: " + ", ".join(
            f"{k} {v}" for k, v in stage.items()
        ),
        file=sys.stderr,
    )
    if summary.get("resumed"):
        print(
            f"resumed at chunk {summary['resumed_chunks']} "
            f"({summary['resumed_rows']} rows already committed)",
            file=sys.stderr,
        )
    q = summary.get("quality")
    if q and q.get("enabled", True):
        print(
            f"cohort quality: {q['status']} (score PSI "
            f"{q['score_psi']}, worst feature {q['worst_feature']} PSI "
            f"{q['worst_psi']}, {q['rows']} rows) — "
            f"{os.path.join(args.out, 'quality.json')}",
            file=sys.stderr,
        )
    print(
        f"output: {len(summary['shards'])} shard(s) in {args.out} "
        f"(sha256 {summary['output_sha256'][:16]}…)",
        file=sys.stderr,
    )
    _write_score_metrics(args)
    return 0


def _write_score_metrics(args) -> None:
    """--metrics-out: the run's final Prometheus exposition (score_*,
    quality_*, jax_* families), validator-clean by contract — CI pushes
    it through tools/validate_metrics.py."""
    if not args.metrics_out:
        return
    from machine_learning_replications_tpu.obs.registry import REGISTRY

    with open(args.metrics_out, "w") as f:
        f.write(REGISTRY.render_prometheus())
    print(f"metrics written to {args.metrics_out}", file=sys.stderr)


def cmd_fleet(args) -> int:
    """Fleet tier (docs/FLEET.md): front-door router, rolling deploys,
    the autoscaler daemon, and fleet status — the `cli fleet ROLE`
    entry points. All are jax-free: a router or autoscaler process
    needs no accelerator stack (the replicas it spawns pay that cost in
    their own processes)."""
    if args.role == "router":
        return _run_fleet_router(args)
    if args.role == "deploy":
        return _run_fleet_deploy(args)
    if args.role == "autoscale":
        return _run_fleet_autoscale(args)
    return _run_fleet_status(args)


def _run_fleet_router(args) -> int:
    import signal
    import threading

    from machine_learning_replications_tpu.fleet import make_router
    from machine_learning_replications_tpu.obs import journal

    replicas = []
    for spec in args.replica or []:
        rid, sep, url = spec.partition("=")
        if not sep or not rid or not url:
            raise SystemExit(
                f"--replica expects ID=URL, got {spec!r}"
            )
        replicas.append((rid, url))
    worker_id = getattr(args, "_worker_id", None)
    if args.workers > 1 and worker_id is None:
        return _run_router_multiworker(args)
    jrn = None
    if args.journal:
        # Deliberately not _observed: that path installs jax.monitoring
        # accounting, and the router must stay jax-free.
        jrn = journal.RunJournal(args.journal, command="fleet router")
        journal.set_journal(jrn)
    handle = make_router(
        host=args.host,
        port=args.port,
        replicas=replicas,
        request_timeout_s=args.request_timeout,
        hedge_ms=args.hedge_ms,
        max_attempts=args.max_attempts,
        probe_interval_s=args.probe_interval,
        probe_timeout_s=args.probe_timeout,
        fail_threshold=args.fail_threshold,
        recover_probes=args.recover_probes,
        breaker_failures=args.breaker_failures,
        reuse_port=args.workers > 1,
        quiet=not args.verbose,
        capture_dir=args.capture,
        capture_rows_per_shard=args.capture_rows_per_shard,
        capture_max_shards=args.capture_max_shards,
        history_interval_s=args.history_interval,
        alert_rules=(
            _load_alert_rules(args.alert_rules) if args.alert_rules
            else None
        ),
        alerts_enabled=not args.no_alerts,
        incident_dir=args.incident_dir,
        incident_min_interval_s=args.incident_min_interval,
        incident_retention=args.incident_retention,
    )
    host, port = handle.address
    who = f" (worker {worker_id})" if worker_id is not None else ""
    print(
        f"fleet router on http://{host}:{port}{who} "
        f"({len(replicas)} static replicas; POST /fleet/replicas to "
        "register more)",
        file=sys.stderr,
    )

    def _graceful(signum, frame):
        print("router shutting down ...", file=sys.stderr)
        threading.Thread(target=handle.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    try:
        handle.serve_forever()
    finally:
        handle.shutdown()
        if jrn is not None:
            journal.set_journal(None)
            jrn.close()
            print(f"journal written to {jrn.path}", file=sys.stderr)
    return 0


def _run_router_multiworker(args) -> int:
    """Pre-fork ``SO_REUSEPORT`` multi-worker routing for many-core
    hosts: N router processes each run their own loop (listener AND
    upstream pool) on one shared port; the kernel spreads inbound
    connections across them. Each worker keeps its own registry — the
    replicas' periodic registration heartbeats (fresh connection per
    beat, so the kernel rotates them across workers) converge every
    worker's membership within a few beats, and static ``--replica``
    seeds apply to all workers at fork. The parent only supervises,
    exactly like ``cli serve --workers``."""
    import signal

    if args.port == 0:
        raise SystemExit("--workers requires a fixed --port (not 0): "
                         "all workers bind the same SO_REUSEPORT port")
    if args.capture:
        # N workers appending to one rotating shard window would
        # interleave rotations and tear the capture contract; the tap
        # stays a single-worker feature.
        raise SystemExit("--capture is not supported with --workers > 1 "
                         "(run a single-worker capture router)")
    if args.incident_dir:
        # Same single-writer contract as --capture: timestamped bundle
        # dirs and retention pruning from N processes would race.
        raise SystemExit("--incident-dir is not supported with "
                         "--workers > 1 (run a single-worker alerting "
                         "router)")
    children: list[int] = []
    for k in range(args.workers):
        pid = os.fork()
        if pid == 0:
            rc = 1
            try:
                args._worker_id = k
                if args.journal:
                    args.journal = f"{args.journal}.w{k}"
                rc = _run_fleet_router(args)
            except SystemExit as exc:
                rc = exc.code if isinstance(exc.code, int) else 1
            except BaseException:
                import traceback

                traceback.print_exc()
                rc = 1
            finally:
                os._exit(rc or 0)
        children.append(pid)
    print(
        f"fleet router with {args.workers} SO_REUSEPORT workers on port "
        f"{args.port} (pids {children})",
        file=sys.stderr,
    )
    shutting_down = False

    def _forward(signum, frame):
        nonlocal shutting_down
        shutting_down = True
        for pid in children:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass

    signal.signal(signal.SIGTERM, _forward)
    signal.signal(signal.SIGINT, _forward)
    rc = 0
    alive = set(children)
    while alive:
        try:
            pid, status = os.waitpid(-1, 0)
        except InterruptedError:
            continue
        except ChildProcessError:
            break
        if pid not in alive:
            continue
        alive.discard(pid)
        code = (
            os.WEXITSTATUS(status) if os.WIFEXITED(status)
            else 128 + os.WTERMSIG(status)
        )
        rc = max(rc, code)
        if code != 0 and not shutting_down and alive:
            print(
                f"router worker pid {pid} exited {code}; stopping the "
                "rest", file=sys.stderr,
            )
            _forward(None, None)
    return rc


def _run_fleet_autoscale(args) -> int:
    """The elastic-fleet daemon (docs/FLEET.md "Elastic fleet"): watch
    the router's load signals, spawn/retire local replica processes
    through the drain-first lifecycle manager, replace crashed ones.
    jax-free — the spawned replicas bring their own accelerator stack."""
    import signal
    import threading
    import time

    from machine_learning_replications_tpu.fleet.autoscale import (
        AutoscaleDaemon,
        AutoscalePolicy,
        AutoscaleThresholds,
    )
    from machine_learning_replications_tpu.fleet.lifecycle import (
        LifecycleManager,
        ReplicaSpec,
        RouterClient,
    )
    from machine_learning_replications_tpu.obs import journal
    from machine_learning_replications_tpu.resilience import faults

    for spec_text in args.inject or []:
        try:
            armed = faults.arm(spec_text)
        except ValueError as exc:
            raise SystemExit(f"--inject: {exc}")
        print(f"fault armed: {armed.describe()}", file=sys.stderr)
    jrn = None
    if args.journal:
        # Not _observed: the autoscaler must stay jax-free (the router's
        # reasoning — no jax.monitoring hooks in this process).
        jrn = journal.RunJournal(args.journal, command="fleet autoscale")
        journal.set_journal(jrn)
    say = lambda m: print(f"autoscale: {m}", file=sys.stderr)  # noqa: E731
    spec = ReplicaSpec(
        model=args.model,
        register_url=args.router,
        host=args.replica_host,
        serve_args=tuple(args.serve_arg or []),
        journal_dir=args.replica_journal_dir,
        no_aot=args.no_aot,
    )
    try:
        manager = LifecycleManager(
            spec,
            RouterClient(args.router),
            min_replicas=args.min,
            max_replicas=args.max,
            ready_deadline_s=args.ready_deadline,
            drain_settle_s=args.drain_settle,
            term_deadline_s=args.term_deadline,
            respawn_backoff_s=args.respawn_backoff,
            respawn_backoff_max_s=args.respawn_backoff_max,
            say=say,
        )
        policy = AutoscalePolicy(
            thresholds=AutoscaleThresholds(
                out_queue_depth=args.out_queue_depth,
                out_latency_ms=args.out_latency_ms,
                out_shed_rate=args.out_shed_rate,
                out_burn_rate=args.out_burn_rate,
                in_queue_depth=args.in_queue_depth,
                in_latency_ms=args.in_latency_ms,
                in_shed_rate=args.in_shed_rate,
                in_burn_rate=args.in_burn_rate,
                out_alerts_active=args.out_alerts_active,
                in_alerts_active=args.in_alerts_active,
            ),
            min_replicas=args.min,
            max_replicas=args.max,
            breach_polls=args.breach_polls,
            idle_polls=args.idle_polls,
            cooldown_s=args.cooldown,
            step=args.step,
        )
    except ValueError as exc:
        # Bad bounds/thresholds are operator input, not a crash.
        raise SystemExit(f"fleet autoscale: {exc}")
    daemon = AutoscaleDaemon(
        args.router, manager, policy,
        poll_interval_s=args.poll_interval, say=say,
    )
    manager.scale_to(args.min)
    print(
        f"autoscaling {args.min}..{args.max} replicas of {args.model} "
        f"behind {args.router} (poll every {args.poll_interval:g}s)",
        file=sys.stderr,
    )
    stop = {"now": False}

    def _stop(signum, frame):
        stop["now"] = True
        print("autoscale: stopping after the current tick ...",
              file=sys.stderr)

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    try:
        daemon.run(stop_check=lambda: stop["now"],
                   max_ticks=args.max_ticks)
    finally:
        if args.leave_running:
            print(
                "autoscale: leaving managed replicas running "
                "(--leave-running)", file=sys.stderr,
            )
        else:
            # Default teardown takes the managed fleet down with the
            # daemon: orphaned children would keep serving unmanaged —
            # alive but outside every control loop this command exists
            # to provide.
            closer = threading.Thread(target=manager.close, daemon=True)
            closer.start()
            closer.join(timeout=args.term_deadline + args.drain_settle + 5)
        if args.metrics_out:
            from machine_learning_replications_tpu.obs.registry import (
                REGISTRY,
            )

            with open(args.metrics_out, "w") as f:
                f.write(REGISTRY.render_prometheus())
            print(f"metrics written to {args.metrics_out}",
                  file=sys.stderr)
        if jrn is not None:
            journal.set_journal(None)
            jrn.close()
            print(f"journal written to {jrn.path}", file=sys.stderr)
    return 0


def _run_fleet_deploy(args) -> int:
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        args.router.rstrip("/") + "/fleet/deploy",
        data=json.dumps({"model": args.model}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=args.timeout) as resp:
            report = json.loads(resp.read())["deploy"]
    except urllib.error.HTTPError as exc:
        body = exc.read()
        try:
            payload = json.loads(body)
        except ValueError:
            payload = None
        if not isinstance(payload, dict):
            raise SystemExit(
                f"deploy request failed (http {exc.code}): "
                f"{body[:200]!r}"
            )
        if exc.code == 409:
            # Single-flight refusal: the "deploy" in this body is the
            # OTHER rollout's live status (result "ok" from the moment
            # it starts) — treating it as ours would print success for
            # a deploy that never began.
            raise SystemExit(
                "deploy refused: a rolling deploy is already in "
                "progress — watch it with `fleet status`:\n"
                + json.dumps(payload.get("deploy"), indent=1)
            )
        report = payload.get("deploy")
        if not isinstance(report, dict):
            raise SystemExit(
                f"deploy request failed (http {exc.code}): "
                f"{body[:200]!r}"
            )
    except (urllib.error.URLError, OSError) as exc:
        # Unreachable router / reset / client-side timeout: a clean exit
        # beats a traceback. NOTE a timed-out POST does not stop the
        # rollout server-side — `fleet status` shows where it got to.
        raise SystemExit(
            f"deploy request to {args.router} failed: {exc} "
            "(the rollout may still be running; check `fleet status`)"
        )
    print(json.dumps(report, indent=1))
    if report.get("result") != "ok":
        print(
            f"rollout {report.get('result')}: "
            f"{report.get('error', 'no detail')}",
            file=sys.stderr,
        )
        return 1
    print(
        f"rollout ok: version {report.get('target_version')} on "
        f"{len(report.get('replicas', []))} replicas",
        file=sys.stderr,
    )
    return 0


def _run_fleet_status(args) -> int:
    import urllib.error
    import urllib.request

    base = args.router.rstrip("/")
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
            health = json.loads(resp.read())
        with urllib.request.urlopen(
            base + "/fleet/replicas", timeout=10
        ) as resp:
            replicas = json.loads(resp.read())["replicas"]
    except (urllib.error.URLError, OSError) as exc:
        raise SystemExit(f"fleet status request to {args.router} failed: {exc}")
    print(json.dumps({"router": health, "replicas": replicas}, indent=1))
    return 0


def _learn_thresholds(args):
    from machine_learning_replications_tpu.learn.shadow import (
        ShadowThresholds,
    )

    return ShadowThresholds(
        max_divergence_mean=args.max_divergence_mean,
        max_divergence_p95=args.max_divergence_p95,
        max_flip_rate=args.max_flip_rate,
        max_score_psi=args.max_score_psi,
        max_candidate_psi=args.max_candidate_psi,
        max_disagreement_delta=args.max_disagreement_delta,
        min_rows=args.shadow_min_rows,
        require_candidate_profile=not args.allow_no_profile,
    )


def cmd_learn(args) -> int:
    """Continual learning (docs/CONTINUAL.md): drift-triggered retraining,
    shadow evaluation, and guarded promotion — the `cli learn ROLE`
    entry points over the `learn/` subsystem."""
    if args.role == "status":
        return _run_learn_status(args)  # jax-free: keep it snappy
    cfg = _config(args) if getattr(args, "config", None) else None
    learn_cfg = json.dumps({
        "role": args.role,
        "model": getattr(args, "model", None),
        "capture": getattr(args, "capture", None),
        "candidate": getattr(args, "candidate", None),
        "router": getattr(args, "router", None),
    }, sort_keys=True)
    with _observed(args, f"learn {args.role}", config_json=learn_cfg):
        if args.role == "run":
            return _run_learn_loop(args, cfg)
        if args.role == "retrain":
            return _run_learn_retrain(args, cfg)
        if args.role == "shadow":
            return _run_learn_shadow(args)
        return _run_learn_promote(args)


def _candidate_default(model: str) -> str:
    return os.path.abspath(model).rstrip(os.sep) + ".candidate"


def _run_learn_loop(args, cfg) -> int:
    from machine_learning_replications_tpu.learn.loop import LearnLoop
    from machine_learning_replications_tpu.learn.trigger import (
        TriggerPolicy,
    )

    loop = LearnLoop(
        model_path=args.model,
        capture_dir=args.capture,
        candidate_dir=args.candidate or _candidate_default(args.model),
        router_url=args.router,
        policy=TriggerPolicy(
            alert_streak=args.alert_streak,
            cooldown_s=args.cooldown,
            schedule_s=args.schedule,
        ),
        cfg=cfg,
        thresholds=_learn_thresholds(args),
        poll_interval_s=args.poll_interval,
        max_rows=args.rows,
        min_rows=args.min_rows,
        recovery_timeout_s=args.recovery_timeout,
        settle_timeout_s=args.settle_timeout,
        say=lambda m: print(f"learn: {m}", file=sys.stderr),
    )
    import signal

    stop = {"now": False}

    def _stop(signum, frame):
        stop["now"] = True
        print("learn: stopping after the current poll ...", file=sys.stderr)

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    cycles = loop.run(
        max_cycles=args.max_cycles, stop_check=lambda: stop["now"]
    )
    print(json.dumps({"cycles": cycles}, indent=1, default=str))
    if args.max_cycles and len(cycles) < args.max_cycles:
        return 1  # interrupted before the demanded cycles completed
    bad = [c for c in cycles if c["outcome"] in ("failed",)]
    return 1 if bad else 0


def _run_learn_retrain(args, cfg) -> int:
    from machine_learning_replications_tpu.learn import capture as capmod
    from machine_learning_replications_tpu.learn.retrain import warm_refit
    from machine_learning_replications_tpu.persist import orbax_io

    X17, n_bad = capmod.load_recent(args.capture, max_rows=args.rows)
    print(
        f"captured cohort: {X17.shape[0]} rows "
        f"({n_bad} malformed dropped)",
        file=sys.stderr,
    )
    live = orbax_io.load_model(args.model)
    out = args.candidate or _candidate_default(args.model)
    try:
        _params, info = warm_refit(
            live, X17, out, cfg=cfg,
            resume_dir=args.resume_dir, min_rows=args.min_rows,
        )
    except (ValueError, TypeError) as exc:
        raise SystemExit(f"learn retrain: {exc}")
    print(json.dumps(info, indent=1))
    return 0


def _run_learn_shadow(args) -> int:
    from machine_learning_replications_tpu.learn import capture as capmod
    from machine_learning_replications_tpu.learn import shadow as shadowmod
    from machine_learning_replications_tpu.persist import orbax_io

    X17, n_bad = capmod.load_recent(args.capture, max_rows=args.rows)
    live = orbax_io.load_model(args.model)
    candidate_dir = args.candidate or _candidate_default(args.model)
    candidate = orbax_io.load_model(candidate_dir)
    verdict = shadowmod.evaluate(
        live, candidate, X17,
        thresholds=_learn_thresholds(args),
        candidate_version=orbax_io.checkpoint_version(candidate_dir),
    )
    line = json.dumps(verdict, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        print(f"verdict written to {args.out}", file=sys.stderr)
    return 0 if verdict["pass"] else 1


def _run_learn_promote(args) -> int:
    from machine_learning_replications_tpu.learn import promote as promod

    candidate_dir = args.candidate or _candidate_default(args.model)
    if args.verdict:
        with open(args.verdict) as f:
            verdict = json.load(f)
    else:
        raise SystemExit(
            "learn promote: pass --verdict VERDICT.json (from `learn "
            "shadow --out`) — promotion without a shadow verdict is "
            "exactly the unguarded swap this gate exists to prevent"
        )
    result = promod.promote(
        candidate_dir, args.model, args.router, verdict,
        deploy_timeout_s=args.timeout, aot=not args.no_aot,
    )
    print(json.dumps(result, indent=1))
    return 0 if result["result"] == "promoted" else 1


def _run_learn_status(args) -> int:
    import urllib.error
    import urllib.request

    from machine_learning_replications_tpu.learn.trigger import (
        poll_quality,
        replica_urls,
    )

    base = args.router.rstrip("/")
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
            health = json.loads(resp.read())
        urls = replica_urls(args.router)
    except (urllib.error.URLError, OSError) as exc:
        raise SystemExit(
            f"learn status request to {args.router} failed: {exc}"
        )
    status = {
        "router": health,
        "capture": health.get("capture"),
        "replicas": {url: poll_quality(url) for url in urls},
    }
    if args.candidate:
        from machine_learning_replications_tpu.fleet.deploy import (
            manifest_version,
        )
        from machine_learning_replications_tpu.learn.promote import (
            REFUSED_FILE,
            is_parked,
        )

        cand = os.path.abspath(args.candidate)
        status["candidate"] = {
            "path": cand,
            "exists": os.path.isdir(cand),
            "version": manifest_version(cand),
            "parked": is_parked(cand),
            "refused_file": (
                os.path.join(cand, REFUSED_FILE) if is_parked(cand)
                else None
            ),
        }
    print(json.dumps(status, indent=1))
    return 0


def cmd_sweep(args) -> int:
    from machine_learning_replications_tpu.config import SweepConfig
    from machine_learning_replications_tpu.data.schema import selected_indices
    from machine_learning_replications_tpu.models import knn_impute, sweep

    import jax.numpy as jnp

    # Mesh (and --distributed bring-up) BEFORE any JAX computation:
    # jax.distributed.initialize must precede backend init, and the
    # imputation below touches the backend (cmd_train orders it the same).
    mesh = _build_mesh(args)

    X64, y = _load_cohort(args, "develop")
    if np.isnan(X64).any():
        _, X64 = knn_impute.fit_transform(jnp.asarray(X64))
        X64 = np.asarray(X64)
    X = X64[:, selected_indices()]

    cfg = SweepConfig(
        n_estimators_grid=tuple(args.n_estimators),
        max_depth_grid=tuple(args.max_depth),
        cv_folds=args.folds,
    )
    res = sweep.cv_sweep(X, y, cfg, mesh=mesh)
    print(f"{'depth':>6} " + " ".join(f"m={m:>5d}" for m in res.n_estimators_grid))
    for di, d in enumerate(res.max_depth_grid):
        print(
            f"{d:>6} "
            + " ".join(f"{a:7.4f}" for a in res.mean_auc[di])
        )
    print(
        f"best: n_estimators={res.best_n_estimators} "
        f"max_depth={res.best_max_depth} mean AUC={res.best_mean_auc:.4f}"
    )
    if args.save:
        from machine_learning_replications_tpu.persist import orbax_io

        params, _ = sweep.refit_best(X, y, res, mesh=mesh)
        orbax_io.save_model(args.save, params)
        print(f"refit best model checkpointed to {args.save}", file=sys.stderr)
    return 0


def cmd_import_sklearn(args) -> int:
    from machine_learning_replications_tpu.persist import (
        REFERENCE_PKL_PATH,
        decode_pickle,
        import_stacking,
        orbax_io,
    )

    pkl = args.pkl or REFERENCE_PKL_PATH
    params = import_stacking(decode_pickle(pkl))
    orbax_io.save_model(args.out, params, aot=args.aot)
    print(
        f"imported {pkl} -> {args.out}"
        + (" (with AOT executable bundle)" if args.aot else "")
    )
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m machine_learning_replications_tpu",
        description=__doc__.split("\n\n")[0],
    )
    ap.add_argument(
        "--version", action="version",
        version=f"machine-learning-replications-tpu {__version__}",
    )
    sub = ap.add_subparsers(dest="command", required=True)

    def add_cohort_flags(p):
        p.add_argument("--develop", help=".mat path of the development cohort")
        p.add_argument("--select", help=".mat path of the model-select cohort")
        p.add_argument(
            "--synthetic", type=int, default=713,
            help="rows per cohort when no .mat is given — two disjoint "
            "halves of this size (Table-S1-matched generator; default 713, "
            "the reference fit-split size)",
        )
        p.add_argument("--missing-rate", type=float, default=0.03)
        p.add_argument("--seed", type=int, default=2020)
        p.add_argument("--config", help="ExperimentConfig JSON path")

    def add_obs_flags(p):
        p.add_argument(
            "--trace-dir", default=None,
            help="write a Perfetto-loadable Chrome-trace JSON of this "
            "run's spans to <dir>/trace.json (load at "
            "https://ui.perfetto.dev; docs/OBSERVABILITY.md)",
        )
        p.add_argument(
            "--journal", default=None,
            help="JSONL run-journal path: first record is a run manifest "
            "(run id, git sha, jax/platform versions, config hash), then "
            "structured stage/checkpoint/flush events",
        )

    def add_alerting_flags(p, role: str):
        p.add_argument(
            "--history-interval", type=float, default=10.0,
            metavar="SECONDS",
            help="in-process metrics history sampling interval for "
            "/debug/history and alert evaluation (0 disables the whole "
            "history/alerting plane; docs/OBSERVABILITY.md)",
        )
        p.add_argument(
            "--alert-rules", default=None, metavar="FILE",
            help="JSON alert-rule file (list of rule specs) replacing "
            f"the built-in {role} defaults; rules evaluate against the "
            "sampled history every tick",
        )
        p.add_argument(
            "--no-alerts", action="store_true",
            help="sample history but evaluate no alert rules "
            "(/debug/history stays available, alert state is empty)",
        )
        p.add_argument(
            "--incident-dir", default=None, metavar="DIR",
            help="capture an incident bundle (alert + history window + "
            "request tail + journal tail) into DIR when a rule fires; "
            "off by default — firing alerts then only journal",
        )
        p.add_argument(
            "--incident-min-interval", type=float, default=60.0,
            metavar="SECONDS",
            help="minimum seconds between incident captures (rate limit "
            "so a flapping rule cannot fill the disk)",
        )
        p.add_argument(
            "--incident-retention", type=int, default=8,
            help="complete incident bundles retained in --incident-dir "
            "(oldest pruned first)",
        )

    def add_mesh_flags(p, what: str):
        p.add_argument(
            "--mesh", default=None,
            help="device-mesh shape DATA[,MODEL] (e.g. 8 or 4,2) or 'auto' "
            f"(all devices on the data axis); {what}",
        )
        p.add_argument(
            "--distributed", action="store_true",
            help="bring up jax.distributed (multi-host) before building "
            "the mesh",
        )

    t = sub.add_parser("train", help="fit the full pipeline and evaluate")
    add_cohort_flags(t)
    t.add_argument("--save", help="Orbax checkpoint directory to write")
    t.add_argument(
        "--aot", action="store_true",
        help="export the AOT executable bundle into --save (docs/AOT.md): "
        "pays the serving ladder's compile bill once at publish so every "
        "replica restores executables instead of tracing at warmup",
    )
    t.add_argument("--plots", help="directory for roc.png / pr.png")
    add_mesh_flags(
        t, "routes the GBDT member through the row-sharded trainers"
    )
    t.add_argument(
        "--resume-dir", default=None,
        help="stage-checkpoint directory: each pipeline stage (impute → "
        "select → members → meta) is durably checkpointed so a preempted "
        "run re-entered with the same data/config resumes instead of "
        "restarting (the dir is fingerprinted against its inputs)",
    )
    add_obs_flags(t)
    t.set_defaults(fn=cmd_train)

    p = sub.add_parser("predict", help="single-patient inference")
    p.add_argument("--model", help="Orbax checkpoint dir from `train --save`")
    p.add_argument("--pkl", help="legacy sklearn pickle (default: the reference artifact)")
    p.add_argument("--patient", help="patient JSON file (default: predict_hf.py example)")
    add_obs_flags(p)
    p.set_defaults(fn=cmd_predict)

    v = sub.add_parser(
        "serve",
        help="micro-batched HTTP inference server (/predict, /healthz, /metrics)",
    )
    v.add_argument("--model", help="Orbax checkpoint dir from `train --save`")
    v.add_argument("--pkl", help="legacy sklearn pickle (default: the reference artifact)")
    v.add_argument("--host", default="127.0.0.1")
    v.add_argument("--port", type=int, default=8000)
    v.add_argument(
        "--buckets", default="1,8,32,64,128,256,512",
        help="compiled batch-size ladder (comma-separated, ascending); "
        "every flush runs as the cheapest covering sequence of buckets "
        "(best-fit sub-batches instead of padding mid-size batches into "
        "one oversized bucket) and the jit cache stays bounded at one "
        "executable per bucket",
    )
    v.add_argument(
        "--max-batch", type=int, default=None,
        help="micro-batch flush size (default: 64 on the CPU backend — "
        "the BENCH.md-measured sweet spot; the largest bucket on device "
        "backends)",
    )
    v.add_argument(
        "--max-wait-ms", type=float, default=5.0,
        help="max time the oldest queued request waits for batch-mates",
    )
    v.add_argument(
        "--max-queue", type=int, default=1024,
        help="admission-queue bound; requests beyond it are shed with an "
        "explicit 503 'overloaded' reply instead of queueing unboundedly "
        "(keep above the largest bucket or full batches can never form)",
    )
    v.add_argument(
        "--request-timeout", type=float, default=30.0,
        help="per-request reply deadline (seconds)",
    )
    v.add_argument(
        "--no-warmup", action="store_true",
        help="skip the startup compile of every bucket (first requests "
        "then pay the XLA compiles)",
    )
    v.add_argument(
        "--workers", type=int, default=1,
        help="pre-fork N worker processes, each binding the same port "
        "with SO_REUSEPORT and running its own event loop + engine over "
        "the shared on-disk checkpoint (requires a fixed --port; "
        "docs/SERVING.md 'Transport architecture')",
    )
    v.add_argument(
        "--idle-timeout", type=float, default=5.0,
        help="seconds a keep-alive connection may sit idle (or park a "
        "partial slow-loris request) before the event loop reaps it",
    )
    v.add_argument(
        "--max-connections", type=int, default=8192,
        help="concurrent-connection cap per worker (fd protection; "
        "admission control proper is --max-queue)",
    )
    v.add_argument(
        "--slo-latency-ms", type=float, default=250.0,
        help="latency SLO threshold: the target fraction of requests must "
        "answer within this many milliseconds (burn gauges on /metrics; "
        "docs/OBSERVABILITY.md)",
    )
    v.add_argument(
        "--slo-latency-target", type=float, default=0.99,
        help="latency SLO target fraction (0, 1)",
    )
    v.add_argument(
        "--slo-availability-target", type=float, default=0.999,
        help="availability SLO target fraction: admitted requests answered "
        "without shed/timeout/error",
    )
    v.add_argument(
        "--no-slo", action="store_true",
        help="disable SLO tracking (no slo_* families on /metrics)",
    )
    v.add_argument(
        "--trace-capacity", type=int, default=256,
        help="flight-recorder bound: max tail-sampled request traces held "
        "for /debug/requests",
    )
    v.add_argument(
        "--tail-quantile", type=float, default=0.99,
        help="tail-sampling threshold: an ok request is kept only when its "
        "latency reaches this quantile of recent ok traffic (failures are "
        "always kept)",
    )
    v.add_argument(
        "--profile-dir", default=None,
        help="directory for /debug/profile captures (default: a "
        "per-process dir under the system temp dir)",
    )
    v.add_argument(
        "--no-quality", action="store_true",
        help="disable model-quality drift monitoring even when the "
        "checkpoint carries a training reference profile (no quality_* "
        "families, /debug/quality reports disabled)",
    )
    v.add_argument(
        "--drift-warn-psi", type=float, default=0.1,
        help="PSI at or above which drift status becomes 'warn' (0.1 is "
        "the industry convention: the population is moving; "
        "docs/OBSERVABILITY.md 'Model quality')",
    )
    v.add_argument(
        "--drift-alert-psi", type=float, default=0.25,
        help="PSI at or above which drift status becomes 'alert' (served "
        "cohort no longer resembles the training cohort)",
    )
    v.add_argument(
        "--no-supervise", action="store_true",
        help="run the engine bare: no watchdog deadline, no circuit "
        "breaker, no supervised restart (docs/RESILIENCE.md)",
    )
    v.add_argument(
        "--flush-deadline-s", type=float, default=20.0,
        help="watchdog deadline per flushed compute; a compute that "
        "misses it is abandoned as wedged and the breaker opens",
    )
    v.add_argument(
        "--breaker-failures", type=int, default=3,
        help="consecutive compute failures that open the circuit breaker "
        "(degraded mode: /predict sheds 503 + Retry-After while the "
        "engine restarts)",
    )
    v.add_argument(
        "--restart-backoff-s", type=float, default=0.5,
        help="initial supervised-restart backoff (doubles per attempt)",
    )
    v.add_argument(
        "--restart-backoff-max-s", type=float, default=30.0,
        help="supervised-restart backoff cap",
    )
    v.add_argument(
        "--inject", action="append", metavar="SPEC", default=None,
        help="arm a faultpoint (repeatable): SITE:MODE[=ARG][@OPTS], e.g. "
        "engine.compute:raise@n=5 or batcher.flush:delay=0.5@p=0.1,seed=7 "
        "— also enables the /debug/faults endpoint "
        "(docs/RESILIENCE.md faultpoint catalog)",
    )
    v.add_argument(
        "--fault-endpoint", action="store_true",
        help="enable the guarded /debug/faults chaos endpoint without "
        "arming anything at startup",
    )
    v.add_argument(
        "--no-aot", action="store_true",
        help="ignore published AOT executable bundles and always "
        "trace+compile at warmup (and on later /admin/deploy swaps) — "
        "the operator escape hatch for a bad serialized artifact "
        "(docs/AOT.md; AOT restore itself already fails open to "
        "tracing per bucket)",
    )
    v.add_argument(
        "--no-host-path", action="store_true",
        help="disable the adaptive host fast path (dual-path scoring, "
        "docs/SERVING.md): every request then goes through the "
        "micro-batcher and the device engine",
    )
    v.add_argument(
        "--host-workers", type=int, default=1,
        help="host fast-path worker threads (one in-flight single-row "
        "score each; a busy host path routes back to the device)",
    )
    v.add_argument(
        "--xla-intra-op-threads", type=int, default=None,
        help="XLA CPU intra-op thread-pool size (default: a host-sized "
        "value, min(4, cores/2) with a floor of 1 — the r11-measured fix "
        "for the default pool starving the event loop; 0 leaves XLA "
        "alone; ignored when XLA_FLAGS already sets the knobs). The "
        "applied value is journaled in the serve manifest",
    )
    v.add_argument(
        "--replica-id", default=None,
        help="fleet identity echoed on every reply as X-Replica and on "
        "the health probes (default when registering: HOST:PORT; "
        "docs/FLEET.md)",
    )
    v.add_argument(
        "--register", default=None, metavar="ROUTER_URL",
        help="self-register with a fleet router (POST /fleet/replicas), "
        "retrying until it answers; deregisters on graceful shutdown. "
        "With --workers N only worker 0 registers (one shared port = "
        "one logical replica)",
    )
    v.add_argument(
        "--advertise", default=None, metavar="URL",
        help="the URL the router should reach this replica at (default "
        "http://HOST:PORT — override when behind NAT or a hostname)",
    )
    v.add_argument(
        "--admin-endpoint", action="store_true",
        help="enable the guarded /admin/deploy warm-swap endpoint "
        "(rolling deploys, docs/FLEET.md); off by default for the same "
        "reason /debug/faults is",
    )
    v.add_argument("--verbose", action="store_true", help="log each request")
    add_alerting_flags(v, "replica")
    add_obs_flags(v)
    v.set_defaults(fn=cmd_serve)

    f = sub.add_parser(
        "fleet",
        help="fleet tier: front-door router, rolling deploys, status "
        "(docs/FLEET.md)",
    )
    fsub = f.add_subparsers(dest="role", required=True)
    fr = fsub.add_parser(
        "router",
        help="run the front-door router: replica registry, /readyz-driven "
        "rotation, retry/hedging, /fleet control plane",
    )
    fr.add_argument("--host", default="127.0.0.1")
    fr.add_argument("--port", type=int, default=8080)
    fr.add_argument(
        "--replica", action="append", metavar="ID=URL", default=None,
        help="seed the registry with a static replica (repeatable); "
        "replicas may also self-register via `cli serve --register`",
    )
    fr.add_argument(
        "--request-timeout", type=float, default=30.0,
        help="router-side reply deadline per request (seconds); an "
        "inbound X-Request-Deadline-Ms tightens it, never loosens",
    )
    fr.add_argument(
        "--hedge-ms", type=float, default=250.0,
        help="fire a duplicate attempt against a second replica when the "
        "first has not answered within this delay (0 disables hedging)",
    )
    fr.add_argument(
        "--max-attempts", type=int, default=3,
        help="upstream attempts per request (first + retries/hedges)",
    )
    fr.add_argument(
        "--probe-interval", type=float, default=0.5,
        help="seconds between /readyz probe passes",
    )
    fr.add_argument(
        "--probe-timeout", type=float, default=2.0,
        help="per-probe HTTP timeout",
    )
    fr.add_argument(
        "--fail-threshold", type=int, default=2,
        help="consecutive failed probes before rotation out (an explicit "
        "not-ready rotates out on the first probe)",
    )
    fr.add_argument(
        "--recover-probes", type=int, default=2,
        help="consecutive ready probes before an out replica re-enters "
        "rotation",
    )
    fr.add_argument(
        "--breaker-failures", type=int, default=3,
        help="consecutive request failures that open a replica's breaker "
        "(immediate rotation out; probes close it)",
    )
    fr.add_argument(
        "--workers", type=int, default=1,
        help="pre-fork N SO_REUSEPORT router processes on the shared "
        "--port for many-core hosts; each worker owns its own event "
        "loop (listener + upstream pool) and registry, converging "
        "membership through the replicas' registration heartbeats",
    )
    fr.add_argument(
        "--journal", default=None,
        help="JSONL journal path (registration, rotation, deploy arc)",
    )
    fr.add_argument(
        "--capture", default=None, metavar="DIR",
        help="continual-learning cohort tap (docs/CONTINUAL.md): append "
        "every served /predict body to a bounded rotating JSONL window "
        "in DIR — the `cli learn` retrain's data source",
    )
    fr.add_argument(
        "--capture-rows-per-shard", type=int, default=4096,
        help="capture shard rotation size (rows)",
    )
    fr.add_argument(
        "--capture-max-shards", type=int, default=8,
        help="capture shards retained (older ones are unlinked; the "
        "window is ~rows-per-shard x max-shards recent rows)",
    )
    add_alerting_flags(fr, "router")
    fr.add_argument("--verbose", action="store_true")
    fr.set_defaults(fn=cmd_fleet)
    fd = fsub.add_parser(
        "deploy",
        help="rolling deploy: drive a new checkpoint version across the "
        "fleet through the router, one replica at a time",
    )
    fd.add_argument("--router", required=True, help="router base URL")
    fd.add_argument(
        "--model", required=True,
        help="checkpoint directory (every replica must be able to read "
        "this path)",
    )
    fd.add_argument(
        "--timeout", type=float, default=1800.0,
        help="end-to-end rollout timeout (seconds)",
    )
    fd.set_defaults(fn=cmd_fleet)
    fa = fsub.add_parser(
        "autoscale",
        help="elastic-fleet daemon: watch the router's load signals and "
        "grow/shrink local replica processes with drain-first "
        "retirement and crash replacement (docs/FLEET.md)",
    )
    fa.add_argument("--router", required=True, help="router base URL")
    fa.add_argument(
        "--model", required=True,
        help="checkpoint directory every spawned replica serves",
    )
    fa.add_argument(
        "--min", type=int, default=1,
        help="minimum replica count (the daemon spawns up to this at "
        "start and never retires below it)",
    )
    fa.add_argument(
        "--max", type=int, default=4,
        help="maximum replica count (scale-out stops here no matter the "
        "load)",
    )
    fa.add_argument(
        "--step", type=int, default=1,
        help="replicas added/removed per scale decision",
    )
    fa.add_argument(
        "--poll-interval", type=float, default=1.0,
        help="seconds between signal polls",
    )
    fa.add_argument(
        "--breach-polls", type=int, default=3,
        help="consecutive breaching polls before a scale-out fires "
        "(debounce)",
    )
    fa.add_argument(
        "--idle-polls", type=int, default=10,
        help="consecutive all-quiet polls before a scale-in fires",
    )
    fa.add_argument(
        "--cooldown", type=float, default=30.0,
        help="seconds after any scale action before the next may fire "
        "(both directions — flapping load cannot thrash the fleet)",
    )
    fa.add_argument(
        "--out-queue-depth", type=float, default=8.0,
        help="scale-out when any replica's /healthz queue depth reaches "
        "this (sustained --breach-polls)",
    )
    fa.add_argument(
        "--out-latency-ms", type=float, default=250.0,
        help="scale-out when the router's recent mean /predict latency "
        "reaches this",
    )
    fa.add_argument(
        "--out-shed-rate", type=float, default=0.02,
        help="scale-out when the router's recent shed fraction reaches "
        "this",
    )
    fa.add_argument(
        "--out-burn-rate", type=float, default=4.0,
        help="scale-out when any replica's worst SLO burn rate reaches "
        "this",
    )
    fa.add_argument(
        "--in-queue-depth", type=float, default=1.0,
        help="scale-in requires every replica queue depth at or under "
        "this (and every other signal under its twin) for --idle-polls",
    )
    fa.add_argument("--in-latency-ms", type=float, default=50.0)
    fa.add_argument("--in-shed-rate", type=float, default=0.0)
    fa.add_argument("--in-burn-rate", type=float, default=1.0)
    fa.add_argument(
        "--out-alerts-active", type=float, default=None,
        help="scale-out when this many router alert rules are firing "
        "(/fleet/alerts; default None keeps the alert plane out of the "
        "control loop — the reading is journaled either way)",
    )
    fa.add_argument(
        "--in-alerts-active", type=float, default=None,
        help="scale-in twin of --out-alerts-active (None: firing "
        "alerts never block a scale-in)",
    )
    fa.add_argument(
        "--ready-deadline", type=float, default=300.0,
        help="seconds a spawned replica may take to answer /readyz "
        "before the spawn fails closed (killed, journaled, retried "
        "under backoff)",
    )
    fa.add_argument(
        "--drain-settle", type=float, default=10.0,
        help="retirement drain bound: seconds to wait (after leaving "
        "rotation) for the replica's queue to empty before SIGTERM",
    )
    fa.add_argument(
        "--term-deadline", type=float, default=30.0,
        help="seconds after SIGTERM before a replica that refuses to "
        "drain is SIGKILLed",
    )
    fa.add_argument(
        "--respawn-backoff", type=float, default=1.0,
        help="initial crash-respawn backoff (doubles per consecutive "
        "failure)",
    )
    fa.add_argument("--respawn-backoff-max", type=float, default=30.0)
    fa.add_argument(
        "--replica-host", default="127.0.0.1",
        help="host spawned replicas bind (ports are allocated fresh)",
    )
    fa.add_argument(
        "--serve-arg", action="append", metavar="ARG", default=None,
        help="extra `serve` flag for every spawned replica (repeatable, "
        "one token per use; use the = form for tokens that start with a "
        "dash: --serve-arg=--buckets --serve-arg=1,8)",
    )
    fa.add_argument(
        "--no-aot", action="store_true",
        help="spawn every replica with `serve --no-aot`: the fleet-wide "
        "escape hatch forcing the trace+compile warmup path when a "
        "published AOT bundle is suspect (docs/AOT.md; scale-out then "
        "pays the compile wall again)",
    )
    fa.add_argument(
        "--replica-journal-dir", default=None,
        help="directory for per-replica journals "
        "(replica_<id>.jsonl each)",
    )
    fa.add_argument(
        "--max-ticks", type=int, default=None,
        help="exit after N polls (drills/CI; default: run until "
        "signalled)",
    )
    fa.add_argument(
        "--leave-running", action="store_true",
        help="on shutdown, leave managed replicas serving (default: "
        "drain and stop them with the daemon)",
    )
    fa.add_argument(
        "--inject", action="append", metavar="SPEC", default=None,
        help="arm a lifecycle faultpoint in this process (repeatable): "
        "lifecycle.spawn:corrupt@once, lifecycle.drain:corrupt@once, … "
        "(docs/RESILIENCE.md faultpoint catalog)",
    )
    fa.add_argument(
        "--metrics-out", default=None,
        help="write the daemon's final Prometheus exposition "
        "(autoscale_*, lifecycle_* families) to this path on exit",
    )
    fa.add_argument(
        "--journal", default=None,
        help="JSONL journal path (autoscale decisions + lifecycle arcs)",
    )
    fa.set_defaults(fn=cmd_fleet)
    fs = fsub.add_parser(
        "status", help="print the router's registry and health snapshot"
    )
    fs.add_argument("--router", required=True, help="router base URL")
    fs.set_defaults(fn=cmd_fleet)

    ln = sub.add_parser(
        "learn",
        help="continual learning: drift-triggered retrain, shadow "
        "evaluation, guarded promotion (docs/CONTINUAL.md)",
    )
    lsub = ln.add_subparsers(dest="role", required=True)

    def add_shadow_threshold_flags(p):
        p.add_argument(
            "--max-divergence-mean", type=float, default=0.15,
            help="shadow gate: max mean |p_candidate - p_live| over the "
            "replay (a refit should recalibrate, not reinvent)",
        )
        p.add_argument(
            "--max-divergence-p95", type=float, default=0.35,
            help="shadow gate: max p95 |p_candidate - p_live|",
        )
        p.add_argument(
            "--max-flip-rate", type=float, default=0.10,
            help="shadow gate: max fraction of replay rows whose "
            "0.5-threshold decision flips",
        )
        p.add_argument(
            "--max-score-psi", type=float, default=2.0,
            help="shadow gate: max PSI between candidate and live score "
            "distributions over the replay",
        )
        p.add_argument(
            "--max-candidate-psi", type=float, default=0.25,
            help="shadow gate: max per-feature PSI of the replay vs the "
            "CANDIDATE's own reference profile (the refit exists to make "
            "this small)",
        )
        p.add_argument(
            "--max-disagreement-delta", type=float, default=0.15,
            help="shadow gate: max increase in mean pairwise ensemble "
            "disagreement, candidate minus live",
        )
        p.add_argument(
            "--shadow-min-rows", type=int, default=64,
            help="shadow gate: minimum replay rows before a verdict may "
            "pass (fails closed below)",
        )
        p.add_argument(
            "--allow-no-profile", action="store_true",
            help="let a candidate without its own quality reference "
            "profile pass the gate (default: refuse — a promoted model "
            "must ship its drift baseline)",
        )

    def add_learn_common(p, router_required: bool, cohort: bool = True):
        p.add_argument(
            "--model", required=True,
            help="the LIVE checkpoint directory (the fleet's deploy "
            "target; the candidate is judged against, and published "
            "into, this path)",
        )
        p.add_argument(
            "--candidate", default=None, metavar="DIR",
            help="candidate checkpoint directory "
            "(default: <model>.candidate)",
        )
        if cohort:  # promote applies a verdict — it never reads rows
            p.add_argument(
                "--capture", required=True, metavar="DIR",
                help="the router's cohort-capture directory "
                "(`cli fleet router --capture DIR`)",
            )
            p.add_argument(
                "--rows", type=int, default=8192,
                help="max captured rows to load (newest first)",
            )
            p.add_argument(
                "--min-rows", type=int, default=200,
                help="refuse to act on fewer captured rows",
            )
        if router_required:
            p.add_argument(
                "--router", required=True, help="fleet router base URL"
            )

    lr = lsub.add_parser(
        "run",
        help="the closed-loop daemon: poll fleet quality, debounce, "
        "retrain on sustained alert, shadow-evaluate, promote through "
        "the fleet deploy rail",
    )
    add_learn_common(lr, router_required=True)
    lr.add_argument(
        "--alert-streak", type=int, default=3,
        help="consecutive alert polls before the trigger fires "
        "(debounce)",
    )
    lr.add_argument(
        "--cooldown", type=float, default=600.0,
        help="seconds between trigger fires",
    )
    lr.add_argument(
        "--schedule", type=float, default=None,
        help="also fire every N seconds regardless of drift (subject to "
        "the cooldown); default: alert-only",
    )
    lr.add_argument(
        "--poll-interval", type=float, default=2.0,
        help="seconds between quality polls",
    )
    lr.add_argument(
        "--recovery-timeout", type=float, default=120.0,
        help="seconds to wait for fleet quality to return to ok after a "
        "promotion (the cycle's closing assertion, journaled either way)",
    )
    lr.add_argument(
        "--settle-timeout", type=float, default=300.0,
        help="post-trigger capture turnover bound: wait (up to this many "
        "seconds) until --rows NEW rows were captured after the trigger "
        "fired, so the refit sees only post-drift traffic — a refit on "
        "the mixed pre/post-drift window learns a blend whose reference "
        "profile matches neither population (0 disables)",
    )
    lr.add_argument(
        "--max-cycles", type=int, default=None,
        help="exit after N completed cycles (drills/CI; default: run "
        "until signalled)",
    )
    lr.add_argument("--config", help="ExperimentConfig JSON for the refit")
    add_shadow_threshold_flags(lr)
    add_obs_flags(lr)
    lr.set_defaults(fn=cmd_learn)

    lt = lsub.add_parser(
        "retrain",
        help="one warm-start refit on the captured cohort -> a versioned "
        "candidate checkpoint (stage-resumable)",
    )
    add_learn_common(lt, router_required=False)
    lt.add_argument("--config", help="ExperimentConfig JSON for the refit")
    lt.add_argument(
        "--resume-dir", default=None,
        help="StageCheckpointer directory: a preempted refit re-entered "
        "with the same captured cohort resumes instead of restarting",
    )
    add_obs_flags(lt)
    lt.set_defaults(fn=cmd_learn)

    lw = lsub.add_parser(
        "shadow",
        help="replay the captured cohort through live + candidate and "
        "print the machine-readable verdict (exit 1 on fail)",
    )
    add_learn_common(lw, router_required=False)
    lw.add_argument(
        "--out", default=None,
        help="write the verdict JSON here (the input `learn promote` "
        "requires)",
    )
    add_shadow_threshold_flags(lw)
    add_obs_flags(lw)
    lw.set_defaults(fn=cmd_learn)

    lp = lsub.add_parser(
        "promote",
        help="apply a shadow verdict: publish the candidate into the "
        "live path and drive the fleet's rolling deploy (pass), or park "
        "it with a REFUSED.json (fail)",
    )
    add_learn_common(lp, router_required=True, cohort=False)
    lp.add_argument(
        "--verdict", required=False, default=None,
        help="verdict JSON from `learn shadow --out` (required: "
        "promotion without a verdict is the unguarded swap the gate "
        "exists to prevent)",
    )
    lp.add_argument(
        "--no-aot", action="store_true",
        help="publish the promoted model WITHOUT the AOT executable "
        "bundle (docs/AOT.md; default: export it, so the rolling deploy "
        "restores executables instead of compiling on every replica)",
    )
    lp.add_argument(
        "--timeout", type=float, default=1800.0,
        help="end-to-end rollout timeout (seconds)",
    )
    add_obs_flags(lp)
    lp.set_defaults(fn=cmd_learn)

    ls = lsub.add_parser(
        "status",
        help="fleet quality + capture-window + candidate state in one "
        "snapshot (jax-free)",
    )
    ls.add_argument("--router", required=True, help="fleet router base URL")
    ls.add_argument(
        "--candidate", default=None,
        help="also report this candidate dir's version/parked state",
    )
    ls.set_defaults(fn=cmd_learn)

    c = sub.add_parser(
        "score",
        help="bulk-score a streamed cohort file (JSONL patients or .mat) "
        "into sharded, resumable output (docs/SCORING.md)",
    )
    c.add_argument("--model", help="Orbax checkpoint dir from `train --save`")
    c.add_argument(
        "--pkl", help="legacy sklearn pickle (default: the reference artifact)"
    )
    c.add_argument(
        "--cohort", required=True,
        help="cohort path: JSONL (one 17-variable patient object per "
        "line, the loadgen --patients format) or a reference-layout .mat "
        "(64 raw schema columns routed through impute → select → "
        "ensemble; a trailing outcome column is ignored)",
    )
    c.add_argument(
        "--format", choices=("auto", "jsonl", "mat"), default="auto",
        help="cohort format (default: by file extension)",
    )
    c.add_argument(
        "--out", required=True,
        help="output directory: scores-NNNNN.jsonl shards, "
        "quarantine.jsonl, progress.json (the resume manifest), "
        "summary.json, quality.json",
    )
    c.add_argument(
        "--chunk-rows", type=int, default=2048,
        help="rows per streamed chunk — the device's one static compiled "
        "shape AND the durable commit/resume granularity",
    )
    c.add_argument(
        "--prefetch", type=int, default=4,
        help="bounded prefetch budget: how many chunks ingest may run "
        "ahead of the device stage",
    )
    c.add_argument(
        "--parse-workers", type=int, default=2,
        help="parse/validate/impute-route worker THREADS feeding the "
        "device stage (used when --parse-procs is 0, and always for "
        ".mat cohorts)",
    )
    c.add_argument(
        "--parse-procs", type=int, default=0,
        help="ingest-parse worker PROCESSES for JSONL cohorts (spawned; "
        "the JSON/validate stage then runs free of the parent's GIL — "
        "worth it on many-core hosts where ingest parsing, not total "
        "CPU, is the ceiling; 0 = in-process threads, the default, "
        "which measured best on the ~2-core bench sandbox where total "
        "CPU binds)",
    )
    c.add_argument(
        "--rows-per-shard", type=int, default=500_000,
        help="output shard rotation size",
    )
    c.add_argument(
        "--max-bad-rows", type=int, default=1000,
        help="malformed-row error budget: bad rows are quarantined to "
        "quarantine.jsonl with line numbers and the run continues, until "
        "this many — then it aborts (exit 2) instead of silently scoring "
        "a garbage cohort's parseable minority",
    )
    c.add_argument(
        "--sequential", action="store_true",
        help="disable the overlapped pipeline: read → parse → device → "
        "write strictly serialized (the bench ablation and the debugging "
        "fallback)",
    )
    c.add_argument(
        "--fresh", action="store_true",
        help="discard any resumable progress in --out and start over "
        "(default: a matching progress.json resumes at the last "
        "committed chunk)",
    )
    c.add_argument(
        "--limit", type=int, default=None,
        help="score only the first N input rows (bench/CI convenience)",
    )
    c.add_argument(
        "--no-quality", action="store_true",
        help="skip the cohort-level quality snapshot even when the "
        "checkpoint carries a reference profile",
    )
    c.add_argument(
        "--quality-window", type=int, default=1 << 20,
        help="quality-monitor window over the scored population (rows)",
    )
    c.add_argument("--drift-warn-psi", type=float, default=None)
    c.add_argument("--drift-alert-psi", type=float, default=None)
    c.add_argument(
        "--no-fsync", action="store_true",
        help="skip per-commit fsync (faster on slow disks; a crash may "
        "then lose the last chunks to the page cache, though resume "
        "still recovers consistently from what reached disk)",
    )
    c.add_argument(
        "--metrics-out", default=None,
        help="write the run's final Prometheus exposition (score_*, "
        "quality_*, jax_* families) to this path",
    )
    c.add_argument(
        "--xla-intra-op-threads", type=int, default=None,
        help="bound the XLA CPU intra-op pool (default: leave XLA alone "
        "— bulk scoring is throughput-bound and benefits from the full "
        "default pool, the opposite trade from `serve`'s event-loop "
        "protection)",
    )
    add_mesh_flags(
        c, "the stacked probability pass runs row-sharded over the "
        "'data' axis"
    )
    add_obs_flags(c)
    c.set_defaults(fn=cmd_score)

    s = sub.add_parser("sweep", help="5-fold CV grid sweep (config 4)")
    add_cohort_flags(s)
    s.add_argument("--n-estimators", type=int, nargs="+", default=[25, 50, 100, 200])
    s.add_argument("--max-depth", type=int, nargs="+", default=[1, 2, 3])
    s.add_argument("--folds", type=int, default=5)
    s.add_argument("--save", help="checkpoint the refit best model here")
    add_mesh_flags(
        s, "each (depth, fold) fit and the best-cell refit run row-sharded "
        "(fold masks ride the trainers' weight path)"
    )
    s.set_defaults(fn=cmd_sweep)

    i = sub.add_parser("import-sklearn", help="legacy pickle → Orbax")
    i.add_argument("--pkl", help="pickle path (default: the reference artifact)")
    i.add_argument("--out", required=True, help="Orbax checkpoint directory")
    i.add_argument(
        "--aot", action="store_true",
        help="also export the AOT executable bundle into the checkpoint "
        "(docs/AOT.md): replicas serving it restore per-bucket "
        "executables instead of compiling at warmup",
    )
    i.set_defaults(fn=cmd_import_sklearn)
    return ap


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
