"""Guarded promotion — a passing candidate rides PR 9's deploy rail; a
failing one is parked, loudly.

The shadow verdict (``learn.shadow``) is the gate's only input: this
module deliberately adds no second opinion, because a gate that
re-litigates its evidence invites threshold drift between the two
judgments. What it adds is *consequence*:

  * **pass** → the candidate is republished into the LIVE checkpoint
    path (``persist.orbax_io.save_model`` — the atomic publish rotates
    the serving version into its last-known-good slot and stamps the
    next monotonic version id), then the fleet router's
    ``POST /fleet/deploy`` drives the zero-downtime rolling swap, replica
    by replica, with the replica-side parity probe and the lastgood
    rollback exactly as any operator-initiated deploy. The continual
    loop owns no deploy machinery of its own — that is the point.
  * **fail** → the candidate stays where the refit published it, with a
    ``REFUSED.json`` sidecar carrying the full verdict (a parked
    candidate must explain itself to the human who finds it), a
    journaled ``learn_promotion`` refusal, and the fleet untouched.

``promote_via_router`` is jax-free (one HTTP POST); ``publish_candidate``
restores + republishes a checkpoint and needs the jax stack — the split
keeps the daemon's polling half accelerator-free.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY

REFUSED_FILE = "REFUSED.json"

PROMOTIONS = REGISTRY.counter(
    "learn_promotions_total",
    "Continual-learning promotion outcomes (promoted: rolling deploy "
    "completed ok; refused: shadow verdict failed, candidate parked; "
    "failed: the deploy itself failed or rolled back).",
    labels=("result",),
)
for _r in ("promoted", "refused", "failed"):
    PROMOTIONS.labels(result=_r)


def park(candidate_dir: str | os.PathLike, verdict: dict) -> str:
    """Refuse a candidate: write the verdict as a ``REFUSED.json``
    sidecar inside the candidate checkpoint dir and journal the refusal.
    Returns the sidecar path. The candidate's payload is left intact —
    a parked model is evidence, not garbage."""
    candidate_dir = os.path.abspath(os.fspath(candidate_dir))
    path = os.path.join(candidate_dir, REFUSED_FILE)
    from machine_learning_replications_tpu.persist.atomicio import (
        atomic_json_write,
    )

    atomic_json_write(path, {
        "kind": "learn_promotion_refused",
        "ts": journal.utc_now_iso(),
        "verdict": verdict,
    })
    PROMOTIONS.inc(result="refused")
    journal.event(
        "learn_promotion",
        result="refused",
        candidate=candidate_dir,
        reasons=verdict.get("reasons"),
    )
    return path


def is_parked(candidate_dir: str | os.PathLike) -> bool:
    return os.path.exists(
        os.path.join(os.path.abspath(os.fspath(candidate_dir)), REFUSED_FILE)
    )


def publish_candidate(
    candidate_dir: str | os.PathLike, model_path: str | os.PathLike,
    aot: bool = True,
) -> int | None:
    """Republish a shadow-approved candidate into the live checkpoint
    path: restore the candidate (integrity-verified) and ``save_model``
    it at ``model_path`` — one atomic publish that rotates the serving
    version into the last-known-good slot and stamps the next monotonic
    version id in the LIVE path's lineage. Returns the published
    version. The candidate dir itself is untouched (it remains the
    refit's resumable artifact).

    By default the publish also exports the AOT executable bundle
    (``persist.aot``, docs/AOT.md): promotion IS publish time, and the
    rolling deploy that follows restores executables instead of paying
    the ladder compile on every replica — the compile bill is paid once,
    here, off every replica's hold window."""
    from machine_learning_replications_tpu.persist import orbax_io

    candidate_dir = os.path.abspath(os.fspath(candidate_dir))
    if is_parked(candidate_dir):
        raise RuntimeError(
            f"candidate {candidate_dir!r} was refused by a shadow "
            "verdict (REFUSED.json present); refusing to publish it"
        )
    params = orbax_io.load_model(candidate_dir)
    orbax_io.save_model(model_path, params, aot=aot)
    version = orbax_io.checkpoint_version(model_path)
    journal.event(
        "learn_candidate_published",
        candidate=candidate_dir,
        model=os.path.abspath(os.fspath(model_path)),
        version=version,
    )
    return version


def promote_via_router(
    router_url: str, model_path: str | os.PathLike,
    timeout_s: float = 1800.0,
) -> dict:
    """Drive the fleet's rolling deploy of ``model_path`` through the
    router (``POST /fleet/deploy`` — single-flight, replica-side warm
    swap + parity probe + lastgood rollback). Returns the rollout
    report; raises ``RuntimeError`` on transport failure. The report's
    ``result`` (``ok`` / ``rolled_back`` / ``failed``) is the caller's
    verdict — a rolled-back rollout means the fleet PROTECTED itself
    and still serves the previous version."""
    req = urllib.request.Request(
        router_url.rstrip("/") + "/fleet/deploy",
        data=json.dumps({"model": os.fspath(model_path)}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read())["deploy"]
    except urllib.error.HTTPError as exc:
        try:
            body = json.loads(exc.read() or b"{}")
        except (ValueError, OSError):
            body = {}
        if isinstance(body, dict) and isinstance(body.get("deploy"), dict):
            return body["deploy"]
        raise RuntimeError(
            f"fleet deploy request failed (http {exc.code}): "
            f"{body.get('error', 'no detail') if isinstance(body, dict) else body}"
        ) from exc
    except (urllib.error.URLError, OSError) as exc:
        raise RuntimeError(
            f"fleet deploy request to {router_url} failed: {exc}"
        ) from exc


def promote(
    candidate_dir: str | os.PathLike,
    model_path: str | os.PathLike,
    router_url: str,
    verdict: dict,
    deploy_timeout_s: float = 1800.0,
    aot: bool = True,
) -> dict:
    """The gate, end to end: apply the shadow verdict, then either park
    (fail) or publish + rolling-deploy (pass). Returns
    ``{"result": promoted|refused|failed, ...}`` and journals
    ``learn_promotion`` either way — the one event the obs-report's
    continual-learning section keys the arc on."""
    candidate_dir = os.path.abspath(os.fspath(candidate_dir))
    from machine_learning_replications_tpu.fleet.deploy import (
        manifest_version,
    )

    judged = verdict.get("candidate_version")
    current = manifest_version(candidate_dir)
    if judged is not None and current is not None and judged != current:
        # A verdict is evidence about ONE candidate. If the dir was
        # retrained since the shadow ran, applying the old passing
        # verdict would roll out a model nobody evaluated — exactly the
        # unguarded swap the gate exists to prevent. Refuse loudly (not
        # park: the new candidate isn't judged bad, just unjudged).
        raise ValueError(
            f"verdict judged candidate v{judged} but {candidate_dir} now "
            f"holds v{current}: re-run `learn shadow` on the current "
            "candidate before promoting"
        )
    if not verdict.get("pass"):
        park(candidate_dir, verdict)
        return {
            "result": "refused",
            "candidate": candidate_dir,
            "reasons": verdict.get("reasons"),
        }
    version = publish_candidate(candidate_dir, model_path, aot=aot)
    try:
        report = promote_via_router(
            router_url, model_path, timeout_s=deploy_timeout_s
        )
    except Exception as exc:
        # The live path on disk already holds the candidate as its next
        # version, but the fleet never saw it (router unreachable,
        # transport drop mid-rollout). That half-state MUST reach the
        # journal — it is exactly what an operator needs to see before
        # the next replica restart silently serves an undeployed
        # version — and the caller gets a failed result, not an
        # exception that skips the arc's terminal event.
        PROMOTIONS.inc(result="failed")
        journal.event(
            "learn_promotion", result="failed",
            candidate=candidate_dir,
            model=os.path.abspath(os.fspath(model_path)),
            version=version,
            deploy_result=None,
            deploy_error=str(exc),
            replicas=[],
        )
        return {
            "result": "failed",
            "candidate": candidate_dir,
            "version": version,
            "error": str(exc),
        }
    ok = report.get("result") == "ok"
    PROMOTIONS.inc(result="promoted" if ok else "failed")
    journal.event(
        "learn_promotion",
        result="promoted" if ok else "failed",
        candidate=candidate_dir,
        model=os.path.abspath(os.fspath(model_path)),
        version=version,
        deploy_result=report.get("result"),
        deploy_error=report.get("error"),
        replicas=[r.get("replica") for r in report.get("replicas", [])],
    )
    return {
        "result": "promoted" if ok else "failed",
        "candidate": candidate_dir,
        "version": version,
        "deploy": report,
    }
