"""Warm-start refit on the captured recent cohort — the loop's "act" half.

The trigger (``learn.trigger``) says the served population no longer
matches the model's training reference; this module produces the model
that DOES match it. The refit rides entirely on machinery that already
exists:

  * **Data** — the router's bounded capture buffer (``learn.capture``),
    loaded as contract-order rows through the same quarantine-tolerant
    parse bulk scoring uses.
  * **Labels** — serving is label-free, so by default the refit
    *distills*: the live model's own probabilities over the captured
    rows, thresholded at the published 0.5 operating point, become
    pseudo-labels. That adapts every distribution-facing stage (imputer
    donors, scaler moments, lasso selection, member fits, the reference
    profile) to the shifted cohort while anchoring the decision function
    to the model clinicians validated — the honest scope of an
    *unsupervised* continual loop. When adjudicated outcomes exist,
    ``labels`` overrides the distillation (journaled either way:
    ``labels_source``).
  * **Fit** — ``fit_pipeline`` / ``fit_stacking`` with their existing
    ``StageCheckpointer``: every stage durably checkpointed and
    stage-timed (the ``stage_start``/``stage_done`` journal arc), so a
    preempted refit re-entered with the same cohort resumes instead of
    restarting.
  * **Publish** — ``persist.orbax_io.save_model`` → the atomic
    ``_publish_tree`` path: the candidate gets a monotonic version id,
    an integrity manifest, and last-known-good rotation for free.

Family dispatch mirrors serving: a ``PipelineParams`` live model refits
the full impute → select → stack program over the captured rows embedded
at their schema positions (the candidate's reference profile comes out
of ``fit_pipeline`` itself); a bare ``StackingParams`` refits the
ensemble on the contract rows and attaches a fresh reference profile
(``StackingParams.quality``) so the candidate ships its own drift
baseline — the property the shadow evaluator and the post-promotion
monitor rebase both key on.
"""

from __future__ import annotations

import os
import time
from typing import Any

import numpy as np

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY

RETRAINS = REGISTRY.counter(
    "learn_retrain_total",
    "Continual-learning refits by result.",
    labels=("result",),
)
for _r in ("ok", "failed"):
    RETRAINS.labels(result=_r)
RETRAIN_SECONDS = REGISTRY.gauge(
    "learn_retrain_seconds",
    "Wall seconds of the most recent refit (NaN until one ran).",
)
RETRAIN_SECONDS.get().set(float("nan"))

#: Refuse to refit on fewer rows: a model fit on a few dozen rows would
#: pass its own reference profile trivially while being statistical noise.
DEFAULT_MIN_ROWS = 200


def pseudo_labels(live_params: Any, X17: np.ndarray) -> np.ndarray:
    """Distillation labels: the live model's decisions over the captured
    rows at the published 0.5 operating point (``predict_hf.py``'s
    threshold; ``train_ensemble_public.py:63`` rounds the same way)."""
    from machine_learning_replications_tpu.learn.shadow import replay_scores

    p1, _members, _rows = replay_scores(live_params, X17)
    return (p1 >= 0.5).astype(np.float64)


def warm_refit(
    live_params: Any,
    X17: np.ndarray,
    out_dir: str | os.PathLike,
    cfg=None,
    labels: np.ndarray | None = None,
    resume_dir: str | os.PathLike | None = None,
    min_rows: int = DEFAULT_MIN_ROWS,
    mesh=None,
) -> tuple[Any, dict]:
    """Refit the live model's family on contract-order rows ``X17`` and
    publish the candidate checkpoint at ``out_dir`` (atomic, versioned,
    integrity-manifested). Returns ``(candidate_params, info)`` where
    ``info`` carries the published version, row counts, label source,
    and wall seconds — the same dict the ``learn_retrain_done`` journal
    event records. ``resume_dir`` makes the fit stage-resumable
    (``StageCheckpointer``; it is fingerprinted against the cohort, so a
    DIFFERENT captured window refuses a stale dir loudly)."""
    from machine_learning_replications_tpu.config import ExperimentConfig
    from machine_learning_replications_tpu.models import (
        pipeline as pipelinemod,
    )
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.obs import quality as qualitymod
    from machine_learning_replications_tpu.persist import orbax_io

    import jax.numpy as jnp

    X17 = np.asarray(X17, np.float64)
    if X17.ndim != 2 or X17.shape[1] != 17:
        raise ValueError(f"refit rows must be [n, 17], got {X17.shape}")
    n = int(X17.shape[0])
    if n < min_rows:
        raise ValueError(
            f"refit cohort has {n} rows, below min_rows={min_rows}; "
            "capture more traffic before retraining"
        )
    if not np.isfinite(X17).all():
        raise ValueError("refit rows must be finite (contract-validated)")
    cfg = cfg or ExperimentConfig()
    # Family dispatch is validated BEFORE the (expensive) distillation
    # pass: an unsupported params object must refuse up front, not fail
    # obscurely inside the live model's replay.
    if not isinstance(
        live_params, (pipelinemod.PipelineParams, stacking.StackingParams)
    ):
        raise TypeError(
            f"cannot warm-refit a {type(live_params).__name__}: the "
            "continual loop supports PipelineParams and StackingParams"
        )
    if labels is None:
        y = pseudo_labels(live_params, X17)
        labels_source = "distilled"
    else:
        y = np.asarray(labels, np.float64).ravel()
        if y.shape[0] != n:
            raise ValueError(
                f"{y.shape[0]} labels for {n} rows"
            )
        labels_source = "provided"
    if len(np.unique(y)) < 2:
        raise ValueError(
            "refit labels are single-class (the live model decides every "
            "captured row the same way); a one-class refit cannot fit "
            "the members — provide labels or widen the capture window"
        )

    t0 = time.perf_counter()
    journal.event(
        "learn_retrain_start", rows=n, labels_source=labels_source,
        family=type(live_params).__name__, out=os.fspath(out_dir),
    )
    try:
        if isinstance(live_params, pipelinemod.PipelineParams):
            # Full pipeline: captured contract rows embedded at their
            # schema positions (unobserved columns stay NaN for the KNN
            # imputer — exactly serving's missing-EHR-value story), then
            # the whole impute → select → stack program, stage-resumable.
            x64 = pipelinemod.contract_rows_to_x64(live_params, X17)
            candidate, _info = pipelinemod.fit_pipeline(
                x64, y, cfg, mesh=mesh,
                checkpoint_dir=(
                    os.fspath(resume_dir) if resume_dir else None
                ),
            )
        else:  # StackingParams — the only other family past the gate
            stages = pipelinemod._make_stages(
                os.fspath(resume_dir) if resume_dir else None,
                None,
                fingerprint=(
                    pipelinemod._fit_fingerprint(X17, y, cfg)
                    if resume_dir else None
                ),
            )
            ens = pipelinemod.fit_stacking(
                X17, y, cfg, mesh=mesh, stages=stages
            )
            scores = pipelinemod._ensemble_scores(
                ens, X17, mesh=mesh,
                chunk_rows=cfg.svc.predict_chunk_rows,
            )
            prof = qualitymod.build_reference_profile(X17, scores, y=y)
            candidate = ens.replace(
                quality={k: jnp.asarray(v) for k, v in prof.items()}
            )
        orbax_io.save_model(out_dir, candidate)
    except BaseException as exc:
        RETRAINS.inc(result="failed")
        journal.event(
            "learn_retrain_failed", rows=n,
            error=f"{type(exc).__name__}: {exc}",
            seconds=round(time.perf_counter() - t0, 3),
        )
        raise
    seconds = round(time.perf_counter() - t0, 3)
    version = orbax_io.checkpoint_version(out_dir)
    RETRAINS.inc(result="ok")
    RETRAIN_SECONDS.get().set(seconds)
    info = {
        "rows": n,
        "labels_source": labels_source,
        "family": type(candidate).__name__,
        "candidate": os.path.abspath(os.fspath(out_dir)),
        "version": version,
        "seconds": seconds,
    }
    journal.event("learn_retrain_done", **info)
    return candidate, info
