"""The closed loop: alert → retrain → shadow → guarded promote → ok.

This module is pure composition — every step below is owned, tested, and
journaled by another module; the loop's job is ordering, bounded waits,
and making the whole arc one joined journal story:

    quality_status(ok→alert)        the replicas (obs.quality)
    learn_trigger(fired)            learn.trigger
    learn_retrain_start/stage_*/…   learn.retrain over fit_* stages
    learn_shadow_verdict            learn.shadow
    learn_promotion                 learn.promote
    fleet_deploy_start/…/done       the router (fleet.deploy)
    quality_status(alert→ok)        the replicas, on the REBASED profile

``run_cycle`` is one trigger-to-verdict pass (the unit ``cli learn run
--once`` and the CI continual job drive); ``LearnLoop.run`` wraps it in
the poll/debounce/cooldown daemon loop.
"""

from __future__ import annotations

import json
import os
import time
import urllib.request

from machine_learning_replications_tpu.learn import capture as capturemod
from machine_learning_replications_tpu.learn import promote as promotemod
from machine_learning_replications_tpu.learn import shadow as shadowmod
from machine_learning_replications_tpu.learn import trigger as triggermod
from machine_learning_replications_tpu.obs import journal


def run_cycle(
    model_path: str,
    capture_dir: str,
    candidate_dir: str,
    router_url: str | None,
    cfg=None,
    thresholds: shadowmod.ShadowThresholds | None = None,
    max_rows: int = 8192,
    min_rows: int = 200,
    resume_dir: str | None = None,
    deploy_timeout_s: float = 1800.0,
    say=None,
) -> dict:
    """One full retrain → shadow → promote cycle against the captured
    cohort. Returns a summary dict (``outcome`` ∈ promoted / refused /
    failed / skipped). ``router_url=None`` stops after the shadow
    verdict (retrain-and-judge mode — the candidate is published or
    parked but no rollout is driven)."""
    from machine_learning_replications_tpu.learn import retrain as retrainmod
    from machine_learning_replications_tpu.persist import orbax_io

    def _say(msg: str) -> None:
        if say is not None:
            say(msg)

    t0 = time.perf_counter()
    X17, n_bad = capturemod.load_recent(capture_dir, max_rows=max_rows)
    _say(f"captured cohort: {X17.shape[0]} rows ({n_bad} malformed dropped)")
    if X17.shape[0] < min_rows:
        journal.event(
            "learn_cycle_done", outcome="skipped",
            reason=f"only {X17.shape[0]} captured rows (min {min_rows})",
            seconds=round(time.perf_counter() - t0, 3),
        )
        return {
            "outcome": "skipped",
            "reason": f"only {X17.shape[0]} captured rows "
                      f"(min_rows={min_rows})",
        }

    live_params = orbax_io.load_model(model_path)
    live_version = orbax_io.checkpoint_version(model_path)
    candidate, retrain_info = retrainmod.warm_refit(
        live_params, X17, candidate_dir, cfg=cfg,
        resume_dir=resume_dir, min_rows=min_rows,
    )
    _say(
        f"refit done: candidate v{retrain_info['version']} "
        f"({retrain_info['seconds']}s over {retrain_info['rows']} rows, "
        f"labels {retrain_info['labels_source']})"
    )
    verdict = shadowmod.evaluate(
        live_params, candidate, X17,
        thresholds=thresholds,
        candidate_version=retrain_info["version"],
    )
    stats = verdict["stats"]
    _say(
        f"shadow verdict: {'pass' if verdict['pass'] else 'FAIL'} "
        f"(divergence mean {stats['divergence_mean']}, flip rate "
        f"{stats['flip_rate']}, candidate quality "
        f"{(stats['candidate_quality'] or {}).get('status')})"
        + (f" — {'; '.join(verdict['reasons'])}" if verdict["reasons"]
           else "")
    )
    if router_url is None:
        outcome = "shadow_pass" if verdict["pass"] else "refused"
        if not verdict["pass"]:
            promotemod.park(candidate_dir, verdict)
        summary = {
            "outcome": outcome,
            "from_version": live_version,
            "retrain": retrain_info,
            "verdict": verdict,
        }
    else:
        result = promotemod.promote(
            candidate_dir, model_path, router_url, verdict,
            deploy_timeout_s=deploy_timeout_s,
        )
        _say(f"promotion: {result['result']}")
        summary = {
            "outcome": result["result"],
            "from_version": live_version,
            "retrain": retrain_info,
            "verdict": verdict,
            "promotion": result,
        }
    summary["seconds"] = round(time.perf_counter() - t0, 3)
    # The arc's destination version: the LIVE path's id after a
    # promotion republishes the candidate (the candidate dir keeps its
    # own local counter — journaling that would tell a v1→v1 story).
    to_version = summary.get("promotion", {}).get("version")
    journal.event(
        "learn_cycle_done", outcome=summary["outcome"],
        from_version=live_version,
        to_version=(to_version if to_version is not None
                    else retrain_info["version"]),
        seconds=summary["seconds"],
    )
    return summary


def wait_for_quality_ok(
    replica_urls: list[str], timeout_s: float = 120.0,
    poll_s: float = 1.0,
) -> bool:
    """Post-promotion verification: block until every reachable replica's
    quality status reads ``ok`` (the rebased profile judging live
    traffic), or the timeout passes. The loop's closing assertion — a
    promotion whose quality never recovers is journaled as such
    (``learn_recovery``), not silently declared victorious."""
    deadline = time.monotonic() + timeout_s
    last: dict[str, str | None] = {}
    while time.monotonic() < deadline:
        last = {
            url: triggermod.poll_quality(url).get("status")
            for url in replica_urls
        }
        statuses = [s for s in last.values() if s is not None]
        if statuses and all(s == "ok" for s in statuses):
            journal.event(
                "learn_recovery", recovered=True, statuses=last,
            )
            return True
        time.sleep(poll_s)
    journal.event("learn_recovery", recovered=False, statuses=last)
    return False


class LearnLoop:
    """The daemon ``cli learn run`` drives: poll the fleet's quality,
    debounce through ``TriggerPolicy``, and run full cycles when it
    fires. ``max_cycles`` bounds the loop for drills and CI (None = run
    until interrupted)."""

    def __init__(
        self,
        model_path: str,
        capture_dir: str,
        candidate_dir: str,
        router_url: str,
        policy: triggermod.TriggerPolicy | None = None,
        cfg=None,
        thresholds: shadowmod.ShadowThresholds | None = None,
        poll_interval_s: float = 2.0,
        max_rows: int = 8192,
        min_rows: int = 200,
        recovery_timeout_s: float = 120.0,
        settle_timeout_s: float = 300.0,
        say=None,
    ) -> None:
        self.model_path = os.path.abspath(model_path)
        self.capture_dir = os.path.abspath(capture_dir)
        self.candidate_dir = os.path.abspath(candidate_dir)
        self.router_url = router_url
        self.policy = policy or triggermod.TriggerPolicy()
        self.cfg = cfg
        self.thresholds = thresholds
        self.poll_interval_s = float(poll_interval_s)
        self.max_rows = int(max_rows)
        self.min_rows = int(min_rows)
        self.recovery_timeout_s = float(recovery_timeout_s)
        self.settle_timeout_s = float(settle_timeout_s)
        self.say = say
        self.cycles: list[dict] = []

    def _capture_rows_appended(self) -> int | None:
        """The router's lifetime capture-append counter (``/healthz``'s
        ``capture.rows_appended``), or ``None`` when the router is
        unreachable or runs without the tap."""
        try:
            with urllib.request.urlopen(
                self.router_url.rstrip("/") + "/healthz", timeout=5.0
            ) as resp:
                health = json.loads(resp.read())
        except Exception:
            return None
        cap = health.get("capture")
        if not isinstance(cap, dict):
            return None
        rows = cap.get("rows_appended")
        return int(rows) if isinstance(rows, (int, float)) else None

    def _await_fresh_capture(self) -> None:
        """Post-trigger capture turnover — the refit must not trust a
        window that still spans the pre-drift cohort. The quality monitor
        alerts within seconds of a drift's onset, while the bounded
        capture buffer turns over only as fast as traffic arrives; a
        refit on the mixed window learns a *blend* whose reference
        profile matches neither the old nor the new population — the
        post-promotion monitor then holds the fleet in alert on exactly
        the traffic the refit was promoted to match (measured: a 50/50
        blend profile reads PSI ~0.4 against pure post-drift traffic vs
        ~0.0004 for a clean post-drift profile). So: wait, bounded by
        ``settle_timeout_s``, until ``max_rows`` NEW rows have been
        captured since the trigger fired — ``load_recent``'s newest-first
        read then sees only post-decision traffic. Journaled
        ``learn_settle`` either way; skipped (journaled) when the router
        exposes no capture counter."""
        if self.settle_timeout_s <= 0:
            return
        t0 = time.monotonic()
        start = self._capture_rows_appended()
        if start is None:
            journal.event(
                "learn_settle", skipped=True,
                reason="router /healthz exposes no capture counter",
            )
            return
        target = start + self.max_rows
        while True:
            waited = time.monotonic() - t0
            rows = self._capture_rows_appended()
            if rows is not None and rows >= target:
                journal.event(
                    "learn_settle", skipped=False, timed_out=False,
                    fresh_rows=rows - start, seconds=round(waited, 3),
                )
                if self.say:
                    self.say(
                        f"capture settled: {rows - start} fresh rows in "
                        f"{waited:.1f}s"
                    )
                return
            if waited >= self.settle_timeout_s:
                journal.event(
                    "learn_settle", skipped=False, timed_out=True,
                    fresh_rows=(rows - start) if rows is not None else None,
                    seconds=round(waited, 3),
                )
                if self.say:
                    self.say(
                        "capture settle timed out after "
                        f"{waited:.1f}s — refitting on the window as-is"
                    )
                return
            time.sleep(min(1.0, self.poll_interval_s))

    def poll_once(self) -> dict | None:
        """One poll pass over the fleet → the policy's decision."""
        urls = triggermod.replica_urls(self.router_url)
        polls = []
        for url in urls:
            p = triggermod.poll_quality(url)
            p["url"] = url
            polls.append(p)
        return self.policy.observe(polls)

    def run(self, max_cycles: int | None = None,
            stop_check=None) -> list[dict]:
        """Poll until ``max_cycles`` cycles have run (or ``stop_check()``
        goes true). Each fire runs a full cycle; a promoted cycle then
        waits (bounded) for the fleet's quality to recover before the
        cooldown clock makes the next fire possible."""
        while max_cycles is None or len(self.cycles) < max_cycles:
            if stop_check is not None and stop_check():
                break
            try:
                decision = self.poll_once()
            except Exception as exc:
                if self.say:
                    self.say(f"poll failed: {exc}")
                decision = None
            if decision is not None:
                if self.say:
                    self.say(
                        f"trigger fired ({decision['reason']}; worst "
                        f"{decision['worst_feature']} PSI "
                        f"{decision['worst_psi']})"
                    )
                self._await_fresh_capture()
                try:
                    summary = run_cycle(
                        self.model_path, self.capture_dir,
                        self.candidate_dir, self.router_url, cfg=self.cfg,
                        thresholds=self.thresholds,
                        max_rows=self.max_rows, min_rows=self.min_rows,
                        say=self.say,
                    )
                    summary["trigger"] = decision
                    if summary["outcome"] == "promoted":
                        # A router blip HERE must not relabel a cycle the
                        # fleet already completed as failed — the rollout
                        # is done; only the recovery verdict is unknown.
                        try:
                            summary["recovered"] = wait_for_quality_ok(
                                triggermod.replica_urls(self.router_url),
                                timeout_s=self.recovery_timeout_s,
                            )
                        except Exception as exc:
                            journal.event(
                                "learn_recovery", recovered=False,
                                error=str(exc),
                            )
                            summary["recovered"] = False
                except Exception as exc:
                    # A daemon documented to run until signalled must not
                    # die on one bad cycle (single-class distilled labels
                    # under extreme drift, a router blip mid-promotion…).
                    # The failure becomes a journaled, counted cycle —
                    # the cooldown the policy started at fire time still
                    # spaces the next attempt.
                    journal.event(
                        "learn_cycle_done", outcome="failed",
                        error=str(exc),
                    )
                    if self.say:
                        self.say(f"cycle failed: {exc}")
                    summary = {
                        "outcome": "failed", "error": str(exc),
                        "trigger": decision,
                    }
                self.cycles.append(summary)
                continue
            time.sleep(self.poll_interval_s)
        return self.cycles
