"""L8 — continual learning: the loop that closes detection into action
(docs/CONTINUAL.md).

PR 4 made drift *visible* (``obs.quality``: journaled ``ok → warn →
alert`` with the offending features) and PR 9 made model swaps *safe*
(``fleet``: versioned checkpoints, rolling ``/admin/deploy`` warm swaps,
last-known-good rollback). Between them sat a human. This package is the
machinery that lets the system act on its own telemetry — with the same
refusal-first posture as everything below it:

  ``capture``   bounded rotating JSONL window of served rows at the
                router front door (the ``score``/``loadgen`` patient
                format) — the refit's data, jax-free
  ``trigger``   debounced, cooldown-guarded decision of WHEN to retrain
                (sustained alert or schedule), every decision journaled
  ``retrain``   warm-start refit of the live family on the captured
                cohort (``fit_pipeline``/``fit_stacking`` stage
                checkpoints — resumable), published through the atomic
                versioned checkpoint path
  ``shadow``    the candidate replayed against live traffic before it
                may serve: divergence, flip rate, candidate self-quality
                on its OWN reference profile, disagreement delta —
                ``learn_shadow_*`` metrics + a machine-readable verdict
  ``promote``   the gate: pass → publish into the live path + the fleet
                router's rolling deploy; fail → candidate parked with a
                ``REFUSED.json``, fleet untouched
  ``loop``      the composition: one journal story from
                ``quality_status(ok→alert)`` to
                ``quality_status(alert→ok)``

jax only where the refit/replay needs it: ``capture``, ``trigger``, and
``promote``'s router half import none of the accelerator stack.
"""

from machine_learning_replications_tpu.learn.capture import (
    CohortCapture,
    load_recent,
)
from machine_learning_replications_tpu.learn.shadow import (
    ShadowThresholds,
    cohort_quality,
    score_divergence,
)
from machine_learning_replications_tpu.learn.trigger import (
    TriggerPolicy,
    poll_quality,
)

__all__ = [
    "CohortCapture",
    "ShadowThresholds",
    "TriggerPolicy",
    "cohort_quality",
    "load_recent",
    "poll_quality",
    "score_divergence",
]
