"""Shadow evaluation — the candidate model judged on live traffic before
it may serve a single client.

A retrained candidate (``learn.retrain``) is a hypothesis, not a deploy:
it was fit on pseudo-labeled recent rows and could be anything from "the
same model, recalibrated to the shifted cohort" to "a confidently wrong
model fit on garbage". This module replays captured live traffic
(``learn.capture``) through BOTH models' eager oracle composition — the
exact route ``cli predict`` takes, the same oracle the deploy parity
probe trusts — and reduces the two score streams to a machine-readable
verdict:

  * **Blended-probability divergence** — mean/p95/max ``|p_cand −
    p_live|`` and the decision flip rate (rows crossing the 0.5
    operating point; ``predict_hf.py``'s published threshold). A
    continual refit should *recalibrate*, not reinvent: large divergence
    means the candidate is a different model, and a human belongs in the
    loop.
  * **Score-distribution PSI** — candidate vs live score histograms over
    the replay, the population-level restatement of the same question.
  * **Candidate self-quality** — the replayed rows binned against the
    candidate's OWN training reference profile (``obs.quality`` math,
    same PSI thresholds): the candidate was refit precisely so that
    current traffic matches its training distribution, so a candidate
    that already reads ``alert`` against its own profile failed at the
    one job the retrain existed to do.
  * **Ensemble-disagreement delta** — mean pairwise member disagreement,
    candidate minus live: a spike means the members stopped agreeing on
    the new cohort (the classic symptom of a member overfit to
    pseudo-labels), which the blended probability alone can hide.

Everything is exported three ways, consistently: the verdict dict
(strict JSON — not-computable statistics are ``None``, never NaN), the
``learn_shadow_*`` gauge families on the process registry (NaN marks "no
data", the idiomatic gauge convention, validator-clean), and one
journaled ``learn_shadow_verdict`` event.

The replay is the *offline* mirror mode: deterministic, free of serving
jitter, and runs anywhere the checkpoint does. A router-level live
mirror tap (duplicate requests to a shadow replica, replies discarded)
would exercise the serving stack too — docs/CONTINUAL.md discusses the
trade; the comparator below is shared by both designs.

The comparator math is numpy-only and import-light; jax is imported
lazily inside ``replay_scores`` (the trigger/gate halves of ``learn``
stay accelerator-free).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs import quality as qualitymod
from machine_learning_replications_tpu.obs.registry import REGISTRY

#: Decision threshold for the flip rate — the published operating point
#: (``predict_hf.py``'s 0.5; ``train_ensemble_public.py:63``).
DECISION_THRESHOLD = 0.5

#: Fewer replay rows than this and the divergence statistics are noise —
#: the verdict refuses to pass (mirrors ``QualityMonitor.min_rows``).
DEFAULT_MIN_ROWS = 64

# One explicit, literal registration per family (rule metrics-catalog):
# a name assembled in a comprehension can't be cataloged, grepped, or
# cross-checked against docs/OBSERVABILITY.md.
_G = {
    "divergence_mean": REGISTRY.gauge(
        "learn_shadow_divergence_mean",
        "Mean |p_candidate - p_live| over the shadow replay (NaN until "
        "a replay ran).",
    ),
    "divergence_p95": REGISTRY.gauge(
        "learn_shadow_divergence_p95",
        "95th-percentile |p_candidate - p_live| over the shadow replay "
        "(NaN until a replay ran).",
    ),
    "divergence_max": REGISTRY.gauge(
        "learn_shadow_divergence_max",
        "Max |p_candidate - p_live| over the shadow replay (NaN until a "
        "replay ran).",
    ),
    "flip_rate": REGISTRY.gauge(
        "learn_shadow_flip_rate",
        "Fraction of replay rows whose 0.5-threshold decision flips "
        "between live and candidate (NaN until a replay ran).",
    ),
    "score_psi": REGISTRY.gauge(
        "learn_shadow_score_psi",
        "PSI between the candidate and live score distributions over "
        "the shadow replay (NaN until a replay ran).",
    ),
    "candidate_worst_psi": REGISTRY.gauge(
        "learn_shadow_candidate_worst_psi",
        "Worst per-feature PSI of the replay rows vs the CANDIDATE's "
        "own training reference profile (NaN when the candidate carries "
        "no profile).",
    ),
    "candidate_status": REGISTRY.gauge(
        "learn_shadow_candidate_status",
        "Candidate self-quality status over the replay: 0 ok, 1 warn, "
        "2 alert (NaN when no profile).",
    ),
    "disagreement_delta": REGISTRY.gauge(
        "learn_shadow_disagreement_delta",
        "Mean pairwise ensemble-member disagreement, candidate minus "
        "live (NaN when the family has no members).",
    ),
    "rows": REGISTRY.gauge(
        "learn_shadow_rows", "Rows in the most recent shadow replay.",
    ),
}
EVALUATIONS = REGISTRY.counter(
    "learn_shadow_evaluations_total",
    "Shadow evaluations by verdict.",
    labels=("verdict",),
)
for _v in ("pass", "fail"):
    EVALUATIONS.labels(verdict=_v)
for _g in _G.values():
    _g.get().set(float("nan"))
_G["rows"].get().set(0.0)


class ShadowThresholds:
    """The promotion gate's contract (docs/CONTINUAL.md "Shadow
    contract"). Defaults are deliberately conservative for a clinical
    score: a refit that moves the mean probability by more than 0.15, or
    flips more than 10% of decisions, is no longer a recalibration."""

    def __init__(
        self,
        max_divergence_mean: float = 0.15,
        max_divergence_p95: float = 0.35,
        max_flip_rate: float = 0.10,
        max_score_psi: float = 2.0,
        max_candidate_psi: float = qualitymod.DEFAULT_ALERT_PSI,
        max_disagreement_delta: float = 0.15,
        min_rows: int = DEFAULT_MIN_ROWS,
        require_candidate_profile: bool = True,
    ) -> None:
        self.max_divergence_mean = float(max_divergence_mean)
        self.max_divergence_p95 = float(max_divergence_p95)
        self.max_flip_rate = float(max_flip_rate)
        self.max_score_psi = float(max_score_psi)
        self.max_candidate_psi = float(max_candidate_psi)
        self.max_disagreement_delta = float(max_disagreement_delta)
        self.min_rows = int(min_rows)
        self.require_candidate_profile = bool(require_candidate_profile)

    def as_dict(self) -> dict:
        return {
            "max_divergence_mean": self.max_divergence_mean,
            "max_divergence_p95": self.max_divergence_p95,
            "max_flip_rate": self.max_flip_rate,
            "max_score_psi": self.max_score_psi,
            "max_candidate_psi": self.max_candidate_psi,
            "max_disagreement_delta": self.max_disagreement_delta,
            "min_rows": self.min_rows,
            "require_candidate_profile": self.require_candidate_profile,
        }


# ---------------------------------------------------------------------------
# Comparator math (numpy-only — the unit-tested spec)
# ---------------------------------------------------------------------------


def score_divergence(
    p_live: np.ndarray,
    p_candidate: np.ndarray,
    score_bins: int = qualitymod.DEFAULT_SCORE_BINS,
) -> dict:
    """Reduce two aligned score streams to the divergence block of the
    verdict. Pure and deterministic: the golden-value tests pin this
    function, and everything downstream (gauges, verdict, journal) is
    formatting."""
    p_live = np.asarray(p_live, np.float64).ravel()
    p_cand = np.asarray(p_candidate, np.float64).ravel()
    if p_live.shape != p_cand.shape:
        raise ValueError(
            f"score streams differ in length: {p_live.shape} vs "
            f"{p_cand.shape}"
        )
    n = int(p_live.shape[0])
    if n == 0:
        return {
            "rows": 0, "divergence_mean": None, "divergence_p95": None,
            "divergence_max": None, "flip_rate": None, "score_psi": None,
        }
    if not (np.isfinite(p_live).all() and np.isfinite(p_cand).all()):
        raise ValueError("score streams must be finite probabilities")
    d = np.abs(p_cand - p_live)
    flips = (p_cand >= DECISION_THRESHOLD) != (p_live >= DECISION_THRESHOLD)
    live_counts = np.bincount(
        qualitymod._score_bin_indices(p_live, score_bins),
        minlength=score_bins,
    )
    cand_counts = np.bincount(
        qualitymod._score_bin_indices(p_cand, score_bins),
        minlength=score_bins,
    )
    return {
        "rows": n,
        "divergence_mean": float(d.mean()),
        "divergence_p95": float(np.quantile(d, 0.95)),
        "divergence_max": float(d.max()),
        "flip_rate": float(flips.mean()),
        # expected = live (the serving status quo), actual = candidate.
        "score_psi": qualitymod.psi(live_counts, cand_counts),
    }


def cohort_quality(profile: Any, X: np.ndarray) -> dict:
    """One-shot ``obs.quality`` judgment of a row matrix against a
    reference profile (the windowed monitor's math without the rings):
    per-feature PSI/KS, worst offender, and the standard thresholded
    status. ``X`` must live in the profile's own feature space."""
    prof = qualitymod._as_host_profile(profile)
    X = np.asarray(X, np.float64)
    F, B = prof["bin_counts"].shape
    if X.ndim != 2 or X.shape[1] != F:
        raise ValueError(
            f"rows are {X.shape} but the profile describes {F} features"
        )
    if not np.isfinite(X).all():
        raise ValueError("cohort_quality rows must be finite")
    mins, widths = qualitymod.profile_bin_geometry(prof)
    fidx = qualitymod._feature_bin_indices(X, mins, widths, B)
    flat = (np.arange(F, dtype=np.int64) * B)[None, :] + fidx
    counts = np.bincount(flat.ravel(), minlength=F * B).reshape(
        F, B
    ).astype(np.float64)
    f_psi = qualitymod._psi_rows(prof["bin_counts"], counts)
    f_ks = qualitymod._ks_rows(prof["bin_counts"], counts)
    worst = int(np.argmax(f_psi))
    worst_psi = float(f_psi[worst])
    status = (
        "alert" if worst_psi >= qualitymod.DEFAULT_ALERT_PSI
        else "warn" if worst_psi >= qualitymod.DEFAULT_WARN_PSI
        else "ok"
    )
    return {
        "rows": int(X.shape[0]),
        "status": status,
        "worst_feature_index": worst,
        "worst_psi": worst_psi,
        "feature_psi": [float(v) for v in f_psi],
        "feature_ks": [float(v) for v in f_ks],
    }


def mean_disagreement(members: np.ndarray | None) -> float | None:
    """Mean pairwise |p_i − p_j| across members — ``None`` (not NaN) for
    a memberless family, the strict-JSON convention."""
    if members is None:
        return None
    members = np.asarray(members, np.float64)
    n, m = members.shape
    if n == 0 or m < 2:
        return None
    return float(qualitymod.pairwise_disagreement(members).mean())


# ---------------------------------------------------------------------------
# Replay (lazy jax — the eager oracle composition)
# ---------------------------------------------------------------------------


def replay_scores(
    params: Any, X17: np.ndarray, chunk_rows: int = 512
) -> tuple[np.ndarray, np.ndarray | None, np.ndarray]:
    """Score contract-order rows through the eager oracle composition —
    the exact ``cli predict`` route the deploy parity probe pins — and
    return ``(p1[n], members[n, M] | None, monitored_rows[n, F])``.
    ``monitored_rows`` is the matrix in the family's quality-profile
    space: the contract rows themselves for a bare ensemble, the
    post-impute post-select matrix for a full pipeline (the space its
    reference profile was built over)."""
    import numpy as _np

    from machine_learning_replications_tpu.models import (
        pipeline, stacking, tree,
    )

    X17 = _np.asarray(X17, _np.float64)
    if X17.ndim != 2 or X17.shape[1] != 17:
        raise ValueError(f"replay rows must be [n, 17], got {X17.shape}")
    p1_parts, member_parts, row_parts = [], [], []
    for s in range(0, X17.shape[0], max(1, int(chunk_rows))):
        chunk = X17[s:s + chunk_rows]
        if isinstance(params, pipeline.PipelineParams):
            x64 = pipeline.contract_rows_to_x64(params, chunk)
            X17sel = _np.asarray(pipeline.impute_select(params, x64))
            p1, members = stacking.predict_proba1_with_members(
                params.ensemble, X17sel
            )
            qrows = X17sel
        elif isinstance(params, tree.TreeEnsembleParams):
            p1, members, qrows = tree.predict_proba1(params, chunk), None, chunk
        else:
            p1, members = stacking.predict_proba1_with_members(params, chunk)
            qrows = chunk
        p1_parts.append(_np.asarray(p1, _np.float64))
        row_parts.append(_np.asarray(qrows, _np.float64))
        if members is not None:
            member_parts.append(_np.asarray(members, _np.float64))
    p1 = _np.concatenate(p1_parts) if p1_parts else _np.zeros(0)
    rows = (
        _np.concatenate(row_parts) if row_parts
        else _np.zeros((0, X17.shape[1]))
    )
    members = _np.concatenate(member_parts) if member_parts else None
    return p1, members, rows


# ---------------------------------------------------------------------------
# The evaluator
# ---------------------------------------------------------------------------


def evaluate(
    live_params: Any,
    candidate_params: Any,
    X17: np.ndarray,
    thresholds: ShadowThresholds | None = None,
    candidate_version: int | None = None,
) -> dict:
    """Run the full shadow comparison and return the verdict dict
    (strict-JSON; ``verdict["pass"]`` is the promotion gate's input).
    Gauges and the ``learn_shadow_verdict`` journal event are updated as
    a side effect — the evaluation IS the observable."""
    thresholds = thresholds or ShadowThresholds()
    p_live, m_live, _ = replay_scores(live_params, X17)
    p_cand, m_cand, cand_rows = replay_scores(candidate_params, X17)
    stats = score_divergence(p_live, p_cand)

    dis_live = mean_disagreement(m_live)
    dis_cand = mean_disagreement(m_cand)
    stats["disagreement_live"] = dis_live
    stats["disagreement_candidate"] = dis_cand
    stats["disagreement_delta"] = (
        None if dis_live is None or dis_cand is None
        else dis_cand - dis_live
    )

    cand_profile = getattr(candidate_params, "quality", None)
    if cand_profile is not None:
        cq = cohort_quality(cand_profile, cand_rows)
        stats["candidate_quality"] = {
            "status": cq["status"], "worst_psi": cq["worst_psi"],
            "rows": cq["rows"],
        }
    else:
        stats["candidate_quality"] = None

    verdict = judge(stats, thresholds)
    verdict["candidate_version"] = candidate_version
    _export(stats)
    EVALUATIONS.inc(verdict="pass" if verdict["pass"] else "fail")
    journal.event(
        "learn_shadow_verdict",
        passed=verdict["pass"],
        reasons=verdict["reasons"],
        candidate_version=candidate_version,
        **{k: stats[k] for k in (
            "rows", "divergence_mean", "divergence_p95", "divergence_max",
            "flip_rate", "score_psi", "disagreement_delta",
        )},
        candidate_quality=stats["candidate_quality"],
    )
    return verdict


def judge(stats: dict, thresholds: ShadowThresholds) -> dict:
    """Apply the thresholds to a stats block: ``{"pass", "reasons",
    "stats", "thresholds"}``. Pure — the both-sides threshold tests pin
    this. A not-computable statistic (``None``) fails closed where the
    thresholds demand it: a gate that cannot measure must refuse, not
    wave through."""
    reasons: list[str] = []
    rows = stats.get("rows") or 0
    if rows < thresholds.min_rows:
        reasons.append(
            f"replay has {rows} rows, below min_rows={thresholds.min_rows}"
        )
    for key, bound in (
        ("divergence_mean", thresholds.max_divergence_mean),
        ("divergence_p95", thresholds.max_divergence_p95),
        ("flip_rate", thresholds.max_flip_rate),
        ("score_psi", thresholds.max_score_psi),
    ):
        v = stats.get(key)
        if v is not None and v > bound:
            reasons.append(f"{key} {v:.6g} exceeds {bound:g}")
    dd = stats.get("disagreement_delta")
    if dd is not None and dd > thresholds.max_disagreement_delta:
        reasons.append(
            f"disagreement_delta {dd:.6g} exceeds "
            f"{thresholds.max_disagreement_delta:g}"
        )
    cq = stats.get("candidate_quality")
    if cq is None:
        if thresholds.require_candidate_profile:
            reasons.append(
                "candidate carries no quality reference profile"
            )
    elif cq["worst_psi"] > thresholds.max_candidate_psi:
        reasons.append(
            f"candidate self-quality {cq['status']} (worst PSI "
            f"{cq['worst_psi']:.6g} exceeds "
            f"{thresholds.max_candidate_psi:g}): the replayed cohort "
            "does not match the candidate's own training reference"
        )
    return {
        "pass": not reasons,
        "reasons": reasons,
        "stats": _jsonsafe(stats),
        "thresholds": thresholds.as_dict(),
    }


def _jsonsafe(stats: dict) -> dict:
    """Strict-JSON copy: every float rounded, NaN coerced to None (the
    PR 1 convention — a bare NaN token breaks strict parsers)."""
    def fix(v):
        if isinstance(v, dict):
            return {k: fix(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [fix(x) for x in v]
        if isinstance(v, float):
            return None if v != v else round(v, 6)
        return v

    return {k: fix(v) for k, v in stats.items()}


def _export(stats: dict) -> None:
    """Gauge-side rendering of the stats block: ``None`` (JSON's "no
    data") becomes NaN (the gauge convention, legal under the strict
    validator) — the two surfaces stay consistent by construction."""
    def val(v):
        return float("nan") if v is None else float(v)

    _G["divergence_mean"].get().set(val(stats.get("divergence_mean")))
    _G["divergence_p95"].get().set(val(stats.get("divergence_p95")))
    _G["divergence_max"].get().set(val(stats.get("divergence_max")))
    _G["flip_rate"].get().set(val(stats.get("flip_rate")))
    _G["score_psi"].get().set(val(stats.get("score_psi")))
    _G["disagreement_delta"].get().set(val(stats.get("disagreement_delta")))
    cq = stats.get("candidate_quality")
    if cq is None:
        _G["candidate_worst_psi"].get().set(float("nan"))
        _G["candidate_status"].get().set(float("nan"))
    else:
        _G["candidate_worst_psi"].get().set(val(cq.get("worst_psi")))
        _G["candidate_status"].get().set(
            float(qualitymod._STATUS_LEVEL[cq["status"]])
        )
    _G["rows"].get().set(float(stats.get("rows") or 0))
