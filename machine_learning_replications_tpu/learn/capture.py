"""Bounded recent-cohort capture — the continual-learning loop's data tap.

A drift-triggered refit needs the one thing training never had: the rows
the fleet is serving *right now*. This module captures them at the front
door: the router appends every served (HTTP 200) ``/predict`` body to a
rotating set of JSONL shards — ``cohort-00000.jsonl``, ... — in exactly
the 17-variable patient-dict format the rest of the stack already speaks
(``tools/loadgen.py --patients`` writes it, ``score.reader``'s
``JsonlCohortSource`` streams it, ``data.examples.validate_patient``
validates it). The shard discipline mirrors ``score.writer``: append-only
files, rotation every ``rows_per_shard`` rows — with one inversion: the
score writer keeps *everything* it commits, while the capture buffer
keeps only the newest ``max_shards`` shards and unlinks the oldest, so
the on-disk cohort is a bounded sliding window over recent traffic
(~``max_shards × rows_per_shard`` rows), never an unbounded log under a
serving process that runs for months.

Capture is deliberately *raw*: the router appends the admitted body
bytes without parsing them (a JSON parse per request on the proxy hot
path would be a measurable tax at four-digit qps). Validation happens
once, at refit time: ``load_recent`` routes the captured lines through
``score.reader.parse_patient_lines`` — the same quarantine-don't-die
contract bulk scoring uses — so a malformed line captured from a hostile
client costs the refit one dropped row, not a crash.

jax-free by construction (rule ``import-purity`` via the fleet
manifest): the router process imports this module.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading

import numpy as np

from machine_learning_replications_tpu.obs.registry import REGISTRY

SHARD_FMT = "cohort-{:05d}.jsonl"
_SHARD_RE = re.compile(r"^cohort-(\d{5})\.jsonl$")

CAPTURED_ROWS = REGISTRY.counter(
    "learn_capture_rows_total",
    "Served rows appended to the recent-cohort capture buffer.",
)
CAPTURE_RETAINED = REGISTRY.gauge(
    "learn_capture_retained_rows",
    "Rows currently retained in the bounded capture buffer (oldest "
    "shards beyond the bound are unlinked).",
)


class CohortCapture:
    """Rotating, bounded JSONL capture of served patient rows.

    ``append_line`` is the hot-path entry (router ``finish``, ok replies
    only): normalize the body to one line, append, flush (no fsync —
    the buffer is a best-effort recent window, not a ledger; a crash
    loses at most the page cache's tail and the window refills in
    seconds under live traffic). Thread-safe: the router's forwarder
    threads and loop timers all land here.
    """

    def __init__(
        self,
        out_dir: str | os.PathLike,
        rows_per_shard: int = 4096,
        max_shards: int = 8,
    ) -> None:
        if rows_per_shard < 1 or max_shards < 1:
            raise ValueError("rows_per_shard and max_shards must be >= 1")
        self.out_dir = os.path.abspath(os.fspath(out_dir))
        os.makedirs(self.out_dir, exist_ok=True)
        self.rows_per_shard = int(rows_per_shard)
        self.max_shards = int(max_shards)
        self._lock = threading.Lock()
        self._f = None
        self._closed = False
        self._rows_in_shard = 0
        self._rows_total = 0
        # Resume the shard sequence past anything already on disk: a
        # restarted router keeps appending instead of overwriting the
        # previous window's newest shard.
        existing = _shard_indices(self.out_dir)
        self._next_index = (existing[-1] + 1) if existing else 0
        self._retained = {
            i: _count_lines(self._shard_path(i)) for i in existing
        }
        CAPTURE_RETAINED.get().set(float(sum(self._retained.values())))

    def _shard_path(self, index: int) -> str:
        return os.path.join(self.out_dir, SHARD_FMT.format(index))

    def append_line(self, body: bytes | str | dict) -> None:
        """Append one served row. ``bytes``/``str`` bodies are appended
        raw (newlines normalized to spaces — legal JSON never carries a
        raw newline inside a token, so this cannot corrupt a valid row);
        dicts are serialized compactly."""
        if isinstance(body, dict):
            line = json.dumps(body, separators=(",", ":")).encode()
        else:
            raw = body.encode() if isinstance(body, str) else bytes(body)
            line = raw.replace(b"\r", b" ").replace(b"\n", b" ").strip()
        if not line:
            return
        with self._lock:
            if self._closed:
                # Router shutdown: a forwarder thread finishing its last
                # in-flight request may land here after close() — the
                # `_f is None` branch below would silently re-open a
                # fresh shard (leaked fd, stray post-shutdown rows).
                return
            if self._f is None or self._rows_in_shard >= self.rows_per_shard:
                self._rotate_locked()
            self._f.write(line + b"\n")
            self._f.flush()
            self._rows_in_shard += 1
            self._rows_total += 1
            self._retained[self._next_index - 1] = self._rows_in_shard
            retained = sum(self._retained.values())
        CAPTURED_ROWS.inc()
        CAPTURE_RETAINED.get().set(float(retained))

    def _rotate_locked(self) -> None:
        if self._f is not None:
            self._f.close()
        self._f = open(self._shard_path(self._next_index), "ab")
        self._rows_in_shard = 0
        self._retained[self._next_index] = 0
        self._next_index += 1
        # Enforce the bound: unlink oldest shards beyond max_shards.
        while len(self._retained) > self.max_shards:
            oldest = min(self._retained)
            self._retained.pop(oldest)
            try:
                os.unlink(self._shard_path(oldest))
            except OSError:
                pass

    def stats(self) -> dict:
        with self._lock:
            return {
                "dir": self.out_dir,
                "rows_appended": self._rows_total,
                "rows_retained": sum(self._retained.values()),
                "shards": len(self._retained),
                "rows_per_shard": self.rows_per_shard,
                "max_shards": self.max_shards,
            }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._f is not None:
                self._f.close()
                self._f = None


def _shard_indices(out_dir: str) -> list[int]:
    out = []
    for fp in glob.glob(os.path.join(out_dir, "cohort-*.jsonl")):
        m = _SHARD_RE.match(os.path.basename(fp))
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def _count_lines(path: str) -> int:
    try:
        with open(path, "rb") as f:
            return sum(1 for _ in f)
    except OSError:
        return 0


def load_recent(
    capture_dir: str | os.PathLike, max_rows: int = 8192
) -> tuple[np.ndarray, int]:
    """The refit's read side: the newest ``max_rows`` captured rows as a
    contract-order ``(X[n, 17], n_bad)`` pair, oldest first. Lines that
    fail the 17-variable contract are dropped and counted (the
    ``score.reader`` quarantine policy, without the sidecar — the capture
    buffer is a window, not an audit trail)."""
    from machine_learning_replications_tpu.score.reader import (
        parse_patient_lines,
    )

    if max_rows < 1:
        raise ValueError(f"max_rows must be >= 1, got {max_rows}")
    capture_dir = os.path.abspath(os.fspath(capture_dir))
    lines: list[str] = []
    # Newest-first over shards, newest-first within each, until the row
    # budget is met — then restore oldest-first order for the refit.
    for idx in reversed(_shard_indices(capture_dir)):
        if len(lines) >= max_rows:
            break
        try:
            with open(
                os.path.join(capture_dir, SHARD_FMT.format(idx)),
                encoding="utf-8", errors="replace",
            ) as f:
                shard_lines = f.readlines()
        except OSError:
            continue
        take = max_rows - len(lines)
        lines.extend(reversed(shard_lines[-take:] if take < len(shard_lines)
                              else shard_lines))
    lines.reverse()
    X, _line_nos, bad = parse_patient_lines(lines, start_line=1)
    return X, len(bad)
