"""Drift-triggered retraining: the decision of WHEN to act.

PR 4's quality monitor turns silent input drift into a journaled
``ok → warn → alert`` status on every replica; this module turns a
*sustained* alert into exactly one retrain decision. Three rules, all
tuned against the failure modes a naive "retrain on alert" trigger has:

  * **Debounce** — ``alert_streak`` consecutive alert observations
    before firing. A single alert snapshot can be a burst of outlier
    patients or one poll racing a window refresh; retraining is
    expensive and swaps a clinical model, so it must answer to a
    *sustained* signal. The replica-side transition ring
    (``/debug/quality``'s ``transitions`` — the PR 10 satellite) rides
    each poll, so flapping (alert → ok → alert between polls) is visible
    in one payload instead of requiring a journal tail.
  * **Cooldown** — ``cooldown_s`` between fires. A refit takes minutes
    and its effect lands only after shadow + promotion; re-firing while
    the previous cycle is in flight would stack retrains of the same
    drift.
  * **Schedule** — an optional ``schedule_s`` periodic fire (subject to
    the same cooldown), for cohorts that drift too slowly to alert but
    accumulate bias worth refreshing on a calendar.

Every observation that *could* fire journals a ``learn_trigger`` event —
fired or suppressed, with the suppressing rule and the offending
features — so the journal answers "why did/didn't the loop act at t?"
without reconstruction.

jax-free (enforced: graftcheck rule ``import-purity``): the trigger
is an HTTP poller plus a tiny state machine; it
runs happily inside the router process or the ``cli learn run`` daemon.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Any

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY

TRIGGERS = REGISTRY.counter(
    "learn_trigger_total",
    "Continual-learning trigger decisions by outcome (fired: a retrain "
    "cycle starts; suppressed_debounce / suppressed_cooldown: an alert "
    "observation that did not fire).",
    labels=("outcome",),
)
for _o in ("fired", "suppressed_debounce", "suppressed_cooldown"):
    TRIGGERS.labels(outcome=_o)
ALERT_STREAK = REGISTRY.gauge(
    "learn_trigger_alert_streak",
    "Consecutive alert observations across the polled fleet (resets on "
    "any non-alert poll).",
)
ALERT_STREAK.get().set(0.0)


def poll_quality(url: str, timeout_s: float = 5.0) -> dict:
    """One replica's ``/debug/quality`` payload reduced to what the
    trigger needs: ``{"ok", "status", "worst_feature", "worst_psi",
    "transitions"}``. Never raises — an unreachable replica reads as
    ``ok=False`` and simply doesn't vote this poll."""
    try:
        with urllib.request.urlopen(
            url.rstrip("/") + "/debug/quality", timeout=timeout_s
        ) as resp:
            body = json.loads(resp.read())
        return {
            "ok": True,
            "status": body.get("status"),
            "worst_feature": body.get("worst_feature"),
            "worst_psi": body.get("worst_psi"),
            "transitions": body.get("transitions") or [],
        }
    except Exception as exc:
        return {
            "ok": False, "status": None, "worst_feature": None,
            "worst_psi": None, "transitions": [],
            "error": f"{type(exc).__name__}: {exc}",
        }


def replica_urls(router_url: str, timeout_s: float = 5.0) -> list[str]:
    """The fleet's replica URLs off the router's registry snapshot —
    the trigger polls replicas directly (quality lives replica-side; the
    router is jax-free and has no monitor)."""
    with urllib.request.urlopen(
        router_url.rstrip("/") + "/fleet/replicas", timeout=timeout_s
    ) as resp:
        snap = json.loads(resp.read())["replicas"]
    return [r["url"] for r in snap]


class TriggerPolicy:
    """The debounce/cooldown/schedule state machine. Feed it one
    ``observe(...)`` per poll pass; it returns a decision dict when a
    retrain should start, else ``None``. Pure of I/O — the daemon owns
    polling, this owns policy (the ``HealthProber``/``ReplicaRegistry``
    split, again)."""

    def __init__(
        self,
        alert_streak: int = 3,
        cooldown_s: float = 600.0,
        schedule_s: float | None = None,
        clock=time.monotonic,
    ) -> None:
        if alert_streak < 1:
            raise ValueError("alert_streak must be >= 1")
        if cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        if schedule_s is not None and schedule_s <= 0:
            raise ValueError("schedule_s must be > 0 when set")
        self.alert_streak = int(alert_streak)
        self.cooldown_s = float(cooldown_s)
        self.schedule_s = None if schedule_s is None else float(schedule_s)
        self._clock = clock
        self._streak = 0
        self._last_fire_t: float | None = None
        self._started_t = clock()

    # -- policy --------------------------------------------------------------

    def observe(self, polls: list[dict]) -> dict | None:
        """One poll pass over the fleet: ``polls`` is
        ``[{"url", ...poll_quality payload}]``. Fires on a sustained
        alert (any replica alerting counts — drift is a property of the
        traffic, and the first replica to see enough window rows speaks
        for the cohort) or on schedule. Every suppressed alert is
        journaled too (the "every decision" contract)."""
        now = self._clock()
        alerting = [p for p in polls if p.get("status") == "alert"]
        reachable = [p for p in polls if p.get("ok")]
        if alerting:
            self._streak += 1
        elif reachable:
            self._streak = 0
        ALERT_STREAK.get().set(float(self._streak))

        worst = self._worst(alerting)
        if alerting:
            if self._streak < self.alert_streak:
                self._journal(
                    fired=False, reason="alert",
                    suppressed_by="debounce", worst=worst,
                    alerting=[p.get("url") for p in alerting],
                )
                TRIGGERS.inc(outcome="suppressed_debounce")
                return None
            if self._in_cooldown(now):
                self._journal(
                    fired=False, reason="alert",
                    suppressed_by="cooldown", worst=worst,
                    alerting=[p.get("url") for p in alerting],
                )
                TRIGGERS.inc(outcome="suppressed_cooldown")
                return None
            return self._fire(now, "alert", worst, alerting)
        if self.schedule_s is not None and not self._in_cooldown(now):
            anchor = (
                self._last_fire_t if self._last_fire_t is not None
                else self._started_t
            )
            if now - anchor >= self.schedule_s:
                return self._fire(now, "schedule", worst, alerting)
        return None

    # -- internals -----------------------------------------------------------

    def _in_cooldown(self, now: float) -> bool:
        return (
            self._last_fire_t is not None
            and now - self._last_fire_t < self.cooldown_s
        )

    def cooldown_remaining_s(self) -> float:
        if self._last_fire_t is None:
            return 0.0
        return max(
            0.0, self.cooldown_s - (self._clock() - self._last_fire_t)
        )

    def _worst(self, alerting: list[dict]) -> dict | None:
        """The worst offending feature across alerting replicas — what
        the journaled decision names as the drift's face."""
        best = None
        for p in alerting:
            psi = p.get("worst_psi")
            if psi is not None and (best is None or psi > best["psi"]):
                best = {"feature": p.get("worst_feature"), "psi": psi}
        return best

    def _fire(
        self, now: float, reason: str, worst: dict | None,
        alerting: list[dict],
    ) -> dict:
        self._last_fire_t = now
        self._streak = 0
        ALERT_STREAK.get().set(0.0)
        TRIGGERS.inc(outcome="fired")
        decision = {
            "reason": reason,
            "worst_feature": worst["feature"] if worst else None,
            "worst_psi": worst["psi"] if worst else None,
            "alerting_replicas": [p.get("url") for p in alerting],
        }
        self._journal(fired=True, reason=reason, worst=worst,
                      alerting=decision["alerting_replicas"])
        return decision

    def _journal(
        self, fired: bool, reason: str, worst: dict | None,
        alerting: list[Any], suppressed_by: str | None = None,
    ) -> None:
        journal.event(
            "learn_trigger",
            fired=fired,
            reason=reason,
            suppressed_by=suppressed_by,
            streak=self._streak,
            alert_streak_needed=self.alert_streak,
            cooldown_remaining_s=round(self.cooldown_remaining_s(), 3),
            worst_feature=worst["feature"] if worst else None,
            worst_psi=worst["psi"] if worst else None,
            alerting_replicas=alerting,
        )
