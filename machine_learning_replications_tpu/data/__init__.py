"""L2 — data ingestion.

Host-side ingest (``.mat`` files, synthetic cohorts) producing arrays that are
then placed onto the TPU mesh as sharded DeviceArrays (see ``sharding.py``).
Reference contract: ``HF/load_data_public.py:4-14``.

Re-exports resolve lazily (PEP 562): ``sharding`` imports jax at module
level, and importing any submodule of this package executes this
``__init__`` — an eager re-export here put jax into the import-time
closure of every consumer of ``data.examples``/``data.schema``, including
the declared-jax-free ``score.reader`` parse path (graftcheck rule
``import-purity``; the jax-free manifest lives in ``analysis/project.py``).
"""

from machine_learning_replications_tpu.lazyimport import lazy_exports

_EXPORTS = {
    "load_data": "matloader",
    "save_data": "matloader",
    "COHORT_SCHEMA": "schema",
    "N_COHORT": "schema",
    "SELECTED_17": "schema",
    "selected_indices": "schema",
    "variable_names": "schema",
    "make_cohort": "synthetic",
    "shard_rows": "sharding",
    "pad_rows": "sharding",
}

__all__ = sorted(_EXPORTS)
__getattr__, __dir__ = lazy_exports(__name__, _EXPORTS)
