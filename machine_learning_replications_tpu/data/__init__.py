"""L2 — data ingestion.

Host-side ingest (``.mat`` files, synthetic cohorts) producing arrays that are
then placed onto the TPU mesh as sharded DeviceArrays (see ``sharding.py``).
Reference contract: ``HF/load_data_public.py:4-14``.
"""

from machine_learning_replications_tpu.data.matloader import load_data, save_data
from machine_learning_replications_tpu.data.schema import (
    COHORT_SCHEMA,
    N_COHORT,
    SELECTED_17,
    selected_indices,
    variable_names,
)
from machine_learning_replications_tpu.data.synthetic import make_cohort
from machine_learning_replications_tpu.data.sharding import shard_rows, pad_rows

__all__ = [
    "load_data",
    "save_data",
    "make_cohort",
    "shard_rows",
    "pad_rows",
    "COHORT_SCHEMA",
    "N_COHORT",
    "SELECTED_17",
    "selected_indices",
    "variable_names",
]
