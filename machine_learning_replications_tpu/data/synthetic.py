"""Synthetic cohort generator matched to Table S1 marginals.

The reference's training data (``develop_data.mat`` / ``model_select_data.mat``,
loaded at ``train_ensemble_public.py:36,39``) is not shipped; only the fitted
pickle is. Parity and benchmarking therefore run on synthetic cohorts whose
marginals match Supplementary Table S1 (see ``schema.py``) and whose outcome is
generated from a logistic model over the 17 contractual features with
coefficient signs matching the decoded L1-LR member of the shipped model
(SURVEY.md §2.3), calibrated to the fit-split class prior 19.776 % positive
(pickle: ``DummyClassifier.class_prior_ = [0.80224, 0.19776]``).

Host-side numpy by design — ingest stays on host, then ``sharding.shard_rows``
places the arrays onto the TPU mesh (BASELINE.json north star: the loader
"emits sharded DeviceArrays").
"""

from __future__ import annotations

import numpy as np

from machine_learning_replications_tpu.data import schema

# Logistic outcome coefficients over SELECTED_17, sign-matched to the decoded
# L1-LR base member (SURVEY.md §2.3: coef_ = [1.1247, -0.2490, ...]).
_OUTCOME_COEF = np.array(
    [
        1.12, -0.25, 0.39, 1.20, 0.56, 1.42, 0.42, 0.20, -0.22,
        0.59, 0.36, -0.42, 1.23, 0.042, 0.77, 0.20, -0.065,
    ]
)

TARGET_POSITIVE_RATE = 0.19776  # pickle class prior


def _sample_column(rng: np.random.Generator, spec: schema.VariableSpec, n: int) -> np.ndarray:
    if spec.kind == "binary":
        return (rng.random(n) < spec.p).astype(np.float64)
    if spec.kind == "continuous":
        if spec.median == 0.0:
            # Heavily right-skewed (LVOT / mid-cavity gradients: median 0,
            # mean ≪ sd). Zero-inflated exponential matches the published
            # mean and the zero median.
            q = 0.5
            x = rng.exponential(spec.mean / (1 - q), size=n)
            x[rng.random(n) < q] = 0.0
            return x
        x = rng.normal(spec.mean, spec.sd, size=n)
        # Clinical measurements are non-negative.
        return np.maximum(x, 0.0)
    if spec.kind == "ordinal":
        levels = np.arange(spec.lo, spec.hi + 1)
        # Geometric-ish mass decaying away from the median level.
        w = 0.5 ** np.abs(levels - spec.median)
        return rng.choice(levels, size=n, p=w / w.sum()).astype(np.float64)
    raise ValueError(spec.kind)


def make_cohort(
    n: int = schema.N_COHORT,
    seed: int = 2020,
    missing_rate: float = 0.0,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Generate ``(X[n,64] float64, y[n] float64, var_names[1,64] object)``.

    Return types mirror ``load_data_public.py:4-14``'s contract exactly
    (float64 X/y; names as a (1, 64) object row so ``names[0, mask]`` works as
    at ``train_ensemble_public.py:55``).

    ``missing_rate`` > 0 masks that fraction of entries to NaN (MCAR) in the
    continuous/ordinal columns, exercising the KNN imputation path
    (``train_ensemble_public.py:37-40``).
    """
    rng = np.random.default_rng(seed)
    # Fill a preallocated matrix column-by-column: np.stack's 64×n temporary
    # copy was the single largest cost of a 10M-row cohort build (bench
    # config 5 spent ~3.5 min of its budget generating data, r3 profile).
    X = np.empty((n, len(schema.COHORT_SCHEMA)), dtype=np.float64)
    for j, spec in enumerate(schema.COHORT_SCHEMA):
        X[:, j] = _sample_column(rng, spec, n)

    sel = schema.selected_indices()
    Xs = X[:, sel]
    # Standardize continuous scales so one unit of each feature contributes
    # comparably, then calibrate the intercept to the target prior by
    # bisection. Calibration only needs the MEAN sigmoid, so it runs on a
    # leading subsample — a 262k-row estimate of a 0.198 rate is exact to
    # ~1e-3, far tighter than the class-prior variation between seeds —
    # instead of 60 full-cohort exp() passes.
    mu, sd = Xs.mean(0), Xs.std(0) + 1e-12
    logits = ((Xs - mu) / sd) @ _OUTCOME_COEF
    cal = logits[: min(n, 262_144)]
    lo, hi = -20.0, 20.0
    for _ in range(60):
        mid = 0.5 * (lo + hi)
        if (1 / (1 + np.exp(-(cal + mid)))).mean() > TARGET_POSITIVE_RATE:
            hi = mid
        else:
            lo = mid
    p = 1 / (1 + np.exp(-(logits + 0.5 * (lo + hi))))
    y = (rng.random(n) < p).astype(np.float64)

    if missing_rate > 0.0:
        mask = rng.random(X.shape) < missing_rate
        # Only non-binary columns go missing (binary indicators are charted).
        nonbin = np.array([s.kind != "binary" for s in schema.COHORT_SCHEMA])
        X[mask & nonbin[None, :]] = np.nan

    names = np.array([schema.variable_names()], dtype=object)
    return X, y, names


def dev_select_split(
    X: np.ndarray, y: np.ndarray, seed: int = 2020
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic 713/714 development / model-selection split.

    The shipped model was fitted on 713 of 1427 rows (pickle:
    ``SVC.shape_fit_ = (713, 17)``); the split itself is not in the public
    code, so we define a seeded permutation split with the same sizes.
    """
    n = X.shape[0]
    n_dev = round(n * 713 / 1427)
    perm = np.random.default_rng(seed).permutation(n)
    dev, sel = perm[:n_dev], perm[n_dev:]
    return X[dev], y[dev], X[sel], y[sel]
