"""Cohort schema — the 64 clinical variables of Supplementary Table S1.

The reference ships the schema only as a Word table (``HF/Table 1.DOCX``,
"Supplementary Table S1", n=1427 HCM patients); the feature matrix contract is
``data_tb[:, :64]`` + outcome in the last column (``HF/load_data_public.py:9-10``).
This module encodes every variable with its published marginal so the synthetic
cohort generator (``synthetic.py``) can emit statistically matched data — the
real ``.mat`` cohorts are not shipped (``train_ensemble_public.py:36,39`` load
files absent from the repo).

Marginals transcribed from Table S1:
  binary      → ``count (percent)`` of 1427
  continuous  → ``mean ± sd (median)``
  ordinal     → ``lo-hi (median)``
"""

from __future__ import annotations

import dataclasses

N_COHORT = 1427  # Table S1 caption cohort size


@dataclasses.dataclass(frozen=True)
class VariableSpec:
    name: str
    kind: str  # 'binary' | 'continuous' | 'ordinal'
    # binary: p = prevalence; continuous: mean/sd; ordinal: lo/hi/median
    p: float = 0.0
    mean: float = 0.0
    sd: float = 1.0
    lo: int = 0
    hi: int = 0
    median: float = 0.0


def _b(name: str, count: int) -> VariableSpec:
    return VariableSpec(name, "binary", p=count / N_COHORT)


def _c(name: str, mean: float, sd: float, median: float) -> VariableSpec:
    return VariableSpec(name, "continuous", mean=mean, sd=sd, median=median)


def _o(name: str, lo: int, hi: int, median: float) -> VariableSpec:
    return VariableSpec(name, "ordinal", lo=lo, hi=hi, median=median)


# Order follows Table S1 top-to-bottom (the reference's .mat column order is
# unknowable — only the post-selection 17-feature order is contractual, see
# SELECTED_17 below / predict_hf.py:5-27).
COHORT_SCHEMA: tuple[VariableSpec, ...] = (
    _b("Gender", 985),  # 1 = female (predict_hf.py:7)
    _c("Age at HCM diagnosis", 45, 18, 48),
    _b("Obstructive HCM", 747),
    _b("Massive hypertrophy", 84),
    _b("Non-sustained ventricular tachycardia on holter", 137),
    _b("Syncope", 137),
    _b("Dyspnea", 645),
    _b("Chest pain", 252),
    _b("Fatigue", 198),
    _b("Presyncope", 71),
    _b("Palpitations", 192),
    _o("NYHA_Class", 1, 2, 1),
    _b("ICD", 159),
    _b("Appropriate ICD shocks prior to initial visit", 17),
    _o("Number of ICD shocks", 0, 8, 0),
    _b("Permanent pace maker", 21),
    _b("Mitral valve surgery", 2),
    _b("VT ablation", 4),
    _b("Coronary artery bypass graft", 6),
    _b("Stents", 36),
    _b("Cardioversion", 64),
    _o("Number of DC cardioversions", 0, 4, 0),
    _b("Atrial fibrillation ablation", 16),
    _o("Number of AF ablations", 0, 3, 0),
    _b("Recurrent AF after ablation", 13),
    _b("Atrial_Fibrillation", 199),
    _b("Resuscitated cardiac arrest prior to initial visit", 24),
    _b("Hypertension", 461),
    _b("Coronary artery disease", 79),
    _b("Prior myocardial infarction", 22),
    _b("Stroke", 31),
    _o("Type of stroke", 0, 2, 0),
    _b("Family history of SCD", 154),
    _o("FH SCD: relation to patient", 0, 4, 0),
    _b("FH SCD: multiple relatives", 54),
    _b("Family history of HCM", 369),
    _b("Family history of end stage HCM", 41),
    _b("Family history of heart transplant due to HCM", 26),
    _b("Beta_blocker", 807),
    _b("Ca_Channel_Blockers", 290),
    _b("Disopyramide", 20),
    _b("ACEI_ARB", 309),
    _b("Spironolactone", 16),
    _b("Diuretic", 151),
    _b("Amiodarone", 27),
    _b("Coumadin", 80),
    _b("Aspirin", 405),
    _b("Statin", 459),
    _b("Novel anti-coagulation", 51),
    _b("Other anti-arrhythmic", 44),
    _b("Other cardiac medications", 38),
    _c("Max_Wall_Thick", 19, 5, 17),
    _b("Septal_Anterior_Motion", 927),
    _c("LVOT gradient", 19, 35, 0),
    _c("Mid-cavity obstruction gradient", 3, 12, 0),
    _o("Mitral_Regurgitation", 0, 4, 0),
    _c("Ejection_Fraction", 64, 5, 65),
    _c("LA diameter", 40, 7, 40),
    _c("LV end diastolic diameter", 42, 7, 42),
    _c("LV end systolic diameter", 27, 6, 26),
    _b("Severe aortic stenosis", 9),
    _b("Apical HCM", 161),
    _b("Apical aneurysm", 42),
    _b("End-stage HCM", 25),
)

assert len(COHORT_SCHEMA) == 64, len(COHORT_SCHEMA)

# The 17 model-input variables in their contractual order (predict_hf.py:5-27).
SELECTED_17: tuple[str, ...] = (
    "Obstructive HCM",
    "Gender",
    "Syncope",
    "Dyspnea",
    "Fatigue",
    "Presyncope",
    "NYHA_Class",
    "Atrial_Fibrillation",
    "Hypertension",
    "Beta_blocker",
    "Ca_Channel_Blockers",
    "ACEI_ARB",
    "Coumadin",
    "Max_Wall_Thick",
    "Septal_Anterior_Motion",
    "Mitral_Regurgitation",
    "Ejection_Fraction",
)


def variable_names() -> list[str]:
    return [v.name for v in COHORT_SCHEMA]


def selected_indices() -> list[int]:
    """Column indices of the 17 contractual features within the 64-col schema."""
    names = variable_names()
    return [names.index(n) for n in SELECTED_17]
