"""The reference's example patient (``predict_hf.py:5-27``).

The insertion order of this dict IS the model input contract — the 17
Lasso-selected features in training order (SURVEY.md §2.2).
"""

from __future__ import annotations

import math

import numpy as np

EXAMPLE_PATIENT: dict[str, float] = {
    "Obstructive HCM": 1,
    "Gender": 1,
    "Syncope": 0,
    "Dyspnea": 0,
    "Fatigue": 1,
    "Presyncope": 0,
    "NYHA_Class": 1,
    "Atrial_Fibrillation": 1,
    "Hypertension": 0,
    "Beta_blocker": 0,
    "Ca_Channel_Blockers": 0,
    "ACEI_ARB": 0,
    "Coumadin": 0,
    "Max_Wall_Thick": 13,
    "Septal_Anterior_Motion": 0,
    "Mitral_Regurgitation": 0,
    "Ejection_Fraction": 55,
}


# The dict's insertion order IS the model input contract; keep it locked to
# the single source of truth in the schema.
from machine_learning_replications_tpu.data.schema import SELECTED_17 as _SELECTED_17

assert tuple(EXAMPLE_PATIENT) == _SELECTED_17, "example patient order drifted from schema"


def patient_row(params: dict[str, float] | None = None) -> np.ndarray:
    """Flatten a patient dict to the ``(1, 17)`` model input row, exactly as
    ``predict_hf.py:29-31`` does. One allocation — this runs per request
    on the serving hot path."""
    d = EXAMPLE_PATIENT if params is None else params
    return np.array(
        [d[k] for k in EXAMPLE_PATIENT], dtype=np.float64
    ).reshape(1, -1)


def validate_patient(patient: dict) -> np.ndarray:
    """Validate a patient dict against the 17-variable inference contract
    and return its ``(1, 17)`` row. One gate shared by every inference
    front end (``cli.py predict``, ``serve``'s ``/predict``): all 17
    variables present, no unknown keys, numeric values — silently
    defaulting clinical inputs would be unsafe (``predict_hf.py:5-27``)."""
    if not isinstance(patient, dict):
        raise ValueError(
            f"patient must be a JSON object of the 17 variables, got "
            f"{type(patient).__name__}"
        )
    unknown = set(patient) - set(EXAMPLE_PATIENT)
    if unknown:
        raise ValueError(f"unknown patient variables: {sorted(unknown)}")
    missing = [k for k in EXAMPLE_PATIENT if k not in patient]
    if missing:
        raise ValueError(
            "patient JSON must provide all 17 variables; missing: "
            + ", ".join(missing)
        )
    bad = [
        k for k, v in patient.items()
        if isinstance(v, bool)
        or not isinstance(v, (int, float))
        or not math.isfinite(v)
    ]
    if bad:
        # NaN/Infinity included: json.loads admits those tokens, a NaN
        # clinical input would be silently imputed by the pipeline route,
        # and a NaN probability is not representable in strict JSON.
        raise ValueError(
            f"non-numeric or non-finite patient variables: {sorted(bad)}"
        )
    return patient_row(patient)
