"""The reference's example patient (``predict_hf.py:5-27``).

The insertion order of this dict IS the model input contract — the 17
Lasso-selected features in training order (SURVEY.md §2.2).
"""

from __future__ import annotations

import numpy as np

EXAMPLE_PATIENT: dict[str, float] = {
    "Obstructive HCM": 1,
    "Gender": 1,
    "Syncope": 0,
    "Dyspnea": 0,
    "Fatigue": 1,
    "Presyncope": 0,
    "NYHA_Class": 1,
    "Atrial_Fibrillation": 1,
    "Hypertension": 0,
    "Beta_blocker": 0,
    "Ca_Channel_Blockers": 0,
    "ACEI_ARB": 0,
    "Coumadin": 0,
    "Max_Wall_Thick": 13,
    "Septal_Anterior_Motion": 0,
    "Mitral_Regurgitation": 0,
    "Ejection_Fraction": 55,
}


# The dict's insertion order IS the model input contract; keep it locked to
# the single source of truth in the schema.
from machine_learning_replications_tpu.data.schema import SELECTED_17 as _SELECTED_17

assert tuple(EXAMPLE_PATIENT) == _SELECTED_17, "example patient order drifted from schema"


def patient_row(params: dict[str, float] | None = None) -> np.ndarray:
    """Flatten a patient dict to the ``(1, 17)`` model input row, exactly as
    ``predict_hf.py:29-31`` does."""
    d = EXAMPLE_PATIENT if params is None else params
    return np.reshape([d[k] for k in EXAMPLE_PATIENT], (1, -1)).astype(np.float64)
