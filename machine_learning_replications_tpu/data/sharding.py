"""Device placement — host arrays → sharded DeviceArrays on the mesh.

BASELINE.json's north star: the loader "emits sharded DeviceArrays across the
TPU mesh". The reference has no device concept at all (pure single-process
numpy); here the cohort's row dimension is the data-parallel axis
(SURVEY.md §2.5), laid out with ``NamedSharding(mesh, P('data', None))`` so
per-shard histogram partials ride ICI via ``psum``.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pad_rows(x: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Pad axis 0 up to a multiple (XLA wants static, divisible shard shapes).

    Returns the padded array and the original row count. Padding rows are
    zeros; training/metric code masks them out via the returned count — a
    masked reduction, not a semantic change (SURVEY.md §7 "fold-size padding
    with masked reductions").
    """
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_width), n


def pad_rows_to(
    x: np.ndarray, rows: int, mode: str = "zero"
) -> tuple[np.ndarray, int]:
    """Pad axis 0 up to an exact row count — the fixed-chunk-shape variant
    of ``pad_rows`` (the bulk-scoring device stage holds its
    one-compile-for-the-run bound by padding every streamed chunk, tail
    included, to one static shape). ``mode='edge'`` replicates the last
    real row (the serving engine's padding: every predict path is a pure
    per-row map, so replicated rows cannot perturb real ones and, unlike
    zeros, cannot manufacture NaN/denormal edge cases in imputed feature
    space); ``'zero'`` keeps ``pad_rows``'s masked-reduction semantics.
    Returns ``(padded, n_real)``."""
    n = x.shape[0]
    if n > rows:
        raise ValueError(f"cannot pad {n} rows down to {rows}")
    if n == rows:
        return x, n
    if mode not in ("zero", "edge"):
        raise ValueError(f"unknown pad mode {mode!r}; use 'zero' or 'edge'")
    pad_width = [(0, rows - n)] + [(0, 0)] * (x.ndim - 1)
    if mode == "edge" and n > 0:
        return np.pad(x, pad_width, mode="edge"), n
    return np.pad(x, pad_width), n


def shard_rows(
    mesh: Mesh, *arrays: np.ndarray, axis: str = "data"
) -> tuple[tuple[jax.Array, ...] | jax.Array, int]:
    """Place arrays on ``mesh`` with rows sharded over ``axis``.

    Each array is padded with zero rows so its row count divides the axis
    size. Returns ``(sharded, n_rows)`` — padding rows are *fabricated*
    (e.g. outcome 0.0), so every consumer must mask reductions beyond
    ``n_rows``; the count is part of the contract, not optional metadata.
    """
    n_shards = mesh.shape[axis]
    out = []
    n_rows = None
    for a in arrays:
        padded, n = pad_rows(np.asarray(a), n_shards)
        if n_rows is None:
            n_rows = n
        elif n != n_rows:
            raise ValueError(f"row-count mismatch: {n} vs {n_rows}")
        spec = P(axis, *([None] * (padded.ndim - 1)))
        out.append(jax.device_put(padded, NamedSharding(mesh, spec)))
    return (out[0] if len(out) == 1 else tuple(out)), (n_rows or 0)
