"""Device placement — host arrays → sharded DeviceArrays on the mesh.

BASELINE.json's north star: the loader "emits sharded DeviceArrays across the
TPU mesh". The reference has no device concept at all (pure single-process
numpy); here the cohort's row dimension is the data-parallel axis
(SURVEY.md §2.5), laid out with ``NamedSharding(mesh, P('data', None))`` so
per-shard histogram partials ride ICI via ``psum``.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pad_rows(x: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Pad axis 0 up to a multiple (XLA wants static, divisible shard shapes).

    Returns the padded array and the original row count. Padding rows are
    zeros; training/metric code masks them out via the returned count — a
    masked reduction, not a semantic change (SURVEY.md §7 "fold-size padding
    with masked reductions").
    """
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_width), n


def shard_rows(
    mesh: Mesh, *arrays: np.ndarray, axis: str = "data"
) -> tuple[tuple[jax.Array, ...] | jax.Array, int]:
    """Place arrays on ``mesh`` with rows sharded over ``axis``.

    Each array is padded with zero rows so its row count divides the axis
    size. Returns ``(sharded, n_rows)`` — padding rows are *fabricated*
    (e.g. outcome 0.0), so every consumer must mask reductions beyond
    ``n_rows``; the count is part of the contract, not optional metadata.
    """
    n_shards = mesh.shape[axis]
    out = []
    n_rows = None
    for a in arrays:
        padded, n = pad_rows(np.asarray(a), n_shards)
        if n_rows is None:
            n_rows = n
        elif n != n_rows:
            raise ValueError(f"row-count mismatch: {n} vs {n_rows}")
        spec = P(axis, *([None] * (padded.ndim - 1)))
        out.append(jax.device_put(padded, NamedSharding(mesh, spec)))
    return (out[0] if len(out) == 1 else tuple(out)), (n_rows or 0)
