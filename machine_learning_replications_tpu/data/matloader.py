"""MAT-file ingestion (reference contract: ``HF/load_data_public.py:4-14``).

The ``.mat`` must contain ``data_tb`` (features + outcome in the last column)
and ``clin_var_names``; the loader returns float64 ``X[n, d]``, ``y[n]`` and
the names row. Two backends:

  * native  — the in-repo C++ MAT-v5 reader (``native/``, via ctypes), the
    TPU-build equivalent of scipy's C parser the reference leaned on;
  * scipy   — fallback, identical semantics.

Both are host-side by design; device placement happens in ``sharding.py``.
"""

from __future__ import annotations

import numpy as np


def load_data(
    dataset_path: str, backend: str = "auto"
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Load ``(X, Y, var_names)`` from a MAT file.

    Mirrors ``load_data_public.load_data``: features are all columns but the
    last of ``data_tb``; outcome is the last column; both cast to float64.
    """
    data, var_names = _read_mat(dataset_path, backend)
    X = data[:, :-1].astype(np.float64)
    Y = data[:, -1].astype(np.float64)
    return X, Y, var_names


def _read_mat(path: str, backend: str) -> tuple[np.ndarray, np.ndarray]:
    if backend not in ("auto", "native", "scipy"):
        raise ValueError(f"unknown backend {backend!r}; use auto | native | scipy")
    if backend in ("auto", "native"):
        try:
            from machine_learning_replications_tpu.native import matio

            out = matio.read_mat_vars(path, ["data_tb", "clin_var_names"])
            if out is None:
                raise RuntimeError("native matio backend unavailable")
            return out["data_tb"], out["clin_var_names"]
        except Exception:
            # 'auto' falls through to scipy on *any* native failure (missing
            # build, unsupported .mat variant); 'native' surfaces the error.
            if backend == "native":
                raise
    import scipy.io as sio

    d = sio.loadmat(path)
    return d["data_tb"], d["clin_var_names"]


#: ``data_tb`` widths the bulk-scoring loader understands: the model's
#: feature spaces bare (64 raw schema columns / 17 contract columns) or in
#: the reference training layout with the outcome appended as the last
#: column (65 / 18 — ``load_data_public.py:9-10``).
_SCORE_WIDTHS = {64: 64, 65: 64, 17: 17, 18: 17}


def load_feature_matrix(dataset_path: str, backend: str = "auto") -> np.ndarray:
    """Feature matrix of a cohort ``.mat`` for label-free bulk scoring
    (``score/``): accepts both bare feature matrices and the reference
    training layout, stripping a trailing outcome column when one is
    present. Width is the route signal downstream — 64 raw schema columns
    run the full pipeline (impute → select → ensemble), 17 contract
    columns the contract route."""
    data, _ = _read_mat(dataset_path, backend)
    width = data.shape[1]
    feat = _SCORE_WIDTHS.get(width)
    if feat is None:
        raise ValueError(
            f"{dataset_path!r}: data_tb is {width} columns wide; expected "
            "64 raw schema features or 17 contract features (with or "
            "without a trailing outcome column)"
        )
    return data[:, :feat].astype(np.float64)


def save_data(
    dataset_path: str, X: np.ndarray, y: np.ndarray, var_names: np.ndarray
) -> None:
    """Write a cohort in the reference's ``.mat`` layout (for round-trips/tests)."""
    import scipy.io as sio

    data_tb = np.concatenate([X, y.reshape(-1, 1)], axis=1)
    sio.savemat(dataset_path, {"data_tb": data_tb, "clin_var_names": var_names})
