"""Multi-chip dry run — the driver's sharding validation, phase by phase.

Builds an n-device ``jax.sharding.Mesh`` with the framework's real axes
(data × model), jits the FULL depth-1 boosting training step over it
(row-sharded histogram psums + feature-sharded split search), runs the
level-wise any-depth trainer, and finishes with a sharded inference + meta
Newton step under ``NamedSharding`` — asserting sharded == single-device
at every stage.

Engineering contract (VERDICT.md round-1 item 2): every phase prints a
timed line *as it completes* (flush=True) so a partial run is diagnosable
from the driver's output tail; a ``faulthandler`` watchdog dumps all-thread
tracebacks if any phase wedges; the total workload is tiny (n=96 rows,
4+3 stages) so a healthy run fits far inside the driver budget.

Runnable standalone: ``python -m machine_learning_replications_tpu.dryrun N``
(used by ``__graft_entry__.dryrun_multichip``, which prefers running this in
a clean subprocess that the flaky TPU-plugin sitecustomize cannot wedge).
"""

from __future__ import annotations

import os
import sys
import time


def _say(msg: str) -> None:
    print(f"[dryrun {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def force_cpu_platform(n_devices: int) -> None:
    """Point jax at N virtual CPU devices, defensively.

    Safe whether or not jax is already imported (backend init is lazy; the
    XLA_FLAGS env var is read at CPU-backend init time). Must run before the
    first ``jax.devices()`` call in the process. Also deregisters the 'axon'
    TPU plugin factory if the ambient sitecustomize installed one — the
    round-1 driver hang was its backend init wedging on the TPU tunnel, and
    a CPU-mesh dry run has no business touching it.
    """
    from machine_learning_replications_tpu.envsafe import force_host_device_flag

    os.environ["XLA_FLAGS"] = force_host_device_flag(
        os.environ.get("XLA_FLAGS", ""), n_devices
    )

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:  # best-effort: drop the plugin registration entirely
        from jax._src import xla_bridge

        if hasattr(xla_bridge.backends, "cache_clear"):
            xla_bridge.backends.cache_clear()
        for name in list(getattr(xla_bridge, "_backend_factories", {})):
            if name not in ("cpu", "interpreter"):
                xla_bridge._backend_factories.pop(name, None)
    except Exception as e:  # pragma: no cover - jax-internal layout drift
        _say(f"plugin deregistration skipped ({type(e).__name__}: {e})")


def run(n_devices: int) -> None:
    """The dry run proper. Assumes the backend is already pointed at ≥
    ``n_devices`` devices (see ``force_cpu_platform`` / the driver env)."""
    t_all = time.perf_counter()
    _say(f"phase 0: importing jax (n_devices={n_devices})")
    import jax
    import jax.numpy as jnp
    import numpy as np

    avail = len(jax.devices())
    _say(f"phase 0 done: backend={jax.default_backend()} devices={avail} "
         f"({time.perf_counter() - t_all:.1f}s)")
    if avail < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, backend has {avail}; set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices} "
            "before jax's CPU backend initializes"
        )

    from machine_learning_replications_tpu.config import GBDTConfig
    from machine_learning_replications_tpu.data import make_cohort, shard_rows
    from machine_learning_replications_tpu.data.schema import selected_indices
    from machine_learning_replications_tpu.models import gbdt, solvers, tree
    from machine_learning_replications_tpu.parallel import (
        hist_trainer,
        make_mesh,
        stump_trainer,
    )

    t = time.perf_counter()
    model = 2 if n_devices % 2 == 0 and n_devices >= 2 else 1
    mesh = make_mesh(data=n_devices // model, model=model)
    X, y, _ = make_cohort(n=96, seed=3)
    Xs = X[:, selected_indices()]
    _say(f"phase 1 done: mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}, "
         f"cohort 96x17 ({time.perf_counter() - t:.1f}s)")

    # Phase 2 — full sharded depth-1 training step (all boosting stages):
    # rows over 'data' (histogram partials psum over ICI), feature tiles
    # over 'model' (split search all_gather); parity vs single-device.
    t = time.perf_counter()
    cfg = GBDTConfig(n_estimators=4, max_depth=1)
    sharded, _ = stump_trainer.fit(mesh, Xs, y, cfg)
    single, _ = gbdt.fit(Xs, y, cfg)
    np.testing.assert_array_equal(
        np.asarray(sharded.feature), np.asarray(single.feature)
    )
    np.testing.assert_allclose(
        np.asarray(sharded.value), np.asarray(single.value), rtol=1e-5, atol=1e-6
    )
    _say(f"phase 2 done: 4 sharded stump stages == single-device "
         f"({time.perf_counter() - t:.1f}s)")

    # Phase 3 — level-wise trainer, depth 2: per-level histogram psums,
    # replicated split selection. Parity at the model level (deviance +
    # predictions) — psum reduction order may flip near-tied split argmaxes
    # between equivalent trees (cf. tests/test_hist_trainer.py).
    t = time.perf_counter()
    cfg2 = GBDTConfig(n_estimators=3, max_depth=2, splitter="hist", n_bins=16)
    sh2, aux_sh2 = hist_trainer.fit(mesh, Xs, y, cfg2)
    sd2, aux_sd2 = gbdt.fit(Xs, y, cfg2)
    np.testing.assert_allclose(
        aux_sh2["train_deviance"], aux_sd2["train_deviance"], rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(tree.predict_proba1(sh2, Xs)),
        np.asarray(tree.predict_proba1(sd2, Xs)),
        rtol=1e-5, atol=1e-6,
    )
    _say(f"phase 3 done: 3 depth-2 level-wise stages parity-checked "
         f"({time.perf_counter() - t:.1f}s)")

    # Phase 4 — sharded inference + data-parallel meta Newton step under jit
    # with NamedSharding-constrained inputs (GSPMD inserts the collectives).
    # Padding rows fabricated by shard_rows are masked per its contract.
    t = time.perf_counter()
    (Xd, yd), n_rows = shard_rows(mesh, Xs.astype(np.float32), y.astype(np.float32))
    row_mask = (np.arange(Xd.shape[0]) < n_rows).astype(np.float32)

    @jax.jit
    def eval_step(params, Xb, yb, mask):
        p1 = tree.predict_proba1(params, Xb)
        meta = jnp.stack([p1, p1 * 0.5, p1 * p1], axis=-1)
        lp = solvers.logreg_l2_fit(meta, yb, sample_mask=mask, max_iter=3)
        return jnp.sum(p1 * mask) / jnp.sum(mask), lp.coef

    m, coef = eval_step(sharded, Xd, yd, row_mask)
    assert np.isfinite(float(m)) and np.isfinite(np.asarray(coef)).all()
    _say(f"phase 4 done: sharded eval + meta Newton step, mean p1 = "
         f"{float(m):.4f} ({time.perf_counter() - t:.1f}s)")

    # Phase 5 — sharded stacking members (VERDICT r2 item 8): a masked SVC
    # fold fit and the L1-LR FISTA fit under jit with row-sharded inputs
    # (GSPMD inserts the collectives for the kernel matrix and the matvecs);
    # parity vs the same fits on unsharded arrays.
    t = time.perf_counter()
    from jax.sharding import NamedSharding, PartitionSpec as P
    from machine_learning_replications_tpu.models import scaler, svm
    from machine_learning_replications_tpu.parallel.mesh import DATA_AXIS

    n96 = Xs.shape[0]
    fold = (np.arange(n96) % 4 != 0).astype(np.float64)  # one CV train mask
    platt = np.stack([
        (np.arange(n96) % 2 == 0) * fold, (np.arange(n96) % 2 == 1) * fold,
    ]).astype(np.float64)
    Xj = jnp.asarray(Xs)
    sp = scaler.fit(Xj, sample_weight=jnp.asarray(fold))
    Xt = scaler.transform(sp, Xj)

    def member_fits(Xb, yb, fm, pm):
        vp = svm.svc_fit_masked(Xb, yb, fm, pm, C=1.0, gamma=None,
                                balanced=True, tol=1e-6, max_iter=2000)
        lp = solvers.logreg_l1_fit(Xb, yb, C=1.0, sample_mask=fm,
                                   balanced=True, tol=1e-8, max_iter=2000)
        return svm.predict_proba1(vp, Xb), lp.coef, lp.intercept

    shard = lambda a, spec: jax.device_put(np.asarray(a), NamedSharding(mesh, spec))
    args_sh = (shard(Xt, P(DATA_AXIS, None)), shard(y, P(DATA_AXIS)),
               shard(fold, P(DATA_AXIS)), shard(platt, P(None, DATA_AXIS)))
    p_sh, c_sh, b_sh = jax.jit(member_fits)(*args_sh)
    p_sd, c_sd, b_sd = jax.jit(member_fits)(
        Xt, jnp.asarray(y), jnp.asarray(fold), jnp.asarray(platt)
    )
    # f32 tolerances: GSPMD's sharded matvecs reduce in a different order
    # than the single-device dots, so FISTA/PGD iterates drift at the last
    # few ulps over hundreds of iterations (observed ≤4e-7 absolute).
    np.testing.assert_allclose(np.asarray(p_sh), np.asarray(p_sd),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(c_sh), np.asarray(c_sd),
                               rtol=1e-3, atol=1e-5)
    _say(f"phase 5 done: sharded masked SVC + L1-LR fits == single-device "
         f"({time.perf_counter() - t:.1f}s)")

    # Phase 6 — the mesh-routed pipeline stages: row-sharded imputer
    # transform and the stacking CV's GBDT fold fits through the sharded
    # trainer, each against its single-device counterpart.
    t = time.perf_counter()
    from machine_learning_replications_tpu.config import ExperimentConfig, SVCConfig
    from machine_learning_replications_tpu.models import knn_impute, pipeline

    Xm, ym, _ = make_cohort(n=96, seed=5, missing_rate=0.08)
    ip = knn_impute.fit(jnp.asarray(Xm))
    imp_sh = np.asarray(knn_impute.transform(ip, jnp.asarray(Xm), mesh=mesh))
    imp_sd = np.asarray(knn_impute.transform(ip, jnp.asarray(Xm)))
    np.testing.assert_array_equal(imp_sh, imp_sd)

    ecfg = ExperimentConfig(gbdt=cfg, svc=SVCConfig(platt_cv=2, max_iter=500))
    meta_sh = pipeline.cross_val_member_probas(Xs, y, ecfg, mesh=mesh)
    meta_sd = pipeline.cross_val_member_probas(Xs, y, ecfg)
    np.testing.assert_allclose(meta_sh[:, 1], meta_sd[:, 1], rtol=1e-5, atol=1e-6)
    _say(f"phase 6 done: sharded imputer transform + mesh CV fold fits == "
         f"single-device ({time.perf_counter() - t:.1f}s)")

    # Phase 7 — sharded feature selection: the covariance-form LassoCV's
    # per-fold Gram statistics psum'd over 'data'
    # (parallel.select_trainer), against the static-slice single-device
    # stats; the full selection (top-17 mask) must agree exactly.
    t = time.perf_counter()
    from machine_learning_replications_tpu.config import LassoSelectConfig
    from machine_learning_replications_tpu.models import feature_selection

    sel_cfg = LassoSelectConfig()
    mask_sh, _ = feature_selection.fit_select(imp_sd, ym, sel_cfg, mesh=mesh)
    mask_sd, _ = feature_selection.fit_select(imp_sd, ym, sel_cfg)
    np.testing.assert_array_equal(mask_sh, mask_sd)
    assert int(mask_sh.sum()) == sel_cfg.max_features
    _say(f"phase 7 done: sharded lasso fold-Gram selection == single-device "
         f"({time.perf_counter() - t:.1f}s)")

    # Phase 8 — the CV grid sweep (BASELINE config 4) row-sharded: each
    # (depth, fold) fit through fit_gbdt_sharded with the fold mask on the
    # trainers' weight path; the AUC surface must match the single-device
    # vmapped sweep. Continuous features on purpose: the sharded (sorted
    # stump / hist) and vmapped (level-wise) trainers may break EQUAL-GAIN
    # split ties differently — both sklearn-legal — and the tiny
    # mostly-binary cohort above is tie-dense.
    t = time.perf_counter()
    from machine_learning_replications_tpu.config import SweepConfig
    from machine_learning_replications_tpu.models import sweep as sweep_mod

    rng = np.random.default_rng(11)
    Xc = rng.normal(size=(128, 6))
    yc = (Xc @ rng.normal(size=6) + 0.5 * rng.normal(size=128) > 0).astype(float)
    scfg = SweepConfig(
        n_estimators_grid=(2, 4), max_depth_grid=(1, 2), cv_folds=2
    )
    sw_sh = sweep_mod.cv_sweep(Xc, yc, scfg, mesh=mesh)
    sw_sd = sweep_mod.cv_sweep(Xc, yc, scfg)
    np.testing.assert_allclose(
        sw_sh.fold_auc, sw_sd.fold_auc, rtol=0, atol=1e-9
    )
    _say(f"phase 8 done: mesh grid sweep AUC surface == single-device "
         f"({time.perf_counter() - t:.1f}s)")

    # Phase 9 — the COMPOSED program (VERDICT r4 weak #6): fit_pipeline
    # end-to-end on the mesh — impute → select → stack — then a sharded
    # batch predict, against the identical fit/predict single-device.
    # Phases 2-8 validate each stage's sharding in isolation; only a
    # composed run can catch stage-BOUNDARY mismatches (e.g. the selected-
    # column subset of a row-sharded imputed array feeding the stacked fit).
    t = time.perf_counter()
    X9, y9, _ = make_cohort(n=128, seed=7, missing_rate=0.05)
    pp_sh, info_sh = pipeline.fit_pipeline(X9, y9, ecfg, mesh=mesh)
    pp_sd, info_sd = pipeline.fit_pipeline(X9, y9, ecfg)
    assert info_sh["n_selected"] == info_sd["n_selected"]
    np.testing.assert_array_equal(
        np.asarray(pp_sh.support_mask), np.asarray(pp_sd.support_mask)
    )
    Xq, _, _ = make_cohort(n=64, seed=8, missing_rate=0.05)
    pq_sh = np.asarray(pipeline.pipeline_predict_proba1(pp_sh, Xq, mesh=mesh))
    pq_sd = np.asarray(pipeline.pipeline_predict_proba1(pp_sd, Xq))
    # f32 stacking members under different GSPMD reduction orders: same
    # drift envelope as phase 5's member fits.
    np.testing.assert_allclose(pq_sh, pq_sd, rtol=1e-3, atol=1e-5)
    _say(f"phase 9 done: composed fit_pipeline + batch predict on the mesh "
         f"== single-device ({time.perf_counter() - t:.1f}s)")

    _say(f"dryrun_multichip OK in {time.perf_counter() - t_all:.1f}s: mesh "
         f"{dict(zip(mesh.axis_names, mesh.devices.shape))}, all phases "
         "parity-checked")


def main(argv: list[str]) -> int:
    n = int(argv[1]) if len(argv) > 1 else 8
    watchdog_s = int(os.environ.get("DRYRUN_WATCHDOG_S", "300"))
    import faulthandler

    # If anything wedges (the round-1 failure mode), dump every thread's
    # traceback to stderr and exit nonzero — a diagnosable artifact beats a
    # silent rc=124.
    faulthandler.dump_traceback_later(watchdog_s, exit=True)
    _say(f"standalone start (watchdog {watchdog_s}s)")
    force_cpu_platform(n)
    run(n)
    faulthandler.cancel_dump_traceback_later()
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
