"""Shared PEP 562 lazy re-export helper for package ``__init__`` files.

Importing ``a.b.c`` executes ``a/__init__`` and ``a/b/__init__`` first,
so one eager re-export in a package init puts its whole submodule (and
everything that submodule imports — jax, flax, orbax) into the
import-time closure of every consumer of every sibling. The packages on
declared-jax-free import paths (``data``, ``score``, ``persist`` — see
graftcheck rule ``import-purity``, docs/ANALYSIS.md) resolve their
re-exports lazily through this helper instead:

    _EXPORTS = {"make_cohort": "synthetic", "shard_rows": "sharding"}
    __all__ = sorted(_EXPORTS)
    __getattr__, __dir__ = lazy_exports(__name__, _EXPORTS)

This module must stay stdlib-only: it is imported by those same
package inits.
"""

from __future__ import annotations


def lazy_exports(module_name: str, exports: dict):
    """Build a module ``__getattr__``/``__dir__`` pair resolving each
    exported name from its submodule on first access (``exports`` maps
    attribute name -> submodule name). Resolved values are cached into
    the package's namespace, so later accesses skip ``__getattr__``."""

    def __getattr__(name: str):
        submodule = exports.get(name)
        if submodule is None:
            raise AttributeError(
                f"module {module_name!r} has no attribute {name!r}"
            )
        import importlib
        import sys

        mod = importlib.import_module(f"{module_name}.{submodule}")
        value = getattr(mod, name)
        setattr(sys.modules[module_name], name, value)
        return value

    def __dir__():
        import sys

        return sorted(
            set(vars(sys.modules[module_name])) | set(exports)
        )

    return __getattr__, __dir__
