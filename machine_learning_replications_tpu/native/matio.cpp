// Native MAT-v5 reader — the in-repo replacement for the scipy C parser the
// reference's loader leans on (HF/load_data_public.py:5 → scipy.io.loadmat;
// SURVEY.md §2.4 row "scipy.io.loadmat MAT-file reader").
//
// Scope: the Level-5 MAT format as MATLAB and scipy.io.savemat emit it for
// tabular cohorts — numeric matrices of any integer/float storage type
// (promoted to float64), char arrays, cell arrays of char arrays, and
// zlib-compressed elements (MATLAB's default on-disk form). Little-endian
// files only (every platform this framework targets). Column-major payloads
// are surfaced as-is; the Python binding reshapes with order='F'.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>
#include <zlib.h>

namespace {

constexpr uint32_t miINT8 = 1, miUINT8 = 2, miINT16 = 3, miUINT16 = 4,
                   miINT32 = 5, miUINT32 = 6, miSINGLE = 7, miDOUBLE = 9,
                   miINT64 = 12, miUINT64 = 13, miMATRIX = 14,
                   miCOMPRESSED = 15, miUTF8 = 16, miUTF16 = 17;

constexpr uint32_t mxCELL = 1, mxCHAR = 4;
// numeric classes: mxDOUBLE=6 … mxUINT64=15 (contiguous range)

struct Var {
  std::string name;
  int kind = 0;  // 0 numeric, 1 char, 2 cell-of-strings
  std::vector<int64_t> dims;
  std::vector<double> data;          // numeric payload, column-major
  std::vector<std::string> strings;  // char rows / cell entries, column-major
};

struct MatFile {
  std::vector<Var> vars;
};

struct Cursor {
  const uint8_t* p;
  size_t len;
  size_t off = 0;
  bool ok = true;
  std::string err;

  bool need(size_t n) {
    if (off + n > len) {
      ok = false;
      err = "unexpected end of MAT data";
      return false;
    }
    return true;
  }
  uint32_t u32() {
    if (!need(4)) return 0;
    uint32_t v;
    std::memcpy(&v, p + off, 4);
    off += 4;
    return v;
  }
};

struct Element {
  uint32_t type = 0;
  const uint8_t* data = nullptr;
  size_t size = 0;
  bool ok = false;
};

// Read one data element (handles the small-element format); advances cur
// past the element including its 8-byte alignment padding.
Element read_element(Cursor& cur) {
  Element e;
  uint32_t word = cur.u32();
  if (!cur.ok) return e;
  if (word >> 16) {  // small element: size in the upper half-word
    e.type = word & 0xffff;
    e.size = word >> 16;
    if (e.size > 4) {
      cur.ok = false;
      cur.err = "small element larger than 4 bytes";
      return e;
    }
    if (!cur.need(4)) return e;
    e.data = cur.p + cur.off;
    cur.off += 4;
  } else {
    e.type = word;
    uint32_t sz = cur.u32();
    if (!cur.ok) return e;
    e.size = sz;
    if (!cur.need(sz)) return e;
    e.data = cur.p + cur.off;
    cur.off += sz;
    // Elements are 8-byte aligned — except compressed ones, which the spec
    // exempts from padding (back-to-back zlib blocks).
    if (e.type != miCOMPRESSED) cur.off += (8 - cur.off % 8) % 8;
  }
  e.ok = true;
  return e;
}

size_t type_size(uint32_t t) {
  switch (t) {
    case miINT8: case miUINT8: case miUTF8: return 1;
    case miINT16: case miUINT16: case miUTF16: return 2;
    case miINT32: case miUINT32: case miSINGLE: return 4;
    case miDOUBLE: case miINT64: case miUINT64: return 8;
    default: return 0;
  }
}

bool numeric_to_double(const Element& e, std::vector<double>& out,
                       std::string& err) {
  size_t ts = type_size(e.type);
  if (ts == 0) {
    err = "unsupported numeric storage type " + std::to_string(e.type);
    return false;
  }
  size_t n = e.size / ts;
  out.resize(n);
  for (size_t i = 0; i < n; i++) {
    const uint8_t* q = e.data + i * ts;
    switch (e.type) {
      case miINT8:   out[i] = *reinterpret_cast<const int8_t*>(q); break;
      case miUINT8:  out[i] = *q; break;
      case miINT16: { int16_t v; std::memcpy(&v, q, 2); out[i] = v; } break;
      case miUINT16:{ uint16_t v; std::memcpy(&v, q, 2); out[i] = v; } break;
      case miINT32: { int32_t v; std::memcpy(&v, q, 4); out[i] = v; } break;
      case miUINT32:{ uint32_t v; std::memcpy(&v, q, 4); out[i] = v; } break;
      case miSINGLE:{ float v; std::memcpy(&v, q, 4); out[i] = v; } break;
      case miDOUBLE:{ double v; std::memcpy(&v, q, 8); out[i] = v; } break;
      case miINT64: { int64_t v; std::memcpy(&v, q, 8); out[i] = (double)v; } break;
      case miUINT64:{ uint64_t v; std::memcpy(&v, q, 8); out[i] = (double)v; } break;
      default: err = "unreachable storage type"; return false;
    }
  }
  return true;
}

// Decode a char payload into per-codepoint values (column-major order kept).
bool chars_to_codes(const Element& e, std::vector<uint32_t>& codes,
                    std::string& err) {
  codes.clear();
  if (e.type == miUINT16 || e.type == miUTF16) {
    size_t n = e.size / 2;
    codes.reserve(n);
    for (size_t i = 0; i < n; i++) {
      uint16_t v;
      std::memcpy(&v, e.data + 2 * i, 2);
      codes.push_back(v);  // BMP only; surrogate pairs unsupported (clinical
                           // variable names are ASCII in practice)
    }
    return true;
  }
  if (e.type == miUINT8 || e.type == miINT8 || e.type == miUTF8) {
    codes.assign(e.data, e.data + e.size);  // treat as latin-1/ascii
    return true;
  }
  err = "unsupported char storage type " + std::to_string(e.type);
  return false;
}

void append_utf8(std::string& s, uint32_t c) {
  if (c < 0x80) {
    s.push_back((char)c);
  } else if (c < 0x800) {
    s.push_back((char)(0xC0 | (c >> 6)));
    s.push_back((char)(0x80 | (c & 0x3F)));
  } else {
    s.push_back((char)(0xE0 | (c >> 12)));
    s.push_back((char)(0x80 | ((c >> 6) & 0x3F)));
    s.push_back((char)(0x80 | (c & 0x3F)));
  }
}

bool parse_matrix(Cursor cur, Var& var, std::string& err);

bool parse_matrix_element(const Element& e, Var& var, std::string& err) {
  Cursor sub{e.data, e.size};
  return parse_matrix(sub, var, err);
}

bool parse_matrix(Cursor cur, Var& var, std::string& err) {
  Element flags = read_element(cur);
  if (!flags.ok || flags.type != miUINT32 || flags.size < 8) {
    err = cur.err.empty() ? "bad array-flags subelement" : cur.err;
    return false;
  }
  uint32_t flagword;
  std::memcpy(&flagword, flags.data, 4);
  uint32_t klass = flagword & 0xff;

  Element dims_e = read_element(cur);
  if (!dims_e.ok || dims_e.type != miINT32) {
    err = "bad dimensions subelement";
    return false;
  }
  size_t ndim = dims_e.size / 4;
  var.dims.resize(ndim);
  size_t total = 1;
  for (size_t i = 0; i < ndim; i++) {
    int32_t d;
    std::memcpy(&d, dims_e.data + 4 * i, 4);
    var.dims[i] = d;
    total *= (size_t)d;
  }

  Element name_e = read_element(cur);
  if (!name_e.ok) {
    err = "bad name subelement";
    return false;
  }
  var.name.assign(reinterpret_cast<const char*>(name_e.data), name_e.size);
  // names are NUL-padded in the small-element form
  var.name.erase(var.name.find_last_not_of('\0') + 1);

  if (klass >= 6 && klass <= 15) {  // numeric classes
    Element real = read_element(cur);
    if (!real.ok) {
      err = "bad numeric data subelement";
      return false;
    }
    var.kind = 0;
    if (!numeric_to_double(real, var.data, err)) return false;
    if (var.data.size() != total) {
      err = "numeric payload size does not match dims";
      return false;
    }
    return true;
  }
  if (klass == mxCHAR) {
    Element ch = read_element(cur);
    if (!ch.ok) {
      err = "bad char data subelement";
      return false;
    }
    std::vector<uint32_t> codes;
    if (!chars_to_codes(ch, codes, err)) return false;
    // dims = [rows, cols] column-major: row r's string is codes[r + c*rows]
    int64_t rows = ndim > 0 ? var.dims[0] : 0;
    int64_t cols = ndim > 1 ? var.dims[1] : 1;
    var.kind = 1;
    for (int64_t r = 0; r < rows; r++) {
      std::string s;
      for (int64_t c = 0; c < cols; c++) {
        size_t idx = (size_t)(r + c * rows);
        if (idx < codes.size() && codes[idx] != 0) append_utf8(s, codes[idx]);
      }
      s.erase(s.find_last_not_of(' ') + 1);  // MATLAB space-pads char rows
      var.strings.push_back(s);
    }
    return true;
  }
  if (klass == mxCELL) {
    var.kind = 2;
    for (size_t i = 0; i < total; i++) {
      Element cell = read_element(cur);
      if (!cell.ok || cell.type != miMATRIX) {
        err = "bad cell subelement";
        return false;
      }
      Var inner;
      if (!parse_matrix_element(cell, inner, err)) return false;
      if (inner.kind != 1) {
        err = "only cell arrays of char are supported";
        return false;
      }
      var.strings.push_back(inner.strings.empty() ? "" : inner.strings[0]);
    }
    return true;
  }
  err = "unsupported array class " + std::to_string(klass);
  return false;
}

bool inflate_buf(const uint8_t* src, size_t n, std::vector<uint8_t>& out,
                 std::string& err) {
  z_stream zs{};
  if (inflateInit(&zs) != Z_OK) {
    err = "zlib init failed";
    return false;
  }
  out.resize(n * 4 + 1024);
  zs.next_in = const_cast<uint8_t*>(src);
  zs.avail_in = (uInt)n;
  int ret;
  size_t written = 0;
  do {
    if (written == out.size()) out.resize(out.size() * 2);
    zs.next_out = out.data() + written;
    zs.avail_out = (uInt)(out.size() - written);
    ret = inflate(&zs, Z_NO_FLUSH);
    written = out.size() - zs.avail_out;
    if (ret != Z_OK && ret != Z_STREAM_END) {
      inflateEnd(&zs);
      err = "zlib inflate error " + std::to_string(ret);
      return false;
    }
  } while (ret != Z_STREAM_END && zs.avail_in > 0);
  inflateEnd(&zs);
  out.resize(written);
  return true;
}

}  // namespace

extern "C" {

void* matio_open(const char* path, char* errbuf, int errlen) {
  auto fail = [&](const std::string& msg) -> void* {
    if (errbuf && errlen > 0) {
      std::snprintf(errbuf, errlen, "%s", msg.c_str());
    }
    return nullptr;
  };
  FILE* f = std::fopen(path, "rb");
  if (!f) return fail(std::string("cannot open ") + path);
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf((size_t)sz);
  if (std::fread(buf.data(), 1, buf.size(), f) != buf.size()) {
    std::fclose(f);
    return fail("short read");
  }
  std::fclose(f);

  if (buf.size() < 128) return fail("not a MAT-5 file (too short)");
  uint16_t version, endian;
  std::memcpy(&version, buf.data() + 124, 2);
  std::memcpy(&endian, buf.data() + 126, 2);
  if (endian != 0x4D49)  // 'IM' little-endian marker
    return fail("big-endian or non-MAT-5 file unsupported");
  (void)version;

  auto mf = new MatFile();
  Cursor cur{buf.data(), buf.size(), 128};
  std::string err;
  while (cur.off + 8 <= cur.len) {
    Element e = read_element(cur);
    if (!e.ok) {
      delete mf;
      return fail(cur.err);
    }
    std::vector<uint8_t> inflated;
    Element payload = e;
    if (e.type == miCOMPRESSED) {
      if (!inflate_buf(e.data, e.size, inflated, err)) {
        delete mf;
        return fail(err);
      }
      Cursor icur{inflated.data(), inflated.size()};
      payload = read_element(icur);
      if (!payload.ok) {
        delete mf;
        return fail("bad element inside compressed block");
      }
    }
    if (payload.type != miMATRIX) continue;  // skip non-matrix top levels
    Var v;
    if (!parse_matrix_element(payload, v, err)) {
      delete mf;
      return fail(err);
    }
    mf->vars.push_back(std::move(v));
  }
  return mf;
}

int matio_var_count(void* h) { return (int)((MatFile*)h)->vars.size(); }

const char* matio_var_name(void* h, int i) {
  return ((MatFile*)h)->vars[i].name.c_str();
}

int matio_var_kind(void* h, int i) { return ((MatFile*)h)->vars[i].kind; }

int matio_var_ndim(void* h, int i) {
  return (int)((MatFile*)h)->vars[i].dims.size();
}

void matio_var_dims(void* h, int i, int64_t* out) {
  const auto& d = ((MatFile*)h)->vars[i].dims;
  for (size_t k = 0; k < d.size(); k++) out[k] = d[k];
}

// Column-major doubles; returns element count (call with out=NULL to size).
int64_t matio_var_doubles(void* h, int i, double* out) {
  const auto& v = ((MatFile*)h)->vars[i];
  if (out) std::memcpy(out, v.data.data(), v.data.size() * sizeof(double));
  return (int64_t)v.data.size();
}

int matio_var_string_count(void* h, int i) {
  return (int)((MatFile*)h)->vars[i].strings.size();
}

const char* matio_var_string(void* h, int i, int j) {
  return ((MatFile*)h)->vars[i].strings[j].c_str();
}

void matio_close(void* h) { delete (MatFile*)h; }

}  // extern "C"
