"""ctypes binding + on-demand build of the native MAT-v5 reader.

``read_mat_vars(path, names)`` returns ``{name: ndarray}`` (numeric arrays
float64 in MATLAB's column-major layout reshaped to numpy row-major view;
cell/char variables as object arrays of strings), or ``None`` when the
shared library is unavailable and cannot be built — ``data.matloader``
falls back to scipy in that case.

The library is compiled once per checkout with g++ (``-O2 -fPIC -lz``)
into this package directory; a stale object (older than the source) is
rebuilt. Set ``MLR_TPU_NO_NATIVE=1`` to disable the native path entirely.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "matio.cpp")
_SO = os.path.join(_HERE, "_matio.so")
_lock = threading.Lock()
_lib_cache: list = []  # [lib-or-None] once resolved


def _build() -> bool:
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", _SRC, "-o", _SO, "-lz"]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=240
        )
        return True
    except (subprocess.SubprocessError, OSError):
        return False


def _load() -> ctypes.CDLL | None:
    if os.environ.get("MLR_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _lib_cache:
            return _lib_cache[0]
        # A prebuilt .so without the source beside it counts as fresh.
        fresh = os.path.exists(_SO) and (
            not os.path.exists(_SRC)
            or os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        )
        if not fresh and not (os.path.exists(_SRC) and _build()):
            _lib_cache.append(None)
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _lib_cache.append(None)
            return None
        lib.matio_open.restype = ctypes.c_void_p
        lib.matio_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.matio_var_count.argtypes = [ctypes.c_void_p]
        lib.matio_var_name.restype = ctypes.c_char_p
        lib.matio_var_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.matio_var_kind.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.matio_var_ndim.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.matio_var_dims.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64)
        ]
        lib.matio_var_doubles.restype = ctypes.c_int64
        lib.matio_var_doubles.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_double)
        ]
        lib.matio_var_string_count.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.matio_var_string.restype = ctypes.c_char_p
        lib.matio_var_string.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.matio_close.argtypes = [ctypes.c_void_p]
        _lib_cache.append(lib)
        return lib


def read_mat_vars(path: str, names: list[str]) -> dict[str, np.ndarray] | None:
    """Read the named variables; raises KeyError if one is missing, returns
    None if the native backend is unavailable."""
    lib = _load()
    if lib is None:
        return None
    err = ctypes.create_string_buffer(512)
    h = lib.matio_open(os.fspath(path).encode(), err, len(err))
    if not h:
        raise OSError(err.value.decode() or f"matio: cannot parse {path}")
    try:
        found: dict[str, np.ndarray] = {}
        n = lib.matio_var_count(h)
        for i in range(n):
            name = lib.matio_var_name(h, i).decode()
            if name not in names:
                continue
            ndim = lib.matio_var_ndim(h, i)
            dims = (ctypes.c_int64 * ndim)()
            lib.matio_var_dims(h, i, dims)
            shape = tuple(int(d) for d in dims)
            kind = lib.matio_var_kind(h, i)
            if kind == 0:  # numeric, column-major payload
                count = lib.matio_var_doubles(h, i, None)
                buf = np.empty(int(count), dtype=np.float64)
                lib.matio_var_doubles(
                    h, i, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
                )
                found[name] = buf.reshape(shape, order="F")
            else:  # char rows or cell-of-strings (column-major cell order)
                cnt = lib.matio_var_string_count(h, i)
                vals = [lib.matio_var_string(h, i, j).decode() for j in range(cnt)]
                arr = np.array(vals, dtype=object)
                if kind == 2 and arr.size == int(np.prod(shape)):
                    arr = arr.reshape(shape, order="F")
                found[name] = arr
        missing = [nm for nm in names if nm not in found]
        if missing:
            raise KeyError(missing[0])
        return found
    finally:
        lib.matio_close(h)
