"""ctypes binding + on-demand build of the native MAT-v5 reader.

``read_mat_vars(path, names)`` returns ``{name: ndarray}`` (numeric arrays
float64 in MATLAB's column-major layout reshaped to numpy row-major view;
cell/char variables as object arrays of strings), or ``None`` when the
shared library is unavailable and cannot be built — ``data.matloader``
falls back to scipy in that case.

The library is compiled once per checkout with g++ (``-O2 -fPIC -lz``),
preferentially into this package directory; when that is read-only (e.g. a
system-site ``pip install``), into a per-user cache dir keyed by the source
mtime instead, so packaged installs keep the native path. A stale object
(older than the source) is rebuilt. Set ``MLR_TPU_NO_NATIVE=1`` to disable
the native path entirely.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "matio.cpp")
_SO = os.path.join(_HERE, "_matio.so")
_lock = threading.Lock()
_lib_cache: list = []  # [lib-or-None] once resolved


def _cache_so() -> str | None:
    """Fallback build target when the package dir is not writable: a
    per-user 0700 cache dir keyed by the source mtime (a source update gets
    a fresh name, so staleness never needs an unlink of a mapped .so).

    Loading a .so executes it, so the dir must belong to this user and be
    private: it is created 0700, and an existing dir with the wrong owner
    or group/other permissions is refused (predictable /tmp names are
    otherwise plantable by other local users). Returns None when no safe
    dir can be had (the caller then gives up on the native path)."""
    uid = getattr(os, "getuid", lambda: 0)()  # no getuid on Windows
    try:
        tag = int(os.path.getmtime(_SRC))
    except OSError:
        tag = 0
    d = os.path.join(tempfile.gettempdir(), f"mlr_tpu_native_{uid}")
    try:
        os.makedirs(d, mode=0o700, exist_ok=True)
        st = os.lstat(d)
        import stat as stat_mod

        if not stat_mod.S_ISDIR(st.st_mode) or st.st_uid != uid \
                or (st.st_mode & 0o077):
            return None
    except OSError:
        return None
    return os.path.join(d, f"_matio_{tag}.so")


def _build(target: str) -> bool:
    """Compile to a unique temp name, then rename onto ``target``: the
    rename is atomic, so a concurrent process can never dlopen a partially
    written file (the per-process ``_lock`` doesn't cover multi-process)."""
    tmp = f"{target}.build{os.getpid()}"
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", _SRC, "-o",
           tmp, "-lz"]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=240
        )
        os.replace(tmp, target)
        return True
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _gc_stale_cache(keep: str) -> None:
    """Unlink cache-dir siblings with a different source tag — each source
    update otherwise leaks its predecessor's binary forever."""
    d = os.path.dirname(keep)
    try:
        for f in os.listdir(d):
            if f.startswith("_matio_") and f.endswith(".so") \
                    and os.path.join(d, f) != keep:
                try:
                    os.unlink(os.path.join(d, f))
                except OSError:
                    pass
    except OSError:
        pass


def _fresh(so: str) -> bool:
    """A prebuilt .so without the source beside it counts as fresh."""
    return os.path.exists(so) and (
        not os.path.exists(_SRC)
        or os.path.getmtime(so) >= os.path.getmtime(_SRC)
    )


def _resolve_so() -> str | None:
    """Path of a loadable-fresh .so, building if needed; None if neither
    the package dir nor the user cache can produce one."""
    if _fresh(_SO):
        return _SO
    if not os.path.exists(_SRC):
        return None
    if _build(_SO):
        return _SO
    cached = _cache_so()
    if cached is not None and (_fresh(cached) or _build(cached)):
        _gc_stale_cache(cached)
        return cached
    return None


def _load() -> ctypes.CDLL | None:
    if os.environ.get("MLR_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _lib_cache:
            return _lib_cache[0]
        so = _resolve_so()
        if so is None:
            _lib_cache.append(None)
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # e.g. a foreign-platform binary shipped in a wheel: rebuild
            # into the user cache and retry once before giving up.
            rebuilt = _cache_so()
            if rebuilt is None or not (os.path.exists(_SRC)
                                       and _build(rebuilt)):
                _lib_cache.append(None)
                return None
            try:
                lib = ctypes.CDLL(rebuilt)
            except OSError:
                _lib_cache.append(None)
                return None
        lib.matio_open.restype = ctypes.c_void_p
        lib.matio_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.matio_var_count.argtypes = [ctypes.c_void_p]
        lib.matio_var_name.restype = ctypes.c_char_p
        lib.matio_var_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.matio_var_kind.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.matio_var_ndim.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.matio_var_dims.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64)
        ]
        lib.matio_var_doubles.restype = ctypes.c_int64
        lib.matio_var_doubles.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_double)
        ]
        lib.matio_var_string_count.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.matio_var_string.restype = ctypes.c_char_p
        lib.matio_var_string.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.matio_close.argtypes = [ctypes.c_void_p]
        _lib_cache.append(lib)
        return lib


def read_mat_vars(path: str, names: list[str]) -> dict[str, np.ndarray] | None:
    """Read the named variables; raises KeyError if one is missing, returns
    None if the native backend is unavailable."""
    lib = _load()
    if lib is None:
        return None
    err = ctypes.create_string_buffer(512)
    h = lib.matio_open(os.fspath(path).encode(), err, len(err))
    if not h:
        raise OSError(err.value.decode() or f"matio: cannot parse {path}")
    try:
        found: dict[str, np.ndarray] = {}
        n = lib.matio_var_count(h)
        for i in range(n):
            name = lib.matio_var_name(h, i).decode()
            if name not in names:
                continue
            ndim = lib.matio_var_ndim(h, i)
            dims = (ctypes.c_int64 * ndim)()
            lib.matio_var_dims(h, i, dims)
            shape = tuple(int(d) for d in dims)
            kind = lib.matio_var_kind(h, i)
            if kind == 0:  # numeric, column-major payload
                count = lib.matio_var_doubles(h, i, None)
                buf = np.empty(int(count), dtype=np.float64)
                lib.matio_var_doubles(
                    h, i, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
                )
                found[name] = buf.reshape(shape, order="F")
            else:  # char rows or cell-of-strings (column-major cell order)
                cnt = lib.matio_var_string_count(h, i)
                vals = [lib.matio_var_string(h, i, j).decode() for j in range(cnt)]
                arr = np.array(vals, dtype=object)
                if kind == 2 and arr.size == int(np.prod(shape)):
                    arr = arr.reshape(shape, order="F")
                found[name] = arr
        missing = [nm for nm in names if nm not in found]
        if missing:
            raise KeyError(missing[0])
        return found
    finally:
        lib.matio_close(h)
