"""Native (C++) runtime components.

The reference's compute rests on native code inside third-party libraries
(SURVEY.md §2.4); the TPU build keeps its own native layer in-repo:

  * ``matio`` — MAT-v5 reader (``matio.cpp``, C ABI via ctypes), replacing
    scipy's C parser on the ingest path (``HF/load_data_public.py:5``).

Everything degrades gracefully: if the toolchain is absent the Python/scipy
fallbacks take over (``data.matloader``).
"""

from machine_learning_replications_tpu.native import matio

__all__ = ["matio"]
