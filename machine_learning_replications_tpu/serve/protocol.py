"""HTTP/1.1 protocol layer — pure, transport-agnostic parse/respond logic.

Extracted from the original fused ``http.server`` front end so the wire
rules the serving contract depends on are testable as plain functions, with
no sockets anywhere:

  * **Incremental parsing.** ``RequestParser`` is fed raw bytes in whatever
    fragments the transport happens to read — a request split across many
    reads, or several pipelined requests in one TCP segment — and yields
    complete ``HttpRequest`` objects one at a time. Between requests the
    remainder stays buffered, so HTTP/1.1 keep-alive pipelining works by
    construction.
  * **Bounded buffering.** Header bytes are capped (431 past
    ``max_header_bytes``) and bodies are rejected from the
    ``Content-Length`` header alone (413 past ``max_body_bytes``, never
    buffered) — one connection cannot allocate past the caps no matter how
    it drips or floods bytes.
  * **Framing guards.** A body-carrying request with a missing, unparseable,
    or negative ``Content-Length`` is unframeable: the connection cannot be
    resynced (the next request line would be read out of the unconsumed
    body), so the parser raises and the reply must close. These are the
    same desync rules the threaded server enforced, now in one place.
  * **Response building.** ``build_response`` renders a full HTTP/1.1
    response (status line, ``Content-Length`` always, ``Connection: close``
    when the connection will not be reused) as bytes for any transport to
    write.
  * **The outbound leg.** The fleet router speaks HTTP in the other
    direction too: ``build_request`` renders a request for an upstream
    replica, and ``ResponseParser`` incrementally parses the reply the
    same way ``RequestParser`` parses requests — fed raw fragments,
    yielding one complete ``HttpResponse`` at a time, with the identical
    framing discipline (``Content-Length`` required, caps enforced,
    unframeable streams raise and the connection must close). A reused
    upstream connection is only safe while both sides agree on byte
    positions; the parser is where that agreement is checked.

Every parse failure is a ``ProtocolError`` carrying the HTTP status to
reply with and whatever request context (target, headers) was parsed before
the failure, so the application layer can still echo an ``X-Request-Id``
and trace the failure. A ``ProtocolError`` always closes the connection:
by definition the parser no longer knows where the next request starts.
"""

from __future__ import annotations

from urllib.parse import parse_qs, urlparse

#: Default caps — a patient JSON is ~600 bytes; anything near these bounds
#: is not a legitimate request for this API.
MAX_HEADER_BYTES = 16 * 1024
MAX_BODY_BYTES = 64 * 1024

#: Reason phrases for the status codes this server emits.
REASONS = {
    200: "OK",
    400: "Bad Request",
    403: "Forbidden",
    404: "Not Found",
    405: "Method Not Allowed",
    409: "Conflict",
    413: "Payload Too Large",
    431: "Request Header Fields Too Large",
    500: "Internal Server Error",
    501: "Not Implemented",
    502: "Bad Gateway",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}

#: Methods that carry a body and therefore require Content-Length framing.
_BODY_METHODS = frozenset({"POST", "PUT", "PATCH"})


class ProtocolError(Exception):
    """A request that cannot be parsed or framed.

    ``code``/``message`` are the HTTP reply to send; ``target`` and
    ``headers`` are whatever was parsed before the failure (``None`` /
    empty when the failure happened earlier than that), so the reply can
    still echo request identity. The connection must close after the
    reply — an unframeable request means the byte stream position of the
    next request is unknown.
    """

    def __init__(
        self,
        code: int,
        message: str,
        target: str | None = None,
        headers: dict[str, str] | None = None,
    ) -> None:
        super().__init__(message)
        self.code = code
        self.message = message
        self.target = target
        self.headers = headers or {}

    @property
    def path(self) -> str | None:
        return urlparse(self.target).path if self.target else None


class HttpRequest:
    """One complete, framed request: method, target, headers, body.

    ``headers`` keys are lower-cased (HTTP header names are
    case-insensitive); ``path``/``query`` are the parsed target.
    ``keep_alive`` is the connection's post-reply reusability under the
    HTTP/1.1 defaults (1.1: persistent unless ``Connection: close``; 1.0:
    close unless ``Connection: keep-alive``) — the response builder and the
    transport both honor it.
    """

    __slots__ = ("method", "target", "path", "headers", "body",
                 "keep_alive", "_qs", "_query")

    def __init__(
        self,
        method: str,
        target: str,
        headers: dict[str, str],
        body: bytes,
        keep_alive: bool,
    ) -> None:
        self.method = method
        self.target = target
        # Fast split — the hot /predict path has no query string, and a
        # full urlparse per request is measurable on the event loop.
        self.path, _, self._qs = target.partition("?")
        self._query: dict[str, list[str]] | None = None
        self.headers = headers
        self.body = body
        self.keep_alive = keep_alive

    @property
    def query(self) -> dict[str, list[str]]:
        if self._query is None:
            self._query = parse_qs(self._qs)
        return self._query

    def get_header(self, name: str, default: str | None = None) -> str | None:
        return self.headers.get(name.lower(), default)

    def query_param(self, name: str, default: str) -> str:
        return self.query.get(name, [default])[0]


def _parse_head(
    head: bytes,
) -> tuple[str, str, str, dict[str, str]]:
    """Request line + header block → (method, target, version, headers).
    Raises ``ProtocolError`` on a malformed line."""
    lines = head.split(b"\r\n")
    try:
        parts = lines[0].decode("latin-1").split()
    except Exception:
        raise ProtocolError(400, "malformed request line")
    if len(parts) != 3:
        raise ProtocolError(
            400, f"malformed request line: {lines[0][:80].decode('latin-1')!r}"
        )
    method, target, version = parts
    if not version.startswith("HTTP/1."):
        raise ProtocolError(400, f"unsupported protocol version {version}",
                            target=target)
    headers: dict[str, str] = {}
    for raw in lines[1:]:
        if not raw:
            continue
        name, sep, value = raw.partition(b":")
        if not sep:
            raise ProtocolError(
                400, f"malformed header line: {raw[:80].decode('latin-1')!r}",
                target=target, headers=headers,
            )
        headers[name.decode("latin-1").strip().lower()] = \
            value.decode("latin-1").strip()
    return method, target, version, headers


class RequestParser:
    """Incremental HTTP/1.1 request parser over a bounded byte buffer.

    ``feed`` raw bytes as they arrive; ``next_request`` returns one
    complete ``HttpRequest``, ``None`` while more bytes are needed, and
    raises ``ProtocolError`` when the stream is unparseable or exceeds a
    cap. Bytes past a complete request stay buffered for the next call —
    pipelined requests drain one per call, in order.
    """

    def __init__(
        self,
        max_header_bytes: int = MAX_HEADER_BYTES,
        max_body_bytes: int = MAX_BODY_BYTES,
    ) -> None:
        self.max_header_bytes = int(max_header_bytes)
        self.max_body_bytes = int(max_body_bytes)
        self._buf = bytearray()

    def feed(self, data: bytes) -> None:
        self._buf += data

    @property
    def buffered(self) -> int:
        return len(self._buf)

    def has_partial(self) -> bool:
        """Bytes buffered that do not yet form a complete request — the
        state a slow-loris client parks a connection in; the transport's
        idle reaper uses this to bound how long it may persist."""
        return len(self._buf) > 0

    def next_request(self) -> HttpRequest | None:
        buf = self._buf
        if not buf:
            return None
        end = buf.find(b"\r\n\r\n")
        if end < 0:
            if len(buf) > self.max_header_bytes:
                # The header block never terminated within the cap: an
                # attacker (or a broken client) streaming unbounded header
                # bytes. 431 is the specific status for it.
                raise ProtocolError(
                    431, f"headers exceed {self.max_header_bytes} bytes"
                )
            return None
        if end > self.max_header_bytes:
            raise ProtocolError(
                431, f"headers exceed {self.max_header_bytes} bytes"
            )
        method, target, version, headers = _parse_head(bytes(buf[:end]))
        if "transfer-encoding" in headers:
            # Chunked framing is not part of this API's contract; accepting
            # the header while ignoring it would desync the connection.
            raise ProtocolError(
                400, "Transfer-Encoding is not supported",
                target=target, headers=headers,
            )
        length = 0
        raw_length = headers.get("content-length")
        if method in _BODY_METHODS:
            try:
                length = int(raw_length)  # type: ignore[arg-type]
            except (TypeError, ValueError):
                length = -1
            if length < 0:
                # Missing, unparseable, or negative Content-Length: the
                # body length is unknowable, so the connection cannot be
                # resynced either — the reply must close it.
                raise ProtocolError(
                    400, "missing or invalid Content-Length",
                    target=target, headers=headers,
                )
        elif raw_length is not None:
            # A GET/HEAD with a declared body: frame (and deliver) it so
            # the connection stays in sync instead of parsing the stale
            # body bytes as the next request line.
            try:
                length = max(0, int(raw_length))
            except ValueError:
                raise ProtocolError(
                    400, "missing or invalid Content-Length",
                    target=target, headers=headers,
                )
        if length > self.max_body_bytes:
            # Reject from the header alone — the body is never buffered.
            raise ProtocolError(
                413, f"body exceeds {self.max_body_bytes} bytes",
                target=target, headers=headers,
            )
        body_start = end + 4
        if len(buf) - body_start < length:
            return None  # body still in flight
        body = bytes(buf[body_start:body_start + length])
        del buf[:body_start + length]
        keep_alive = _keep_alive(version, headers)
        return HttpRequest(method, target, headers, body, keep_alive)


def _keep_alive(version: str, headers: dict[str, str]) -> bool:
    conn = headers.get("connection", "").lower()
    if version == "HTTP/1.0":
        return conn == "keep-alive"
    return conn != "close"


class HttpResponse:
    """One complete, framed upstream response: status code, headers,
    body. ``headers`` keys are lower-cased; ``keep_alive`` is whether the
    CONNECTION may carry another request after this reply (HTTP/1.1
    defaults — the pooling decision also requires the parser to be empty,
    which the transport checks)."""

    __slots__ = ("code", "reason", "headers", "body", "keep_alive")

    def __init__(
        self,
        code: int,
        reason: str,
        headers: dict[str, str],
        body: bytes,
        keep_alive: bool,
    ) -> None:
        self.code = code
        self.reason = reason
        self.headers = headers
        self.body = body
        self.keep_alive = keep_alive

    def get_header(self, name: str, default: str | None = None) -> str | None:
        return self.headers.get(name.lower(), default)


class ResponseParser:
    """Incremental HTTP/1.1 *response* parser — the outbound mirror of
    ``RequestParser``, for the transport's upstream leg.

    ``feed`` raw bytes as they arrive; ``next_response`` returns one
    complete ``HttpResponse``, ``None`` while more bytes are needed, and
    raises ``ProtocolError`` when the stream is garbled or exceeds a cap.
    The framing rules are deliberately strict: every response this stack
    emits carries a ``Content-Length`` (``build_response`` guarantees it),
    so a missing/invalid one on the upstream leg means the peer is not one
    of ours or the stream is desynced — unframeable either way, and the
    connection must close. ``Transfer-Encoding`` is rejected for the same
    reason as inbound. A ``ProtocolError`` here never reaches a client
    as-is; the router classifies it as an upstream failure (retryable).

    ``at_start`` distinguishes a clean EOF between responses (an idle
    keep-alive connection the peer reaped — retryable on a fresh socket)
    from an EOF mid-response (a truncated reply — the bytes received so
    far are unusable and must never be taken for a complete answer).
    """

    def __init__(
        self,
        max_header_bytes: int = MAX_HEADER_BYTES,
        max_body_bytes: int = MAX_BODY_BYTES,
    ) -> None:
        self.max_header_bytes = int(max_header_bytes)
        self.max_body_bytes = int(max_body_bytes)
        self._buf = bytearray()

    def feed(self, data: bytes) -> None:
        self._buf += data

    @property
    def buffered(self) -> int:
        return len(self._buf)

    def at_start(self) -> bool:
        """True when no response bytes are pending — the only state in
        which a connection EOF is a clean close rather than truncation."""
        return not self._buf

    def next_response(self) -> HttpResponse | None:
        buf = self._buf
        if not buf:
            return None
        end = buf.find(b"\r\n\r\n")
        if end < 0:
            if len(buf) > self.max_header_bytes:
                raise ProtocolError(
                    502, f"upstream headers exceed {self.max_header_bytes} "
                    "bytes"
                )
            return None
        if end > self.max_header_bytes:
            raise ProtocolError(
                502, f"upstream headers exceed {self.max_header_bytes} bytes"
            )
        lines = bytes(buf[:end]).split(b"\r\n")
        try:
            parts = lines[0].decode("latin-1").split(None, 2)
            version, code = parts[0], int(parts[1])
            reason = parts[2] if len(parts) > 2 else ""
        except (ValueError, IndexError):
            raise ProtocolError(
                502, "malformed upstream status line: "
                f"{lines[0][:80].decode('latin-1')!r}"
            )
        if not version.startswith("HTTP/1."):
            raise ProtocolError(
                502, f"unsupported upstream protocol version {version}"
            )
        headers: dict[str, str] = {}
        for raw in lines[1:]:
            if not raw:
                continue
            name, sep, value = raw.partition(b":")
            if not sep:
                raise ProtocolError(
                    502, "malformed upstream header line: "
                    f"{raw[:80].decode('latin-1')!r}"
                )
            headers[name.decode("latin-1").strip().lower()] = \
                value.decode("latin-1").strip()
        if "transfer-encoding" in headers:
            raise ProtocolError(
                502, "upstream Transfer-Encoding is not supported"
            )
        try:
            length = int(headers.get("content-length"))  # type: ignore[arg-type]
        except (TypeError, ValueError):
            length = -1
        if length < 0:
            # Every server in this stack stamps Content-Length on every
            # reply; its absence means close-delimited framing (not part
            # of this contract) or a desynced stream.
            raise ProtocolError(
                502, "upstream response missing or invalid Content-Length"
            )
        if length > self.max_body_bytes:
            raise ProtocolError(
                502, f"upstream body exceeds {self.max_body_bytes} bytes"
            )
        body_start = end + 4
        if len(buf) - body_start < length:
            return None  # body still in flight
        body = bytes(buf[body_start:body_start + length])
        del buf[:body_start + length]
        keep_alive = _keep_alive(version, headers)
        return HttpResponse(code, reason, headers, body, keep_alive)


def build_request(
    method: str,
    target: str,
    headers: dict[str, str] | None = None,
    body: bytes = b"",
    host: str = "",
) -> bytes:
    """Render a complete HTTP/1.1 request as bytes — the outbound leg's
    counterpart of ``build_response``. ``Content-Length`` is always
    present on body-carrying methods (the framing contract both parsers
    enforce); connections default to keep-alive."""
    lines = [f"{method} {target} HTTP/1.1", f"Host: {host or 'localhost'}"]
    if method in _BODY_METHODS or body:
        lines.append(f"Content-Length: {len(body)}")
    if headers:
        lines.extend(f"{k}: {v}" for k, v in headers.items())
    head = "\r\n".join(lines).encode("latin-1") + b"\r\n\r\n"
    return head + body


def build_response(
    code: int,
    body: bytes,
    content_type: str,
    headers: dict[str, str] | None = None,
    request_id: str | None = None,
    keep_alive: bool = True,
) -> bytes:
    """Render a complete HTTP/1.1 response as bytes.

    ``Content-Length`` is always present (the keep-alive framing
    contract); ``Connection: close`` is added when the connection will not
    be reused, so clients stop waiting for a next response the moment the
    socket closes.
    """
    reason = REASONS.get(code, "Unknown")
    lines = [
        f"HTTP/1.1 {code} {reason}",
        f"Content-Type: {content_type}",
        f"Content-Length: {len(body)}",
    ]
    if request_id is not None:
        # Echoed (or assigned) correlation id: the client can join its own
        # latency record against /debug/requests samples.
        lines.append(f"X-Request-Id: {request_id}")
    if headers:
        lines.extend(f"{k}: {v}" for k, v in headers.items())
    if not keep_alive:
        lines.append("Connection: close")
    head = "\r\n".join(lines).encode("latin-1") + b"\r\n\r\n"
    return head + body
