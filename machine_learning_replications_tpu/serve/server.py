"""HTTP front end for the serving layer — application logic over the
event-loop transport.

The stack is three layers since the transport refactor (docs/SERVING.md
"Transport architecture"):

  ``serve.protocol``   pure HTTP parse/respond rules (Content-Length
                       framing guards, keep-alive/pipelining, desync
                       closes) — no sockets, unit-testable.
  ``serve.transport``  the non-blocking ``selectors`` event loop: one
                       thread owns every socket, keep-alive pipelining,
                       bounded read buffers, idle/slow-loris reaping,
                       explicit backpressure (a socket with a request in
                       flight is not read), ``SO_REUSEPORT`` pre-fork
                       sharding for ``cli serve --workers N``.
  this module          the endpoints below, plus request tracing, SLO
                       accounting, quality monitoring, and degraded-mode
                       shedding — unchanged semantics behind the new
                       transport; the batcher/engine/supervisor stack
                       behind it is untouched.

Endpoints:

  POST /predict   body = the 17-variable patient JSON (``predict_hf.py:5-27``,
                  same validation as ``cli.py predict --patient``) → 200
                  ``{"probability": p, "text": "Probability of progressive
                  HF is: XX.XX %"}``. 400 on contract violations, 413 on
                  oversized bodies (never read into memory), 431 on
                  oversized headers, 503
                  ``{"error": "overloaded"}`` when admission control sheds,
                  504 when an admitted request misses the request deadline
                  (it is cancelled, so the engine never computes it).
                  Every reply carries an ``X-Request-Id`` header — the
                  inbound header's value when the client sent one (so
                  upstream trace ids propagate, Dapper-style), a fresh id
                  otherwise — and the whole request records a per-phase
                  trace (``obs.reqtrace``): parse → queue wait → batch
                  assembly → device compute (cold-compile flagged) →
                  respond (host-path requests: parse → queue wait → host
                  compute → respond). With dual-path scoring enabled the
                  request is routed (``PathRouter``): host fast path for
                  singles on an idle server, device micro-batches for
                  bursts; the taken path is echoed as ``X-Serve-Path``
                  (an inbound ``X-Serve-Path: host|device`` header pins
                  it), counted in ``serve_path_total``, and a client
                  ``X-Request-Deadline-Ms`` header tightens the reply
                  deadline and biases routing toward the host path.
  GET  /healthz   LIVENESS (always 200 while the process can answer) plus
                  the load signal an external prober wants: params family,
                  bucket ladder, warm flag, queue depth, uptime, the run
                  id from the journal manifest when one is active, the
                  worker id in multi-worker mode, a compact model-quality
                  block (``{"status": ok|warn|alert|disabled,
                  "worst_feature", "worst_psi"}``), and — when the engine
                  is supervised — the circuit breaker's state (``status``
                  reads ``degraded`` while the breaker is open). Liveness
                  and readiness are split deliberately: a recovering
                  replica must be rotated OUT (readiness false) without
                  being KILLED (liveness true).
  GET  /readyz    READINESS: 200 only when the engine is warm, the server
                  is not draining, and the breaker is closed; 503 with the
                  blocking reasons otherwise — the signal a load balancer
                  acts on.
  GET  /metrics   Prometheus text exposition (``?format=json`` for the
                  same data as JSON) — ``serve.metrics``, with the
                  process-global ``obs`` registry's exposition appended
                  (jax compile counts/seconds and transfer bytes from
                  ``obs.jaxmon``, installed at ``make_server``; SLO burn
                  gauges from ``obs.slo``; flight-recorder sampling
                  counters; ``serve_worker_info{worker=…}`` in
                  multi-worker mode so scrapes through the shared
                  ``SO_REUSEPORT`` port stay attributable).
  GET  /debug/requests
                  the flight recorder's tail-sampled request traces
                  (every failure + the p99-slowest completions), newest
                  first, with recorder stats and per-SLO state. ``?n=K``
                  caps the trace count (default 64). ``?id=<request-id>``
                  is an exact lookup over the recorder's all-completions
                  index (JSON 404 when the id aged out) — the fetch
                  primitive behind the router's fleet trace join.
  GET  /debug/profile?seconds=N
                  on-demand ``jax.profiler`` capture of N wall seconds
                  (default 1) while traffic keeps flowing; replies with
                  the artifact file list. Single-flight: a capture in
                  progress makes concurrent calls fail fast with 409.
                  (Runs on its own short-lived thread — a blocking capture
                  must not stall the event loop.)
  GET  /debug/quality
                  the model-quality monitor's full snapshot
                  (``obs.quality``): drift status vs the training
                  reference profile, per-feature PSI/KS sorted worst
                  first, score-distribution PSI, calibration bins, and
                  windowed ensemble disagreement. ``{"enabled": false}``
                  when the served params carry no reference profile or
                  the server started with ``--no-quality``.
  GET/POST /debug/faults
                  the fault-injection registry (``resilience.faults``):
                  GET snapshots armed sites and their call/fire counts;
                  POST ``{"arm": SPEC}`` / ``{"disarm": SITE}`` /
                  ``{"reset": true}`` drives a chaos run over HTTP. 403
                  unless the process opted in (``cli serve --inject`` /
                  ``--fault-endpoint``) — a production server must not be
                  chaos-drivable by whoever can reach its port.

Degraded mode (``resilience.supervisor``, docs/RESILIENCE.md): while the
supervised engine's circuit breaker is open, ``/predict`` sheds with 503 +
``Retry-After`` instead of queueing into a dead engine, ``/healthz``
reports ``degraded`` (still 200 — the process is alive), and ``/readyz``
goes 503 so load balancers rotate the replica out while the supervisor
rebuilds and re-warms the engine off the request path.

``ServerHandle.shutdown`` is the graceful path: mark draining (readiness
drops), stop accepting, drain the batcher (admitted requests are never
dropped), flush every queued reply, then stop the listener.
"""

from __future__ import annotations

import json
import math
import os
import sys
import tempfile
import threading
import time

from machine_learning_replications_tpu.obs import (
    jaxmon,
    journal,
    profiler,
    reqtrace,
    slo,
    timeseries,
)
from machine_learning_replications_tpu.obs import alerts as alertsmod
from machine_learning_replications_tpu.obs import incident as incidentmod
from machine_learning_replications_tpu.obs import quality as qualitymod
from machine_learning_replications_tpu.obs.registry import REGISTRY
from machine_learning_replications_tpu.resilience import faults
from machine_learning_replications_tpu.resilience.supervisor import (
    DEGRADED_SHEDS,
    BreakerOpen,
    ComputeDeadlineExceeded,
    SupervisedEngine,
)
from machine_learning_replications_tpu.serve.batcher import (
    MicroBatcher,
    Overloaded,
    PathRouter,
)
from machine_learning_replications_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    BucketedPredictEngine,
)
from machine_learning_replications_tpu.serve.hostpath import (
    DEFAULT_HOST_BUCKETS,
    HOST_FALLBACKS,
    PATHS,
    HostBusy,
    HostPath,
    HostScorer,
)
from machine_learning_replications_tpu.serve.metrics import ServingMetrics
from machine_learning_replications_tpu.serve.transport import (
    EventLoopHttpServer,
)

#: On the CPU backend the r11 campaign measured mid-size flushes padding
#: into the big buckets as pure waste; BENCH.md's recommendation — cap
#: flushes at the cheap 64-row executable — is now the default there.
#: Device backends keep the top bucket (big batches are the whole point
#: of an accelerator).
CPU_DEFAULT_MAX_BATCH = 64

# predict_hf.py:38-40 — the single-patient CLI prints exactly this line;
# the HTTP reply carries it verbatim so the serving layer inherits the
# output contract.
OUTPUT_CONTRACT = "Probability of progressive HF is: {:.2f} %"

#: Rolling-deploy accounting (docs/FLEET.md): ok = the target version
#: swapped in; rolled_back = the checkpoint failed to restore and the
#: retained last-known-good was served instead; failed = nothing swapped
#: (load/warmup/parity failure — the previous engine keeps serving).
DEPLOYS = REGISTRY.counter(
    "serve_deploys_total",
    "In-place model deploys (/admin/deploy) by result.",
    labels=("result",),
)
#: The served checkpoint's monotonic version id (0 when unversioned —
#: pickle-imported params or a pre-versioning checkpoint). The loadgen
#: crossover evidence reads the per-reply X-Model-Version header; this
#: gauge is the same fact on the scrape side.
MODEL_VERSION = REGISTRY.gauge(
    "serve_model_version",
    "Monotonic checkpoint version currently served (0 = unversioned).",
)
#: Pre-fork worker attribution through the shared SO_REUSEPORT port:
#: constant 1, the worker label carries the id (registered at import,
#: rule metrics-catalog; a single-worker process never sets a child).
WORKER_INFO = REGISTRY.gauge(
    "serve_worker_info",
    "Serving worker identity (pre-fork multi-worker mode); constant 1, "
    "the worker label carries the id.",
    labels=("worker",),
)


def _retry_after(seconds: float) -> dict[str, str]:
    """``Retry-After`` header for degraded-mode sheds: integer seconds,
    floor 1 (RFC 7231 delta-seconds; a 0 would invite an instant retry
    stampede against a still-restarting engine)."""
    return {"Retry-After": str(max(1, math.ceil(seconds)))}


class ServerHandle:
    """A running serving stack: engine + batcher + metrics + request-trace
    recorder + SLO tracker + event-loop HTTP listener."""

    def __init__(
        self, engine, batcher, metrics, httpd,
        recorder=None, slo_tracker=None, profile_dir: str | None = None,
        quality=None, worker_id: int | None = None,
        host=None, router=None, quality_feed=None,
        model_version: int | None = None, replica_id: str | None = None,
        admin_enabled: bool = False, live=None, say=None,
        use_aot: bool = True,
    ) -> None:
        self.engine = engine
        self.batcher = batcher
        self.metrics = metrics
        self.httpd = httpd  # transport.EventLoopHttpServer
        self.recorder = recorder
        self.slo_tracker = slo_tracker
        self.profile_dir = profile_dir
        self.quality = quality  # obs.quality.QualityMonitor or None
        self.worker_id = worker_id  # pre-fork multi-worker id, or None
        self.host = host            # hostpath.HostPath or None
        self.router = router        # batcher.PathRouter or None
        self.quality_feed = quality_feed  # AsyncQualityFeed or None
        # Fleet identity (docs/FLEET.md): the checkpoint version this
        # replica serves and the id it registered under — echoed on every
        # reply (X-Model-Version / X-Replica) so the rolling-deploy
        # crossover is provable from client artifacts alone.
        self.model_version = model_version
        self.replica_id = replica_id
        self.admin_enabled = admin_enabled  # /admin/deploy opt-in
        # AOT restore policy (docs/AOT.md): when False (cli serve
        # --no-aot) deploys ignore published executable bundles and
        # always trace — the operator escape hatch that guarantees a bad
        # serialized artifact can never brick a fleet.
        self.use_aot = use_aot
        # The live-params holder the supervised-restart factory reads
        # through (make_server) — deploys update it so a post-deploy
        # restart rebuilds the CURRENT model, not the boot-time one.
        self.live = live if live is not None else {"params": None}
        # The alerting plane (obs.timeseries / obs.alerts /
        # obs.incident), wired by make_server; all optional.
        self.history = None
        self.sampler = None
        self.alerts = None
        self.incidents = None
        self._say = say
        self._deploy_lock = threading.Lock()
        self.deploy_status: dict | None = None
        # Graceful-drain marker: set FIRST in shutdown so /readyz drops
        # before admission closes — a load balancer stops routing here
        # while in-flight requests finish.
        self.draining = False
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self.httpd.server_address[:2]

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def start_background(self) -> "ServerHandle":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="serve-http", daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self, drain: bool = True) -> None:
        """Graceful stop: mark draining (readiness goes false), close
        admission (draining by default — every in-flight reply is still
        written through the live event loop), then stop and flush the
        transport. Safe to call more than once."""
        self.draining = True
        if self.sampler is not None:
            self.sampler.close()
        self.batcher.close(drain=drain)
        if self.host is not None:
            # In-flight host-path work finishes (its computes are
            # single-digit ms); anything unclaimed fails fast — same
            # admitted-work contract as the batcher drain.
            self.host.close()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        close_engine = getattr(self.engine, "close", None)
        if close_engine is not None:  # supervised: stop the worker thread
            close_engine()
        if self.quality_feed is not None:
            # Drain-then-stop: rows already handed off still reach the
            # monitor so a post-shutdown snapshot reflects all traffic.
            self.quality_feed.close()
        if self.incidents is not None:
            self.incidents.close()

    # -- fleet identity ------------------------------------------------------

    def identity_headers(self) -> dict[str, str]:
        """Per-reply fleet identity: which replica answered, serving which
        checkpoint version. The front-door router passes these through,
        so a client artifact (loadgen's ``fleet`` block) can prove the
        rolling-deploy crossover without touching a single scrape."""
        h: dict[str, str] = {}
        if self.replica_id is not None:
            h["X-Replica"] = self.replica_id
        if self.model_version is not None:
            h["X-Model-Version"] = str(self.model_version)
        return h

    # -- in-place model deploy ----------------------------------------------

    def deploy_model(self, model_path: str) -> dict:
        """Warm-swap this replica onto the checkpoint at ``model_path``
        (docs/FLEET.md "Deploy lifecycle"). Runs on the caller's thread —
        the /admin/deploy handler spawns one — entirely off the request
        path: the live engine keeps serving while the new version loads,
        builds, warms, and proves parity; only then does the atomic swap
        happen. Single-flight (``RuntimeError`` when one is already in
        progress). Steps:

          1. ``load_model_versioned``: integrity-verified restore with
             the last-known-good rollback net — a corrupt checkpoint
             deploys the PREVIOUS version, loudly (``rolled_back``).
          2. Build + warm a fresh engine (and host scorer, when the fast
             path is on) via the supervisor's rebuild machinery.
          3. Parity probe: the new engine's probabilities must equal the
             eager oracle composition bit-for-bit on probe rows — the
             same contract the serve parity suite pins.
          4. ``SupervisedEngine.swap_engine`` (+ host scorer swap): a
             reference swap, atomic at flush granularity; the restart
             factory now rebuilds the new version.

        Any failure before step 4 leaves the previous engine serving and
        reports ``result="failed"`` — a bad deploy can degrade a replica
        to its previous model, never to a dead server."""
        from machine_learning_replications_tpu.persist import orbax_io
        from machine_learning_replications_tpu.resilience.supervisor import (
            SupervisedEngine,
        )

        if not isinstance(self.engine, SupervisedEngine):
            raise RuntimeError(
                "in-place deploy requires a supervised engine "
                "(serve without --no-supervise)"
            )
        if not self._deploy_lock.acquire(blocking=False):
            raise RuntimeError("a deploy is already in progress")
        t0 = time.monotonic()
        status: dict = {
            "state": "loading", "target": model_path,
            "from_version": self.model_version,
            # Display timestamp in the deploy-status payload; durations
            # come from the monotonic t0 above.
            "started": time.time(),  # graftcheck: disable=monotonic-clock
        }
        self.deploy_status = status
        journal.event(
            "deploy_start", path=model_path,
            from_version=self.model_version, replica=self.replica_id,
        )
        try:
            params, info = orbax_io.load_model_versioned(model_path)
            status.update(
                state="warming", to_version=info["version"],
                rolled_back=info["rolled_back"],
            )
            # AOT executable restore (docs/AOT.md): the bundle comes from
            # the directory that ACTUALLY restored (a rollback serves the
            # lastgood's blobs, never the corrupt target's). The whole
            # deploy hold — build + warm + parity below — collapses from
            # a ladder of compiles to a ladder of deserializes.
            aot_bundle = None
            if self.use_aot:
                from machine_learning_replications_tpu.persist import (
                    aot as aot_mod,
                )

                aot_bundle = aot_mod.load_bundle(info["path"])
            engine_buckets = self.engine.buckets
            # The new engine keeps feeding the SAME quality monitor only
            # when the input space is unchanged; a different family (or
            # lasso support) would feed rows the reference profile cannot
            # bin, so monitoring detaches, journaled.
            quality = (
                self.engine.quality
                if _same_input_space(self.live.get("params"), params)
                else None
            )
            if quality is None and self.engine.quality is not None:
                journal.event("deploy_quality_detached", path=model_path)
                if self.quality is not None:
                    # The kept monitor will never be fed again — left
                    # enabled it would serve its PRE-deploy status (e.g.
                    # a frozen 'alert') forever, which an unattended
                    # continual-learning daemon would read as "the
                    # promotion never recovered" and retrain in a loop.
                    # Disabled, /debug/quality says so and the trigger
                    # treats this replica as non-voting.
                    self.quality.disable(
                        "detached by deploy: the new checkpoint's input "
                        "space does not match the reference profile"
                    )

            def factory():
                import jax

                eng = BucketedPredictEngine(
                    params, buckets=engine_buckets, quality=quality,
                    aot=(
                        aot_bundle.for_backend(jax.default_backend())
                        if aot_bundle is not None else None
                    ),
                )
                # The version tags the engine (not just handle state) so
                # replies name the version of the bits they carry even
                # across the swap instant — and so a post-deploy
                # supervised restart rebuilds a correctly-tagged engine.
                eng.model_version = info["version"]
                eng.warmup(say=self._say)
                return eng

            new_engine = factory()
            new_scorer = None
            if self.host is not None:
                new_scorer = HostScorer(
                    params, buckets=self.host.scorer.buckets,
                    quality=quality,
                    aot=(
                        aot_bundle.for_backend("cpu")
                        if aot_bundle is not None else None
                    ),
                )
                new_scorer.model_version = info["version"]
                new_scorer.warmup(say=self._say)
            status["state"] = "verifying"
            _verify_parity(params, new_engine, new_scorer)
            self.engine.swap_engine(new_engine, factory)
            if new_scorer is not None:
                self.host.swap_scorer(new_scorer)
            self.live["params"] = params
            if quality is not None and self.quality is not None:
                # Continual-learning rebase (docs/CONTINUAL.md): when the
                # new checkpoint ships its OWN reference profile (a
                # retrained candidate fit on the shifted cohort), the
                # kept monitor must judge traffic against THAT baseline
                # — keeping the superseded model's profile would hold
                # the fleet in alert forever on exactly the traffic the
                # refit was promoted to match. Same-width is guaranteed
                # here (_same_input_space passed); the recovery to ok is
                # earned by post-swap traffic, journaled as a real
                # quality_status transition. A profile-less checkpoint
                # keeps the existing baseline unchanged, as before.
                new_profile = getattr(params, "quality", None)
                if new_profile is not None:
                    try:
                        self.quality.rebase(new_profile)
                    except Exception as exc:
                        # The engine swap above already committed — the
                        # replica IS serving the new version. Raising
                        # here would report a 'failed' deploy for a
                        # model that is live (the rollback rail would
                        # then reason from wrong state). A profile the
                        # monitor can't adopt detaches monitoring
                        # instead, loudly, on every surface.
                        journal.event(
                            "deploy_quality_detached", path=model_path,
                            error=str(exc),
                        )
                        self.quality.disable(
                            f"rebase failed after deploy: {exc}"
                        )
            self.model_version = info["version"]
            if info["version"] is not None:
                MODEL_VERSION.get().set(float(info["version"]))
            result = "rolled_back" if info["rolled_back"] else "ok"
            status.update(
                state="done", result=result, version=info["version"],
                restored_from=info["path"],
                seconds=round(time.monotonic() - t0, 3),
            )
            DEPLOYS.inc(result=result)
            journal.event(
                "deploy_applied", path=model_path,
                from_version=status["from_version"],
                to_version=info["version"],
                rolled_back=info["rolled_back"], replica=self.replica_id,
                seconds=status["seconds"],
            )
            return status
        except BaseException as exc:
            status.update(
                state="done", result="failed",
                error=f"{type(exc).__name__}: {exc}",
                seconds=round(time.monotonic() - t0, 3),
            )
            DEPLOYS.inc(result="failed")
            journal.event(
                "deploy_failed", path=model_path, replica=self.replica_id,
                error=status["error"], seconds=status["seconds"],
            )
            raise
        finally:
            self._deploy_lock.release()


def _same_input_space(old_params, new_params) -> bool:
    """True when the new checkpoint scores the same input space the
    quality monitor was built over: same param family, same lasso
    support (when the family selects columns)."""
    if old_params is None or type(old_params) is not type(new_params):
        return False
    old_mask = getattr(old_params, "support_mask", None)
    new_mask = getattr(new_params, "support_mask", None)
    if (old_mask is None) != (new_mask is None):
        return False
    if old_mask is not None:
        import numpy as np

        if not np.array_equal(np.asarray(old_mask), np.asarray(new_mask)):
            return False
    return True


def _oracle_probs(params, rows):
    """The eager single-request composition — the exact route
    ``cli predict`` takes — as the deploy parity oracle (shared with the
    engine's AOT restore probe: ``serve.engine.oracle_proba1``)."""
    from machine_learning_replications_tpu.serve.engine import oracle_proba1

    return oracle_proba1(params, rows)


def _verify_parity(params, engine, scorer=None, n_rows: int = 4) -> None:
    """Probe-row parity gate for a deploy candidate: the warmed engine
    (and host scorer) must reproduce the eager oracle at the engine
    parity contract — XLA fusion may regroup float ops vs op-by-op
    dispatch, so the tolerance is precision-dependent: rtol 1e-12 under
    x64 (the serve parity suite's documented bound), 1e-5 under the
    default float32 mode (fusion noise sits at ~1e-7 relative there;
    wrong weights differ at 1e-1) — and the host and device paths must
    agree with EACH OTHER bit-for-bit on the single-row program, before
    the candidate may swap into rotation. A miscompiled or
    wrong-weights candidate can never serve a single wrong answer."""
    import numpy as np

    from machine_learning_replications_tpu.data.examples import patient_row
    from machine_learning_replications_tpu.serve.engine import (
        parity_tolerance,
    )

    base = np.asarray(patient_row(), np.float64)
    rng = np.random.default_rng(0)
    rows = np.concatenate(
        [base] + [
            base * (1.0 + 0.05 * rng.standard_normal(base.shape))
            for _ in range(n_rows - 1)
        ],
        axis=0,
    )
    rtol, atol = parity_tolerance()
    want = _oracle_probs(params, rows)
    got = np.asarray(engine.predict(rows), np.float64)
    if not np.allclose(got, want, rtol=rtol, atol=atol):
        raise RuntimeError(
            "deploy candidate failed the parity probe: engine "
            f"probabilities {got.tolist()} != oracle {want.tolist()}"
        )
    if scorer is not None:
        got_host = np.asarray(
            [float(scorer.predict(r[None, :])[0]) for r in rows], np.float64
        )
        # Host vs device is the bit-for-bit leg: same composition, same
        # SINGLE-ROW program shape on both sides (hostpath.py) — any
        # drift here means the two paths would serve different bits for
        # the same patient. Compared per-row against the engine's own
        # single-row program: cross-bucket shapes are only
        # tolerance-comparable, same-shape programs are bit-comparable.
        got_single = np.asarray(
            [float(engine.predict(r[None, :])[0]) for r in rows],
            np.float64,
        )
        if not np.array_equal(got_host, got_single):
            raise RuntimeError(
                "deploy candidate failed the host-path parity probe: "
                f"{got_host.tolist()} != device {got_single.tolist()}"
            )


class _InFlight:
    """One admitted /predict request: the race between the batcher's
    completion (any flush thread) and the deadline timer (loop thread) is
    resolved under a lock — exactly one of them replies."""

    __slots__ = ("app", "trace", "responder", "future", "timer", "path",
                 "deadline_s", "row", "fell_back", "_done", "_lock")

    def __init__(self, app, trace, responder, future, path: str = "device",
                 deadline_s: float | None = None, row=None) -> None:
        self.app = app
        self.trace = trace
        self.responder = responder
        self.future = future
        self.timer = None
        self.path = path
        self.deadline_s = (
            deadline_s if deadline_s is not None else app.request_timeout_s
        )
        # Host-path requests keep their row for the one-shot fallback
        # resubmission through the device path (see on_done).
        self.row = row
        self.fell_back = False
        self._done = False
        self._lock = threading.Lock()

    def _claim(self) -> bool:
        with self._lock:
            if self._done:
                return False
            self._done = True
            return True

    def on_deadline(self) -> None:
        """The request missed its reply deadline (loop thread)."""
        if not self._claim():
            return
        app, trace = self.app, self.trace
        # Cancel so a still-queued request is dropped at flush time (the
        # batcher skips cancelled entries) — otherwise every deadline miss
        # still burns an engine slot computing an answer nobody reads,
        # compounding the overload.
        cancelled = self.future.cancel()
        app.metrics.timeouts_total.inc()
        msg = f"timed out after {self.deadline_s:g}s"
        if cancelled:
            # Truly unclaimed: the wait WAS the request — attribute it as
            # queue time. When cancel LOSES the claim race the flush
            # thread is stamping its own phases concurrently, so leave the
            # trace to it.
            trace.add_phase(
                "queue_wait",
                trace.phase_end("parse", trace.t_start),
                time.perf_counter(),
            )
        # Freeze BEFORE replying: a finished trace rejects late
        # flush-thread stamps, so the published phases can never overlap
        # each other or extend past t_end.
        trace.finish("timeout", error=msg)
        app._fail(self.responder, trace, "timeout", 504, msg)

    def on_done(self, future) -> None:
        """The batcher/host pool resolved the future (flush or host-path
        worker thread — or inline when already resolved at callback
        registration)."""
        exc0 = None if future.cancelled() else future.exception()
        if exc0 is not None and self.path == "host" and self.row is not None:
            # Host fast-path failure: ONE transparent resubmission through
            # the device path before anything reaches the client. The
            # supervised engine owns failure semantics — its watchdog,
            # breaker streak, and restart machinery must see engine
            # faults, and the host path is an optimization, not a second
            # failure domain (a persistently broken engine then degrades
            # exactly as it would without routing: device 500s feed the
            # breaker, the breaker sheds, the supervisor restarts).
            with self._lock:
                retry = not self._done and not self.fell_back
                if retry:
                    self.fell_back = True
            if retry:
                HOST_FALLBACKS.inc()
                self.path = "device"
                self.trace.note(path="device",
                                path_reason="host_error_fallback")
                # The failed attempt's phases would overlap the device
                # path's fresh stamps (its queue_wait restarts at parse
                # end); drop them so the published phases still
                # partition the request — the abandoned host time reads
                # as queueing, which is what it was to the client.
                self.trace.drop_phases("queue_wait", "host_compute")
                try:
                    # count=False: this logical request was counted at
                    # its host admission; the resubmission must not move
                    # requests_total again.
                    new_future = self.app.batcher.submit(
                        self.row, trace=self.trace, count=False
                    )
                except BaseException as sub_exc:
                    if not self._claim():
                        return
                    if self.timer is not None:
                        self.timer.cancel()
                    if isinstance(sub_exc, Overloaded):
                        self.trace.note(shed=True)
                        self.app._fail(self.responder, self.trace, "shed",
                                       503, "overloaded")
                    else:
                        self.app._fail(self.responder, self.trace, "error",
                                       500, str(exc0))
                    return
                self.future = new_future
                new_future.add_done_callback(self.on_done)
                return
        if not self._claim():
            return  # the deadline path already answered (and cancelled us)
        if self.timer is not None:
            self.timer.cancel()
        app, trace, responder = self.app, self.trace, self.responder
        exc = future.exception()
        if exc is not None:
            if isinstance(exc, BreakerOpen):
                # The breaker opened after this request was admitted (its
                # flush ran while degraded): same explicit shed contract
                # as the pre-admission check.
                DEGRADED_SHEDS.inc()
                trace.note(shed=True, degraded=True)
                app._fail(
                    responder, trace, "shed", 503, str(exc),
                    headers=_retry_after(exc.retry_after_s),
                )
            elif isinstance(exc, ComputeDeadlineExceeded):
                # The watchdog abandoned a wedged compute: the request is
                # dead in bounded time — 504, never a hang.
                app._fail(responder, trace, "timeout", 504, str(exc))
            else:
                app._fail(responder, trace, "error", 500, str(exc))
            return
        prob = future.result()
        # Respond phase starts at compute end (device_compute for the
        # batched path, host_compute for the fast path), so the phases
        # partition the whole server-side interval: completion-callback
        # scheduling delay is response-path latency, not dead time.
        t_resp0 = trace.phase_end(
            "device_compute",
            trace.phase_end("host_compute", time.perf_counter()),
        )
        try:
            # Faultpoint on the respond path: an injected fault here drops
            # the connection with NOTHING written — the client sees an
            # explicit transport error. A partial/garbled 200 body would
            # be the one unforgivable failure mode (a wrong answer); a
            # dead socket is not.
            faults.fire("server.respond")
        except faults.InjectedFault as exc:
            responder.abort()
            trace.add_phase("respond", t_resp0, time.perf_counter())
            trace.finish("error", error=str(exc))
            if app.slo_tracker is not None:
                app.slo_tracker.observe(trace.total_s, ok=False)
            app.recorder.record(trace)
            return
        # The taken path rides every reply so clients (loadgen's `paths`
        # block) can account the routing split without a /metrics scrape
        # — and the fleet identity (replica id + model version) rides
        # with it for the deploy crossover. The version comes from the
        # compute-time tag when one was stamped (batcher flush / host
        # worker note it from the engine that ran): handle state at
        # respond time can already name the NEXT version for bits an
        # in-flight flush computed on the old engine mid-deploy.
        identity = {"X-Serve-Path": self.path,
                    **app.handle.identity_headers()}
        computed_version = trace.meta.get("model_version")
        if computed_version is not None:
            identity["X-Model-Version"] = str(computed_version)
        responder.send_json(200, {
            "probability": prob,
            "text": OUTPUT_CONTRACT.format(100.0 * prob),
        }, request_id=trace.request_id, headers=identity)
        trace.add_phase("respond", t_resp0, time.perf_counter())
        trace.finish("ok")
        if app.slo_tracker is not None:
            app.slo_tracker.observe(trace.total_s, ok=True)
        app.recorder.record(trace)


class _App:
    """The application the transport dispatches into. Handlers run ON the
    event-loop thread and never block: /predict completes through the
    batcher future's done-callback, /debug/profile on its own thread —
    everything else is fast enough to answer inline."""

    def __init__(self, handle: ServerHandle, request_timeout_s: float,
                 quiet: bool) -> None:
        self.handle = handle
        self.request_timeout_s = float(request_timeout_s)
        self.quiet = quiet
        # Captured once (same lifetime as the old closure-captured
        # handler): tests may swap batcher internals, never these slots.
        self.batcher = handle.batcher
        self.metrics = handle.metrics
        self.engine = handle.engine
        self.recorder = handle.recorder
        self.slo_tracker = handle.slo_tracker
        self.host = handle.host          # HostPath or None
        self.router = handle.router      # PathRouter or None

    # -- transport interface -----------------------------------------------

    def handle_request(self, req, rsp) -> None:
        if not self.quiet:
            print(f"{req.method} {req.target}", file=sys.stderr)
        if req.method == "GET":
            self._get(req, rsp)
        elif req.method == "POST":
            self._post(req, rsp)
        else:
            rsp.send_json(
                501, {"error": f"unsupported method {req.method}"},
                close=True,
            )

    def handle_protocol_error(self, exc, rsp) -> None:
        """An unframeable request (bad Content-Length, oversized body or
        headers, malformed line). The reply always closes the connection
        — the parser no longer knows where the next request starts. A
        /predict failure still gets a trace (client-fault: it never
        reaches the SLO — a malformed body is not a served request the
        availability objective can lose)."""
        if exc.path == "/predict":
            trace = reqtrace.RequestTrace(
                reqtrace.sanitize_request_id(exc.headers.get("x-request-id"))
            )
            self._fail(
                rsp, trace, "bad_request", exc.code, exc.message,
                observe_slo=False, close=True,
            )
        else:
            rsp.send_json(exc.code, {"error": exc.message}, close=True)

    # -- failure path ------------------------------------------------------

    def _fail(
        self, rsp, trace, status: str, code: int, message: str,
        observe_slo: bool = True,
        headers: dict[str, str] | None = None,
        close: bool = False,
    ) -> None:
        """Terminal error path for a traced /predict request: reply
        (respond phase stamped around the enqueue), finish + record the
        trace, and feed the SLO tracker (client-fault 4xx paths pass
        ``observe_slo=False``). The responder never raises — a client
        that already hung up cannot exempt its request from the burn
        gauges or the flight recorder (the transport accounts the write
        failure separately)."""
        t0 = time.perf_counter()
        rsp.send_json(
            code, {"error": message}, request_id=trace.request_id,
            headers={**self.handle.identity_headers(), **(headers or {})},
            close=close,
        )
        trace.add_phase("respond", t0, time.perf_counter())
        trace.finish(status, error=message)
        if self.slo_tracker is not None and observe_slo:
            self.slo_tracker.observe(trace.total_s, ok=False)
        self.recorder.record(trace)

    # -- GET ----------------------------------------------------------------

    def _readiness_blockers(self) -> list[str]:
        """Why this replica should NOT receive traffic right now (empty =
        ready). The three non-ready states are exactly the ones a load
        balancer must react to without killing the process: still
        compiling, draining out, or degraded."""
        reasons = []
        if not self.engine.warm:
            reasons.append("warmup incomplete")
        if self.handle.draining:
            reasons.append("draining")
        if getattr(self.engine, "breaker_open", False):
            reasons.append("degraded: circuit breaker open")
        return reasons

    def _get(self, req, rsp) -> None:
        path, handle, engine = req.path, self.handle, self.engine
        if path == "/healthz":
            jrn = journal.get_journal()
            breaker = (
                engine.snapshot()
                if isinstance(engine, SupervisedEngine) else None
            )
            degraded = getattr(engine, "breaker_open", False)
            blockers = self._readiness_blockers()
            rsp.send_json(200, {
                # Liveness stays 200 even degraded: the process is alive
                # and must NOT be restarted by a prober — the supervisor
                # is already rebuilding the engine, and a kill would just
                # add a cold start on top.
                "status": "degraded" if degraded else "ok",
                "ready": not blockers,
                "draining": handle.draining,
                "breaker": breaker,
                "params": type(engine.params).__name__,
                "buckets": list(engine.buckets),
                "warm": engine.warm,
                "queue_depth": self.batcher.queue_depth,
                # Dual-path scoring: whether the host fast path is live
                # (the per-path traffic split is serve_path_total on
                # /metrics and the per-reply X-Serve-Path header).
                "host_path": handle.host is not None,
                "uptime_seconds": round(
                    self.metrics.uptime_seconds(), 3
                ),
                "run_id": (
                    jrn.manifest.get("run_id") if jrn is not None else None
                ),
                "worker": handle.worker_id,
                # Fleet identity: which replica this is and which
                # checkpoint version it serves (docs/FLEET.md).
                "replica": handle.replica_id,
                "model_version": handle.model_version,
                # Compact drift signal so an orchestrator can act on
                # model-quality degradation from the same probe it
                # already polls (full detail: /debug/quality).
                "quality": (
                    handle.quality.health()
                    if handle.quality is not None
                    else {"status": "disabled"}
                ),
                # Alerting plane summary (obs.alerts): rule counts and
                # the worst firing severity; None when disabled.
                "alerts": (
                    handle.alerts.summary()
                    if handle.alerts is not None else None
                ),
            })
        elif path == "/readyz":
            blockers = self._readiness_blockers()
            rsp.send_json(
                200 if not blockers else 503,
                {
                    "ready": not blockers, "reasons": blockers,
                    # The fleet prober reads identity off the same probe
                    # it rotates on: one GET per replica per tick.
                    "replica": handle.replica_id,
                    "version": handle.model_version,
                    # ... and the admission-queue depth: the router's
                    # least-loaded score and the autoscaler both read
                    # replica load without an extra request.
                    "queue_depth": self.batcher.queue_depth,
                    # This process's monotonic clock, echoed so the
                    # router's ClockSync can estimate the per-replica
                    # offset (NTP-style midpoint) and place replica-side
                    # trace phases on the router's timeline.
                    "clock_perf": time.perf_counter(),
                },
            )
        elif path == "/admin/deploy":
            if not handle.admin_enabled:
                rsp.send_json(403, {
                    "error": "admin deploy endpoint disabled "
                    "(start serve with --admin-endpoint)",
                })
            else:
                rsp.send_json(200, {
                    "deploy": handle.deploy_status,
                    "model_version": handle.model_version,
                })
        elif path == "/debug/faults":
            if not faults.endpoint_enabled():
                rsp.send_json(403, {
                    "error": "fault-injection endpoint disabled "
                    "(start serve with --inject or --fault-endpoint)",
                })
            else:
                rsp.send_json(200, faults.snapshot())
        elif path == "/debug/quality":
            if handle.quality is None:
                rsp.send_json(200, qualitymod.disabled_snapshot(
                    "no reference profile in the served params "
                    "(or started with --no-quality)"
                ))
            elif handle.quality_feed is not None:
                # Async feed: drain what is already handed off so a
                # snapshot taken right after traffic reflects that
                # traffic. The bounded wait runs on its own short-lived
                # thread (the /debug/profile pattern) — the event loop
                # must never block behind the feed.
                threading.Thread(
                    target=self._quality_snapshot, args=(rsp,),
                    name="serve-quality-snap", daemon=True,
                ).start()
            else:
                rsp.send_json(200, handle.quality.snapshot(detail=True))
        elif path == "/debug/requests":
            rid = req.query_param("id", "")
            if rid:
                # Exact lookup by request id (the fleet trace join's
                # fetch primitive): every completed request is indexed,
                # not just the tail-sampled ring, since the router and
                # replica sample independently.
                snap = self.recorder.lookup(rid)
                if snap is None:
                    rsp.send_json(404, {
                        "error": f"request id not indexed: {rid}",
                    })
                else:
                    rsp.send_json(200, {"request": snap})
                return
            try:
                n = int(req.query_param("n", "64"))
            except ValueError:
                rsp.send_json(400, {"error": "n must be an integer"})
                return
            rsp.send_json(200, {
                "stats": self.recorder.stats(),
                "slo": (
                    self.slo_tracker.snapshot()
                    if self.slo_tracker is not None else []
                ),
                "requests": self.recorder.snapshot(n),
            })
        elif path == "/debug/alerts":
            # In-memory read — inline is fine.
            if handle.alerts is None:
                rsp.send_json(200, {
                    "enabled": False, "active": [], "summary": None,
                })
                return
            snap = handle.alerts.snapshot()
            rsp.send_json(200, {
                "enabled": True,
                "active": snap["active"],
                "summary": handle.alerts.summary(),
                "rules": snap["rules"],
            })
        elif path == "/debug/history":
            store = handle.history
            if store is None:
                rsp.send_json(200, {"enabled": False, "families": {}})
                return
            family = req.query_param("family", "")
            if not family:
                rsp.send_json(200, {
                    "enabled": True,
                    "families": store.families(),
                    "stats": store.stats(),
                })
                return
            try:
                window = float(req.query_param("window", "0") or 0)
            except ValueError:
                rsp.send_json(400, {"error": "window must be a number"})
                return
            now = time.time()  # graftcheck: disable=monotonic-clock
            rsp.send_json(200, store.query(
                family, window if window > 0 else None, now,
            ))
        elif path == "/debug/profile":
            try:
                seconds = float(req.query_param("seconds", "1"))
            except ValueError:
                rsp.send_json(400, {"error": "seconds must be a number"})
                return
            # The capture blocks for its whole window — on a dedicated
            # short-lived thread, never the event loop (a 10 s capture
            # inline would stall every connection for 10 s).
            threading.Thread(
                target=self._profile_capture, args=(seconds, rsp),
                name="serve-profile", daemon=True,
            ).start()
        elif path == "/metrics":
            fmt = req.query_param("format", "prometheus")
            if fmt == "json":
                snap = self.metrics.snapshot()
                snap["runtime"] = REGISTRY.snapshot()
                rsp.send_json(200, snap)
            else:
                # serve_* exposition first, byte-identical to the
                # standalone render; the global registry (jax compile and
                # transfer accounting) appended as its own families.
                text = self.metrics.render_prometheus() + \
                    REGISTRY.render_prometheus()
                rsp.send(
                    200, text.encode(), "text/plain; version=0.0.4",
                )
        else:
            rsp.send_json(404, {"error": f"no such path: {path}"})

    def _quality_snapshot(self, rsp) -> None:
        try:
            self.handle.quality_feed.drain(timeout=2.0)
            snap = self.handle.quality.snapshot(detail=True)
        except Exception as exc:
            rsp.send_json(500, {"error": f"{type(exc).__name__}: {exc}"})
            return
        rsp.send_json(200, snap)

    def _profile_capture(self, seconds: float, rsp) -> None:
        try:
            artifact = profiler.capture(seconds, self.handle.profile_dir)
        except profiler.ProfilerBusy as exc:
            rsp.send_json(409, {"error": str(exc)})
            return
        except ValueError as exc:
            rsp.send_json(400, {"error": str(exc)})
            return
        except Exception as exc:  # profiler backend failure
            rsp.send_json(500, {"error": f"{type(exc).__name__}: {exc}"})
            return
        rsp.send_json(200, artifact)

    # -- POST ---------------------------------------------------------------

    def _post(self, req, rsp) -> None:
        if req.path == "/debug/faults":
            self._post_faults(req, rsp)
            return
        if req.path == "/admin/deploy":
            self._post_deploy(req, rsp)
            return
        if req.path != "/predict":
            # The body was framed and consumed, but a POST to an unknown
            # path keeps the threaded server's contract: reply 404 and
            # close.
            rsp.send_json(
                404, {"error": f"no such path: {req.target}"}, close=True,
            )
            return
        self._predict(req, rsp)

    def _post_deploy(self, req, rsp) -> None:
        """POST /admin/deploy ``{"model": PATH}``: warm-swap this replica
        onto a new checkpoint version (``ServerHandle.deploy_model``).
        Guarded like /debug/faults — a production server must not be
        model-swappable by whoever can reach its port. The reply comes
        when the deploy is DONE (load + warm + parity + swap), so the
        fleet controller's per-replica step is one long POST; progress is
        observable meanwhile on GET /admin/deploy. Runs on a dedicated
        thread — warmup compiles must never stall the event loop."""
        if not self.handle.admin_enabled:
            rsp.send_json(403, {
                "error": "admin deploy endpoint disabled "
                "(start serve with --admin-endpoint)",
            }, close=True)
            return
        try:
            body = json.loads(req.body or b"{}")
            model = body.get("model") if isinstance(body, dict) else None
            if not model or not isinstance(model, str):
                raise ValueError('expected {"model": "checkpoint path"}')
        except (ValueError, json.JSONDecodeError) as exc:
            rsp.send_json(400, {"error": str(exc)})
            return

        def run():
            try:
                status = self.handle.deploy_model(model)
            except RuntimeError as exc:
                busy = "already in progress" in str(exc)
                rsp.send_json(
                    409 if busy else 500,
                    {"error": str(exc),
                     "deploy": self.handle.deploy_status},
                )
                return
            except Exception as exc:
                rsp.send_json(500, {
                    "error": f"{type(exc).__name__}: {exc}",
                    "deploy": self.handle.deploy_status,
                })
                return
            rsp.send_json(200, {"deploy": status})

        threading.Thread(
            target=run, name="serve-deploy", daemon=True
        ).start()

    def _post_faults(self, req, rsp) -> None:
        """POST /debug/faults: arm/disarm/reset the injection registry
        over HTTP (the chaos driver's control plane). Guarded — see
        ``faults.enable_endpoint``."""
        if not faults.endpoint_enabled():
            rsp.send_json(403, {
                "error": "fault-injection endpoint disabled "
                "(start serve with --inject or --fault-endpoint)",
            }, close=True)
            return
        try:
            body = json.loads(req.body or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            if "arm" in body:
                faults.arm(str(body["arm"]))
            elif "disarm" in body:
                faults.disarm(str(body["disarm"]))
            elif body.get("reset"):
                faults.reset()
            else:
                raise ValueError(
                    'expected {"arm": SPEC}, {"disarm": SITE}, '
                    'or {"reset": true}'
                )
        except (ValueError, json.JSONDecodeError) as exc:
            rsp.send_json(400, {"error": str(exc)})
            return
        rsp.send_json(200, faults.snapshot())

    def _predict(self, req, rsp) -> None:
        from machine_learning_replications_tpu.data.examples import (
            validate_patient,
        )

        # Request identity at admission: honor an inbound X-Request-Id
        # (sanitized — a hostile header must not inject into logs/replies),
        # mint one otherwise; every reply below echoes it.
        trace = reqtrace.RequestTrace(
            reqtrace.sanitize_request_id(req.get_header("x-request-id"))
        )
        try:
            # Faultpoint at admission, before the body is parsed: an
            # injected parse fault replies an explicit 500 and closes.
            faults.fire("server.parse")
        except faults.InjectedFault as exc:
            self._fail(rsp, trace, "error", 500, str(exc), close=True)
            return
        try:
            patient = json.loads(req.body or b"{}")
            row = validate_patient(patient)
        except (ValueError, json.JSONDecodeError) as exc:
            self._fail(
                rsp, trace, "bad_request", 400, str(exc), observe_slo=False
            )
            return
        trace.add_phase("parse", trace.t_start, time.perf_counter())
        # Degraded mode: while the breaker is open the engine cannot
        # answer, so shed HERE — an explicit 503 with a Retry-After
        # derived from the restart schedule — instead of admitting into a
        # queue that can only fail or time the client out.
        if getattr(self.engine, "breaker_open", False):
            # Both shed families move, once each: serve_shed_total is THE
            # shed-rate metric (overload + degraded alike — same
            # explicit-503 contract), resilience_degraded_sheds_total
            # attributes the degraded subset.
            self.metrics.shed_total.inc()
            DEGRADED_SHEDS.inc()
            trace.note(shed=True, degraded=True)
            self._fail(
                rsp, trace, "shed", 503, "degraded: engine restarting",
                headers=_retry_after(self.engine.retry_after_s()),
            )
            return
        # Per-request deadline: the server-wide --request-timeout, tightened
        # by an optional client X-Request-Deadline-Ms header (never
        # loosened — the server's bound is the contract). The router sees
        # the effective value: a tight deadline is a routing signal.
        deadline_s = self.request_timeout_s
        raw_deadline = req.get_header("x-request-deadline-ms")
        if raw_deadline:
            try:
                client_s = float(raw_deadline) / 1000.0
            except ValueError:
                client_s = 0.0
            if client_s > 0.0:
                deadline_s = min(deadline_s, client_s)
        # Dual-path routing (PathRouter, docs/SERVING.md): host fast path
        # for singles on an idle server, device micro-batches for bursts.
        # A HostBusy race (a slot vanished between decide and submit)
        # falls back to the device path; the counted path is the one the
        # request actually took. An inbound X-Serve-Path header pins the
        # request (device: always honored — the drill/bench escape hatch
        # for exercising the supervised engine directly; host: honored
        # when the fast path can take it) — pinning selects an execution
        # strategy, both of which serve the same bits.
        pin = (req.get_header("x-serve-path") or "").strip().lower()
        if self.router is None:
            path, reason = "device", "no_host_path"
        elif pin == "device":
            path, reason = "device", "client_pinned"
        elif pin == "host":
            # A zero deadline makes decide() prefer the host whenever it
            # can take the request; saturation/unavailability still fall
            # back with their own reason.
            path, reason = self.router.decide(0.0)
            if path == "host":
                reason = "client_pinned"
        else:
            path, reason = self.router.decide(deadline_s)
        future = None
        if path == "host":
            try:
                future = self.host.submit(row[0], trace=trace)
                self.metrics.requests_total.inc()
            except HostBusy:
                path, reason = "device", "host_saturated"
            except RuntimeError as exc:  # closed during shutdown
                self._fail(rsp, trace, "shed", 503, str(exc))
                return
        if future is None:
            try:
                future = self.batcher.submit(row[0], trace=trace)
            except Overloaded:
                trace.note(shed=True)
                self._fail(rsp, trace, "shed", 503, "overloaded")
                return
            except RuntimeError as exc:  # closed during shutdown
                self._fail(rsp, trace, "shed", 503, str(exc))
                return
        PATHS.inc(path=path)
        trace.note(path=path, path_reason=reason)
        ctx = _InFlight(
            self, trace, rsp, future, path=path, deadline_s=deadline_s,
            row=row[0] if path == "host" else None,
        )
        # Deadline on the loop clock; the done-callback and the timer race
        # under the ctx lock, so exactly one replies. add_done_callback
        # runs inline when the future already resolved.
        ctx.timer = self.handle.httpd.call_later(
            deadline_s, ctx.on_deadline
        )
        future.add_done_callback(ctx.on_done)


def make_server(
    params,
    host: str = "127.0.0.1",
    port: int = 8000,
    buckets=DEFAULT_BUCKETS,
    max_batch_size: int | None = None,
    max_wait_ms: float = 5.0,
    max_queue: int = 1024,  # above the top default bucket (512): a full
    # largest-bucket batch must be formable under saturation, or the top
    # bucket's executable only ever runs padded
    warmup: bool = True,
    request_timeout_s: float = 30.0,
    quiet: bool = True,
    say=None,
    slos=None,
    recorder=None,
    trace_capacity: int = 256,
    tail_quantile: float = 0.99,
    profile_dir: str | None = None,
    quality_profile=None,
    no_quality: bool = False,
    drift_warn_psi: float = qualitymod.DEFAULT_WARN_PSI,
    drift_alert_psi: float = qualitymod.DEFAULT_ALERT_PSI,
    quality_window: int = 2048,
    supervise: bool = True,
    flush_deadline_s: float = 20.0,
    breaker_failures: int = 3,
    restart_backoff_s: float = 0.5,
    restart_backoff_max_s: float = 30.0,
    fault_endpoint: bool = False,
    idle_timeout_s: float = 5.0,
    max_connections: int = 8192,
    reuse_port: bool = False,
    worker_id: int | None = None,
    host_path: bool = False,
    host_buckets=DEFAULT_HOST_BUCKETS,
    host_workers: int = 1,
    burst_depth: int = 1,
    tight_deadline_s: float = 0.05,
    quality_async: bool = True,
    model_version: int | None = None,
    replica_id: str | None = None,
    admin_endpoint: bool = False,
    aot_bundle=None,
    use_aot: bool = True,
    history_interval_s: float = 10.0,
    alert_rules: list | None = None,
    alerts_enabled: bool = True,
    incident_dir: str | None = None,
    incident_min_interval_s: float = 60.0,
    incident_retention: int = 8,
) -> ServerHandle:
    """Assemble the serving stack around fitted ``params`` and bind the
    listener (not yet serving — call ``serve_forever`` or
    ``start_background``). ``max_batch_size`` defaults to
    ``CPU_DEFAULT_MAX_BATCH`` (64) on the CPU backend — BENCH.md's
    measured recommendation; big flushes there are pure padded waste —
    and to the largest bucket on device backends, where a full top
    bucket pads nothing.

    Dual-path scoring (docs/SERVING.md "Dual-path scoring"): with
    ``host_path=True`` (the ``cli serve`` default; off here so embedded
    and test callers opt in) a ``HostScorer`` — the SAME engine
    composition pre-traced on the host CPU backend at ``host_buckets`` —
    answers requests the ``PathRouter`` routes away from the batcher:
    singles and small groups on an idle server skip both the coalescing
    window and the accelerator round trip, at bit-for-bit parity with
    the device path. ``host_workers`` bounds the pool (a busy host path
    self-routes back to the device); ``burst_depth`` is the batcher
    queue depth at which coalescing wins; requests whose effective
    deadline is at or under ``tight_deadline_s`` prefer the host path.
    The split is exported as ``serve_path_total{path=…}``, echoed
    per-reply as ``X-Serve-Path``, and annotated on every trace.

    ``history_interval_s`` > 0 starts the telemetry history sampler
    (``obs.timeseries``) behind ``GET /debug/history``;
    ``alerts_enabled`` evaluates ``alert_rules`` (None →
    ``obs.alerts.default_rules("replica")``) each tick, served on
    ``GET /debug/alerts`` and summarized on ``/healthz``;
    ``incident_dir`` captures a flight-recorder bundle when a rule
    fires (docs/OBSERVABILITY.md "Alerting & incidents").

    ``quality_async`` (default) feeds the drift monitor through
    ``obs.quality.AsyncQualityFeed`` — a bounded hand-off serviced by a
    background thread, sampling then shedding (counted) under pressure —
    instead of running binning and PSI refreshes on the flush thread
    (measured at ~30% of saturated throughput in r11).

    Request-scoped observability: ``recorder`` (default a fresh
    ``reqtrace.FlightRecorder(trace_capacity, tail_quantile)``) receives
    every completed /predict trace under tail sampling; ``slos`` (default
    ``slo.default_slos()``; pass ``[]`` to disable) declares the
    objectives whose burn gauges ride ``/metrics``; ``profile_dir``
    (default a per-process dir under the system temp dir) receives
    ``/debug/profile`` captures.

    Model-quality monitoring (``obs.quality``): ``quality_profile`` is the
    training-time reference profile — by default the one the served
    ``PipelineParams`` carries (``params.quality``); pass one explicitly to
    monitor a bare imported ensemble, or ``no_quality=True`` to disable.
    When a profile is available, every flushed batch streams into a
    ``QualityMonitor`` (PSI/KS drift vs the reference under the
    ``drift_warn_psi``/``drift_alert_psi`` thresholds, over a
    ``quality_window``-row sliding window) exported on ``/metrics``
    (``quality_*``), ``/debug/quality``, and ``/healthz``. Without one,
    quality monitoring is simply off (``/healthz`` says ``disabled``) —
    pre-profile checkpoints keep serving.

    Resilience (``resilience.supervisor``, docs/RESILIENCE.md): with
    ``supervise`` (the default) the engine runs behind a watchdog
    (``flush_deadline_s`` per flush) and a circuit breaker
    (``breaker_failures`` consecutive failures, or one wedged compute,
    open it); while open, ``/predict`` sheds 503 + ``Retry-After`` and a
    supervised restart rebuilds + re-warms the engine under bounded
    exponential backoff (``restart_backoff_s``..``restart_backoff_max_s``).
    ``fault_endpoint`` opts the process into ``/debug/faults`` chaos
    control (``resilience.faults``).

    Transport (``serve.transport``): a non-blocking event loop serves
    every connection from one thread — keep-alive pipelining, bounded
    buffers, idle/slow-loris reaping after ``idle_timeout_s``, at most
    ``max_connections`` concurrent sockets. ``reuse_port`` binds with
    ``SO_REUSEPORT`` for the pre-fork multi-worker mode (``cli serve
    --workers N``); ``worker_id`` threads the worker's identity into
    ``/healthz``, ``/metrics`` (``serve_worker_info{worker=…}``), and —
    via the CLI — the journal manifest, so scrapes and journals through
    the shared port stay attributable to a specific worker process.

    Fleet (docs/FLEET.md): ``model_version`` is the served checkpoint's
    monotonic version id (``persist.checkpoint_version``) and
    ``replica_id`` the identity this replica registered under — both are
    echoed per reply (``X-Model-Version`` / ``X-Replica``) and on the
    health probes. ``admin_endpoint`` opts into the guarded
    ``/admin/deploy`` warm-swap endpoint (``ServerHandle.deploy_model``)
    — off by default for the same reason ``/debug/faults`` is.

    AOT restore (docs/AOT.md): ``aot_bundle`` is the served checkpoint's
    published executable bundle (``persist.aot.load_bundle``) — warmup
    then deserializes per-bucket executables instead of tracing them,
    with a journaled fails-open fallback per bucket. ``use_aot=False``
    (``cli serve --no-aot``) ignores bundles everywhere, including later
    ``/admin/deploy`` swaps — the escape hatch that guarantees a bad
    serialized artifact can never brick a fleet.

    The listener BINDS before warmup runs: a port conflict fails in
    milliseconds instead of after the multi-second compile bill. Warmup
    still completes before this returns (warm standby — the first served
    request never pays a compile); start serving first and call
    ``engine.warmup`` yourself for observable warm=false readiness. On
    ANY failure (warmup included) the bound port is released — and the
    same guarantee holds per worker in multi-worker mode, where a failed
    worker must not wedge the shared port's replacement bind."""
    # Compile/transfer accounting BEFORE the engine exists, so the param
    # upload and every warmup compile land in the /metrics counters.
    jaxmon.install()
    quality_monitor = None
    if not no_quality:
        prof = (
            quality_profile if quality_profile is not None
            else getattr(params, "quality", None)
        )
        if prof is not None:
            import numpy as np

            # Full-pipeline checkpoints profile the model's OWN
            # lasso-selected columns (ascending schema order) — NOT the
            # 17-variable contract order a bare ensemble scores — so the
            # monitor's feature labels must come from the support mask,
            # or every quality_feature_psi series (and the /debug/quality
            # worst-offender table) names the wrong variable.
            feature_names = None
            if getattr(params, "support_mask", None) is not None:
                from machine_learning_replications_tpu.models.pipeline import (
                    support_feature_names,
                )

                feature_names = support_feature_names(params)
            # Fail at startup, not on the first flush: a profile whose
            # width doesn't match the rows the engine will feed (e.g. one
            # built over a pre-selection 64-column matrix attached to a
            # bare 17-column ensemble) would otherwise fail every served
            # batch's observe call. Checked on the RAW profile, before
            # the monitor exists — constructing it first would register
            # phantom series in the process-global registry that no
            # rejection can remove.
            expected_width = (
                len(feature_names) if feature_names is not None else 17
            )
            if isinstance(prof, dict) and "bin_counts" in prof:
                width = int(np.asarray(prof["bin_counts"]).shape[0])
                if width != expected_width:
                    raise ValueError(
                        f"quality profile is {width} features wide but "
                        f"the served model scores {expected_width}-feature "
                        "rows; build the profile over the model's own "
                        "input space"
                    )
            quality_monitor = qualitymod.QualityMonitor(
                prof,
                warn_psi=drift_warn_psi,
                alert_psi=drift_alert_psi,
                window=quality_window,
                feature_names=feature_names,
            )
    # The engine (and the host scorer) feed rows through the async
    # hand-off by default: drift math must not tax the flush thread.
    quality_feed = None
    engine_quality = quality_monitor
    if quality_monitor is not None and quality_async:
        quality_feed = qualitymod.AsyncQualityFeed(quality_monitor)
        engine_quality = quality_feed
    if fault_endpoint:
        faults.enable_endpoint()
    if not use_aot:
        aot_bundle = None
    device_aot = host_aot = None
    if aot_bundle is not None:
        import jax

        device_aot = aot_bundle.for_backend(jax.default_backend())
        host_aot = aot_bundle.for_backend("cpu")
    engine = BucketedPredictEngine(
        params, buckets=buckets, quality=engine_quality, aot=device_aot
    )
    # Fleet identity rides ON the computing engine, not just the handle:
    # around a warm swap (/admin/deploy), in-flight flushes finish on the
    # engine they were submitted to, so the version a reply claims must
    # come from that engine — handle state at respond time can already
    # name the NEXT version for bits the old engine computed.
    engine.model_version = model_version
    if supervise:
        engine_buckets = engine.buckets

        def rebuild_engine():
            # Restart path (supervisor thread, off the request path):
            # fresh jit cache, ALWAYS re-warmed — a restarted engine that
            # made the first post-recovery requests pay the compile bill
            # would turn recovery into a tail-latency incident. With an
            # AOT bundle the rebuild restores executables too, so the
            # breaker's rebuild-after-wedge window shrinks the same way
            # cold start does.
            eng = BucketedPredictEngine(
                params, buckets=engine_buckets, quality=engine_quality,
                aot=device_aot,
            )
            eng.model_version = model_version
            eng.warmup(say=say)
            return eng

        engine = SupervisedEngine(
            engine, rebuild_engine,
            flush_deadline_s=flush_deadline_s,
            breaker_failures=breaker_failures,
            restart_backoff_s=restart_backoff_s,
            restart_backoff_max_s=restart_backoff_max_s,
        )
    if max_batch_size is None:
        import jax

        # BENCH.md's CPU recommendation is the default there; device
        # backends keep the full top bucket.
        max_batch_size = (
            min(CPU_DEFAULT_MAX_BATCH, engine.buckets[-1])
            if jax.default_backend() == "cpu" else engine.buckets[-1]
        )
    metrics = ServingMetrics(batch_buckets=engine.buckets)
    batcher = MicroBatcher(
        engine,
        max_batch_size=max_batch_size,
        max_wait_ms=max_wait_ms,
        max_queue=max_queue,
        metrics=metrics,
    )
    host_pool = router = None
    if host_path:
        scorer = HostScorer(
            params, buckets=host_buckets, quality=engine_quality,
            aot=host_aot,
        )
        scorer.model_version = model_version
        host_pool = HostPath(scorer, workers=host_workers, metrics=metrics)
        router = PathRouter(
            batcher, host_pool,
            burst_depth=burst_depth, tight_deadline_s=tight_deadline_s,
        )
    if recorder is None:
        recorder = reqtrace.FlightRecorder(
            capacity=trace_capacity, tail_quantile=tail_quantile
        )
    if slos is None:
        slos = slo.default_slos()
    slo_tracker = slo.SLOTracker(slos) if slos else None
    if profile_dir is None:
        profile_dir = os.path.join(
            tempfile.gettempdir(), f"mlr_profiles_{os.getpid()}"
        )
    if worker_id is not None:
        # Attribution through the shared SO_REUSEPORT port: every scrape
        # names the worker process it landed on.
        WORKER_INFO.set(1, worker=str(worker_id))
    if model_version is not None:
        MODEL_VERSION.get().set(float(model_version))
    handle = ServerHandle(
        engine, batcher, metrics, None,
        recorder=recorder, slo_tracker=slo_tracker, profile_dir=profile_dir,
        quality=quality_monitor, worker_id=worker_id,
        host=host_pool, router=router, quality_feed=quality_feed,
        model_version=model_version, replica_id=replica_id,
        admin_enabled=admin_endpoint, live={"params": params}, say=say,
        use_aot=use_aot,
    )
    if history_interval_s > 0:
        handle.history = timeseries.TimeSeriesStore(
            interval_s=history_interval_s,
        )
        if alerts_enabled:
            handle.alerts = alertsmod.AlertEngine(
                alert_rules if alert_rules is not None
                else alertsmod.default_rules("replica"),
                handle.history,
            )
        if incident_dir is not None and handle.alerts is not None:
            handle.incidents = incidentmod.IncidentCapturer(
                incident_dir,
                store=handle.history,
                collectors={
                    "requests": lambda: recorder.snapshot(64),
                    "metrics": REGISTRY.snapshot,
                    "slo": (
                        slo_tracker.snapshot if slo_tracker is not None
                        else dict
                    ),
                    "quality": (
                        quality_monitor.health
                        if quality_monitor is not None else dict
                    ),
                },
                min_interval_s=incident_min_interval_s,
                retention=incident_retention,
            )
    app = _App(handle, request_timeout_s, quiet)
    try:
        handle.httpd = EventLoopHttpServer(
            (host, port), app,
            idle_timeout_s=idle_timeout_s,
            max_connections=max_connections,
            reuse_port=reuse_port,
        )
        if warmup:
            engine.warmup(say=say)
            if host_pool is not None:
                # The fast path's tiny ladder compiles in a fraction of
                # the device warmup; until it is warm the router keeps
                # every request on the device path (with --no-warmup the
                # host path stays parked the same way).
                host_pool.scorer.warmup(say=say)
    except BaseException:
        batcher.close(drain=False, timeout=1.0)
        if host_pool is not None:
            host_pool.close(timeout=1.0)
        if quality_feed is not None:
            quality_feed.close(timeout=1.0)
        close_engine = getattr(engine, "close", None)
        if close_engine is not None:
            close_engine()
        if handle.httpd is not None:
            # The listener bound before warmup failed: release the port so
            # a caller that catches and retries doesn't hit EADDRINUSE.
            handle.httpd.server_close()
        raise
    if handle.history is not None:
        # Started only after the stack assembled: a bind/warmup failure
        # must not leak a sampler thread.
        engine_ref, capturer = handle.alerts, handle.incidents

        def _tick(now: float) -> None:
            if engine_ref is None:
                return
            for transition in engine_ref.evaluate(now):
                if capturer is not None:
                    capturer.maybe_capture(transition)

        handle.sampler = timeseries.HistorySampler(
            handle.history, timeseries.collect_registry,
            interval_s=history_interval_s, on_tick=_tick,
        ).start()
    return handle
