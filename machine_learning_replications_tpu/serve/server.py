"""Stdlib HTTP front end for the serving layer.

Endpoints (``ThreadingHTTPServer`` — one thread per connection feeding the
shared micro-batcher, no third-party dependencies):

  POST /predict   body = the 17-variable patient JSON (``predict_hf.py:5-27``,
                  same validation as ``cli.py predict --patient``) → 200
                  ``{"probability": p, "text": "Probability of progressive
                  HF is: XX.XX %"}``. 400 on contract violations, 413 on
                  oversized bodies (never read into memory), 503
                  ``{"error": "overloaded"}`` when admission control sheds,
                  504 when an admitted request misses the request deadline
                  (it is cancelled, so the engine never computes it).
  GET  /healthz   liveness/readiness: params family, bucket ladder, warm
                  flag, queue depth.
  GET  /metrics   Prometheus text exposition (``?format=json`` for the
                  same data as JSON) — ``serve.metrics``, with the
                  process-global ``obs`` registry's exposition appended
                  (jax compile counts/seconds and transfer bytes from
                  ``obs.jaxmon``, installed at ``make_server``), so one
                  scrape answers both "is the server shedding?" and "did
                  it start recompiling?".

``ServerHandle.shutdown`` is the graceful path: stop accepting, drain the
batcher (admitted requests are never dropped), then stop the listener.
"""

from __future__ import annotations

import json
import threading
from concurrent.futures import TimeoutError as FuturesTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse, parse_qs


class _Server(ThreadingHTTPServer):
    # Kernel accept backlog. The socketserver default (5) drops SYNs under
    # open-loop bursts, so clients stall in 1 s / 3 s / 7 s TCP retransmit
    # and overload shows up as silent kernel drops — it must instead reach
    # the bounded batcher queue, whose explicit 503 is the shedding
    # contract this layer is built around.
    request_queue_size = 128

from machine_learning_replications_tpu.obs import jaxmon
from machine_learning_replications_tpu.obs.registry import REGISTRY
from machine_learning_replications_tpu.serve.batcher import (
    MicroBatcher,
    Overloaded,
)
from machine_learning_replications_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    BucketedPredictEngine,
)
from machine_learning_replications_tpu.serve.metrics import ServingMetrics

# predict_hf.py:38-40 — the single-patient CLI prints exactly this line;
# the HTTP reply carries it verbatim so the serving layer inherits the
# output contract.
OUTPUT_CONTRACT = "Probability of progressive HF is: {:.2f} %"


class ServerHandle:
    """A running serving stack: engine + batcher + metrics + HTTP listener."""

    def __init__(self, engine, batcher, metrics, httpd) -> None:
        self.engine = engine
        self.batcher = batcher
        self.metrics = metrics
        self.httpd = httpd
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self.httpd.server_address[:2]

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def start_background(self) -> "ServerHandle":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="serve-http", daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self, drain: bool = True) -> None:
        """Graceful stop: close admission (draining by default), then stop
        the HTTP loop. Safe to call more than once."""
        self.batcher.close(drain=drain)
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None


def _make_handler(handle: ServerHandle, request_timeout_s: float, quiet: bool):
    batcher, metrics, engine = handle.batcher, handle.metrics, handle.engine

    class Handler(BaseHTTPRequestHandler):
        # Persistent connections keep the loadgen's closed loop honest
        # (no per-request TCP handshake in the measured latency).
        protocol_version = "HTTP/1.1"
        # Socket-level read timeout (StreamRequestHandler applies this per
        # connection): without it, every idle keep-alive client pins a
        # handler thread forever in readline(). BaseServer.timeout would
        # NOT do this — serve_forever ignores it. Also bounds how long a
        # lingering idle connection can delay the drain-join in shutdown.
        timeout = 5.0
        # A patient JSON is ~600 bytes; anything near this bound is not a
        # legitimate request, and an uncapped read would let one oversized
        # POST buffer past every bound the admission queue enforces.
        max_body_bytes = 64 * 1024

        def _reply(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _json(self, code: int, obj) -> None:
            self._reply(
                code, json.dumps(obj).encode(), "application/json"
            )

        def do_GET(self) -> None:  # noqa: N802 (http.server API)
            url = urlparse(self.path)
            if url.path == "/healthz":
                self._json(200, {
                    "status": "ok",
                    "params": type(engine.params).__name__,
                    "buckets": list(engine.buckets),
                    "warm": engine.warm,
                    "queue_depth": batcher.queue_depth,
                })
            elif url.path == "/metrics":
                fmt = parse_qs(url.query).get("format", ["prometheus"])[0]
                if fmt == "json":
                    snap = metrics.snapshot()
                    snap["runtime"] = REGISTRY.snapshot()
                    self._json(200, snap)
                else:
                    # serve_* exposition first, byte-identical to the
                    # standalone render; the global registry (jax compile
                    # and transfer accounting) appended as its own
                    # families.
                    text = metrics.render_prometheus() + \
                        REGISTRY.render_prometheus()
                    self._reply(
                        200, text.encode(), "text/plain; version=0.0.4",
                    )
            else:
                self._json(404, {"error": f"no such path: {url.path}"})

        def do_POST(self) -> None:  # noqa: N802 (http.server API)
            if urlparse(self.path).path != "/predict":
                # Unread body on a keep-alive connection would be parsed
                # as the NEXT request line — close instead of desyncing.
                self.close_connection = True
                self._json(404, {"error": f"no such path: {self.path}"})
                return
            from machine_learning_replications_tpu.data.examples import (
                validate_patient,
            )

            try:
                length = int(self.headers.get("Content-Length", ""))
            except ValueError:
                length = -1
            if length < 0:
                # Missing, unparseable, or negative Content-Length: the
                # body length is unknowable (rfile.read(negative) would
                # even read to EOF, stalling until the socket timeout),
                # so the connection cannot be resynced either — close it.
                self.close_connection = True
                self._json(400, {"error": "missing or invalid Content-Length"})
                return
            try:
                if length > self.max_body_bytes:
                    # Don't read a body we've rejected: close the
                    # connection instead of draining it.
                    self.close_connection = True
                    self._json(413, {
                        "error": f"body exceeds {self.max_body_bytes} bytes",
                    })
                    return
                patient = json.loads(self.rfile.read(length) or b"{}")
                row = validate_patient(patient)
            except (ValueError, json.JSONDecodeError) as exc:
                self._json(400, {"error": str(exc)})
                return
            try:
                future = batcher.submit(row[0])
            except Overloaded:
                self._json(503, {"error": "overloaded"})
                return
            except RuntimeError as exc:  # closed during shutdown
                self._json(503, {"error": str(exc)})
                return
            try:
                prob = future.result(timeout=request_timeout_s)
            except FuturesTimeout:
                # Cancel so a still-queued request is dropped at flush time
                # (batcher skips cancelled entries) — otherwise every
                # deadline miss still burns an engine slot computing an
                # answer nobody reads, compounding the overload.
                future.cancel()
                metrics.timeouts_total.inc()
                self._json(504, {
                    "error": f"timed out after {request_timeout_s:g}s",
                })
                return
            except Exception as exc:
                self._json(500, {"error": str(exc)})
                return
            self._json(200, {
                "probability": prob,
                "text": OUTPUT_CONTRACT.format(100.0 * prob),
            })

        def log_message(self, fmt: str, *args) -> None:
            if not quiet:
                super().log_message(fmt, *args)

    return Handler


def make_server(
    params,
    host: str = "127.0.0.1",
    port: int = 8000,
    buckets=DEFAULT_BUCKETS,
    max_batch_size: int | None = None,
    max_wait_ms: float = 5.0,
    max_queue: int = 1024,  # above the top default bucket (512): a full
    # largest-bucket batch must be formable under saturation, or the top
    # bucket's executable only ever runs padded
    warmup: bool = True,
    request_timeout_s: float = 30.0,
    quiet: bool = True,
    say=None,
) -> ServerHandle:
    """Assemble the serving stack around fitted ``params`` and bind the
    listener (not yet serving — call ``serve_forever`` or
    ``start_background``). ``max_batch_size`` defaults to the largest
    bucket so a full batch pads nothing.

    The listener BINDS before warmup runs: a port conflict fails in
    milliseconds instead of after the multi-second compile bill. Warmup
    still completes before this returns (warm standby — the first served
    request never pays a compile); start serving first and call
    ``engine.warmup`` yourself for observable warm=false readiness."""
    # Compile/transfer accounting BEFORE the engine exists, so the param
    # upload and every warmup compile land in the /metrics counters.
    jaxmon.install()
    engine = BucketedPredictEngine(params, buckets=buckets)
    metrics = ServingMetrics(batch_buckets=engine.buckets)
    batcher = MicroBatcher(
        engine,
        max_batch_size=max_batch_size or engine.buckets[-1],
        max_wait_ms=max_wait_ms,
        max_queue=max_queue,
        metrics=metrics,
    )
    handle = ServerHandle(engine, batcher, metrics, None)
    handler = _make_handler(handle, request_timeout_s, quiet)
    try:
        handle.httpd = _Server((host, port), handler)
        # Joinable handler threads: shutdown() must be able to wait for
        # in-flight replies to finish writing (ThreadingHTTPServer's
        # daemon default is excluded from server_close's thread join).
        handle.httpd.daemon_threads = False
        if warmup:
            engine.warmup(say=say)
    except BaseException:
        batcher.close(drain=False, timeout=1.0)
        if handle.httpd is not None:
            # The listener bound before warmup failed: release the port so
            # a caller that catches and retries doesn't hit EADDRINUSE.
            handle.httpd.server_close()
        raise
    return handle
