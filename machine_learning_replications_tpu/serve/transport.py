"""Non-blocking event-loop HTTP transport (``selectors``-based).

The original front end was ``ThreadingHTTPServer``: one OS thread per
connection, each parked in a blocking ``readline``. That model capped the
serving layer at ~130 qps on this hardware — thread creation, stack
memory, and GIL-contended wakeups per connection dominated long before the
engine (2.9 ms single-row, 1.4 M rows/s batched) broke a sweat. This
module replaces it with the standard single-threaded readiness loop
(``selectors.DefaultSelector`` — epoll on Linux):

  * **One loop thread** owns every socket. Reads feed the connection's
    ``protocol.RequestParser``; complete requests are dispatched to the
    application; response bytes queue on a per-connection write buffer
    flushed as the socket accepts them.
  * **Keep-alive pipelining.** A connection's buffered bytes may hold
    several requests; they are served strictly in order, one in flight at
    a time per connection.
  * **Explicit backpressure.** While a connection has a request in flight
    (or unflushed response bytes) the loop STOPS READING its socket: a
    client that floods pipelined requests is throttled by TCP flow
    control instead of ballooning server memory. Read buffers are bounded
    by the protocol caps on top.
  * **Idle reaping.** Connections idle past ``idle_timeout_s`` — including
    slow-loris partials that never complete a request — are swept and
    closed on a periodic tick, so each parked socket costs one fd and a
    small buffer, never a thread.
  * **Thread-safe completion.** Handlers may finish a request from any
    thread (the batcher's flush thread completes ``/predict`` futures):
    ``Responder.send`` marshals the response onto the loop via a wake
    pipe. ``call_later`` schedules deadline callbacks on the loop clock.
  * **Pre-fork sharding.** ``reuse_port=True`` binds with ``SO_REUSEPORT``
    so N worker processes each run their own loop on the same address and
    the kernel load-balances accepted connections across them
    (``cli serve --workers N``).

The application interface is two callbacks (see ``serve.server._App``):
``handle_request(req, responder)`` and
``handle_protocol_error(exc, responder)``. Handlers run ON the loop
thread and must not block — anything slow (device compute, profiler
captures) is handed to another thread and completed through the
responder.

The listener binds in the constructor and is released by
``server_close()`` on every exit path — including a warmup failure before
the loop ever ran — so a crashed worker never wedges its port
(EADDRINUSE) for the replacement that rebinds it.
"""

from __future__ import annotations

import errno
import heapq
import selectors
import socket
import threading
import time
from collections import deque

from machine_learning_replications_tpu.serve import protocol

_READ_CHUNK = 65536


class _Timer:
    __slots__ = ("deadline", "fn", "cancelled")

    def __init__(self, deadline: float, fn) -> None:
        self.deadline = deadline
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        # Lazy deletion: the heap entry stays until its deadline pops, but
        # a cancelled timer's callback never runs and the entry is
        # discarded cheaply at pop time.
        self.cancelled = True


class _Conn:
    __slots__ = (
        "sock", "parser", "out_buf", "in_flight", "close_after_write",
        "last_activity", "partial_since", "mask", "closed", "advancing",
    )

    def __init__(self, sock: socket.socket, parser) -> None:
        self.sock = sock
        self.parser = parser
        self.out_buf = bytearray()
        self.in_flight = False
        self.close_after_write = False
        self.last_activity = time.monotonic()
        self.partial_since: float | None = None
        self.mask = 0  # currently registered selector interest
        self.closed = False
        self.advancing = False


class Responder:
    """Exactly-once reply channel for one dispatched request.

    ``send`` may be called from any thread; the transport marshals the
    bytes onto the loop. ``abort`` closes the connection with NOTHING
    written — the explicit-transport-error reply (a partial or garbled
    body would be the one unforgivable failure mode; a dead socket is
    not). The effective keep-alive of the reply is the request's
    keep-alive AND ``close=False``.
    """

    __slots__ = ("_server", "_conn", "_keep_alive", "_done", "_lock")

    def __init__(self, server: "EventLoopHttpServer", conn: _Conn,
                 keep_alive: bool) -> None:
        self._server = server
        self._conn = conn
        self._keep_alive = keep_alive
        self._done = False
        self._lock = threading.Lock()

    def _claim(self) -> bool:
        with self._lock:
            if self._done:
                return False
            self._done = True
            return True

    def send(
        self,
        code: int,
        body: bytes,
        content_type: str,
        headers: dict[str, str] | None = None,
        request_id: str | None = None,
        close: bool = False,
    ) -> None:
        if not self._claim():
            return
        keep = self._keep_alive and not close
        data = protocol.build_response(
            code, body, content_type, headers=headers,
            request_id=request_id, keep_alive=keep,
        )
        self._server._complete(self._conn, data, close=not keep)

    def send_json(self, code: int, obj, **kw) -> None:
        import json

        self.send(code, json.dumps(obj).encode(), "application/json", **kw)

    def abort(self) -> None:
        """Drop the connection without writing a byte."""
        if not self._claim():
            return
        self._server._post(lambda: self._server._close_conn(self._conn))


class EventLoopHttpServer:
    """Single-threaded non-blocking HTTP server over ``selectors``.

    ``app`` provides ``handle_request(req, responder)`` and
    ``handle_protocol_error(exc, responder)``. The listener binds here;
    run the loop with ``serve_forever()`` (blocking) — stop it with
    ``shutdown()`` from another thread, then ``server_close()``.
    """

    def __init__(
        self,
        address: tuple[str, int],
        app,
        backlog: int = 128,
        idle_timeout_s: float = 5.0,
        max_header_bytes: int = protocol.MAX_HEADER_BYTES,
        max_body_bytes: int = protocol.MAX_BODY_BYTES,
        max_connections: int = 8192,
        reuse_port: bool = False,
    ) -> None:
        self.app = app
        self.idle_timeout_s = float(idle_timeout_s)
        self.max_header_bytes = int(max_header_bytes)
        self.max_body_bytes = int(max_body_bytes)
        self.max_connections = int(max_connections)
        self._sel = selectors.DefaultSelector()
        self._conns: dict[socket.socket, _Conn] = {}
        self._timers: list[tuple[float, int, _Timer]] = []
        self._timer_seq = 0
        self._pending: deque = deque()  # cross-thread posted callables
        self._pending_lock = threading.Lock()
        self._running = False
        self._stop_requested = False
        self._drain_deadline: float | None = None
        self._stopped = threading.Event()
        self._stopped.set()  # not running yet
        self._loop_tid: int | None = None
        self._closed = False

        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if reuse_port:
                # Pre-fork multi-worker mode: every worker binds the same
                # concrete port; the kernel spreads new connections across
                # the listeners.
                lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            lsock.bind(address)
            # Kernel accept backlog stays at 128 (the r6 lesson): bursts
            # must reach the application-level admission decision, not die
            # as silent SYN drops.
            lsock.listen(backlog)
            lsock.setblocking(False)
        except BaseException:
            lsock.close()
            raise
        self._listener: socket.socket | None = lsock
        self.server_address = lsock.getsockname()
        # Wake pipe: cross-thread posts (flush-thread completions) nudge a
        # sleeping select.
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._sel.register(lsock, selectors.EVENT_READ, "accept")

    # -- cross-thread entry points -----------------------------------------

    def _post(self, fn) -> None:
        """Run ``fn`` on the loop thread (soon). Safe from any thread;
        silently dropped once the loop has exited (late completions after
        shutdown must not deadlock their caller)."""
        with self._pending_lock:
            self._pending.append(fn)
            first = len(self._pending) == 1
        if first and threading.get_ident() != self._loop_tid:
            try:
                self._wake_w.send(b"\0")
            except OSError:
                pass

    def call_later(self, delay_s: float, fn) -> _Timer:
        """Schedule ``fn`` on the loop thread after ``delay_s``. Loop
        thread only (the request handlers run there); returns a handle
        whose ``cancel()`` is safe from any thread."""
        t = _Timer(time.monotonic() + delay_s, fn)
        self._timer_seq += 1
        heapq.heappush(self._timers, (t.deadline, self._timer_seq, t))
        return t

    # -- loop --------------------------------------------------------------

    def serve_forever(self) -> None:
        self._running = True
        self._stopped.clear()
        self._loop_tid = threading.get_ident()
        next_sweep = time.monotonic() + min(1.0, self.idle_timeout_s / 2)
        try:
            while True:
                now = time.monotonic()
                if self._stop_requested and self._drained(now):
                    break
                timeout = 0.5
                if self._timers:
                    timeout = min(timeout, max(
                        0.0, self._timers[0][0] - now
                    ))
                timeout = min(timeout, max(0.0, next_sweep - now))
                if self._stop_requested:
                    timeout = min(timeout, 0.05)
                for key, mask in self._sel.select(timeout):
                    kind = key.data
                    if kind == "accept":
                        self._accept()
                    elif kind == "wake":
                        try:
                            self._wake_r.recv(4096)
                        except OSError:
                            pass
                    else:  # a connection
                        conn = kind
                        if mask & selectors.EVENT_READ:
                            self._readable(conn)
                        if mask & selectors.EVENT_WRITE and not conn.closed:
                            self._writable(conn)
                self._run_pending()
                now = time.monotonic()
                self._run_timers(now)
                if now >= next_sweep:
                    self._sweep_idle(now)
                    next_sweep = now + min(1.0, self.idle_timeout_s / 2)
        finally:
            self._running = False
            self._loop_tid = None
            self._teardown()
            self._stopped.set()

    def _drained(self, now: float) -> bool:
        """Shutdown gate: every enqueued response flushed (or the drain
        deadline passed) — an admitted request's reply must not be cut off
        by shutdown racing the write."""
        if self._drain_deadline is not None and now >= self._drain_deadline:
            return True
        return not any(
            c.in_flight or c.out_buf for c in self._conns.values()
        )

    def _run_pending(self) -> None:
        while True:
            with self._pending_lock:
                if not self._pending:
                    return
                fn = self._pending.popleft()
            try:
                fn()
            except Exception:
                pass  # a posted completion must never kill the loop

    def _run_timers(self, now: float) -> None:
        while self._timers and self._timers[0][0] <= now:
            _, _, t = heapq.heappop(self._timers)
            if t.cancelled:
                continue
            try:
                t.fn()
            except Exception:
                pass  # a deadline callback must never kill the loop

    def _sweep_idle(self, now: float) -> None:
        # In-flight requests are exempt: their lifetime is bounded by the
        # application's own request deadline, and reaping them would cut
        # off an admitted request's reply. Everything else — idle
        # keep-alives, drip-fed partials (stamped at first byte), AND
        # clients that stopped reading their response (out_buf making no
        # progress; _flush_writes refreshes last_activity per successful
        # send) — is bounded by idle_timeout_s.
        stale = [
            c for c in self._conns.values()
            if not c.in_flight
            and (
                now - c.last_activity > self.idle_timeout_s
                or (
                    c.partial_since is not None
                    and now - c.partial_since > self.idle_timeout_s
                )
            )
        ]
        for c in stale:
            self._close_conn(c)

    # -- connection lifecycle ----------------------------------------------

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except BlockingIOError:
                return
            except OSError as exc:
                if exc.errno in (errno.EMFILE, errno.ENFILE):
                    # Fd exhaustion: the pending connection stays in the
                    # kernel queue, so the listener would read as ready
                    # on every select and busy-spin the loop. Pause
                    # accepting briefly instead; existing connections
                    # keep being served and closes free fds.
                    lsock = self._listener
                    try:
                        self._sel.unregister(lsock)
                    except (KeyError, ValueError):
                        pass

                    def resume():
                        if self._listener is lsock:
                            try:
                                self._sel.register(
                                    lsock, selectors.EVENT_READ, "accept"
                                )
                            except KeyError:
                                pass
                    self.call_later(0.2, resume)
                return
            if len(self._conns) >= self.max_connections:
                # Fd protection, not admission control (that is the
                # batcher's bounded queue): past the cap the connection is
                # refused at the door.
                sock.close()
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, protocol.RequestParser(
                self.max_header_bytes, self.max_body_bytes
            ))
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)
            conn.mask = selectors.EVENT_READ

    def _close_conn(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        if conn.mask:
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, ValueError):
                pass
            conn.mask = 0
        self._conns.pop(conn.sock, None)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _set_interest(self, conn: _Conn, read: bool, write: bool) -> None:
        """Reconcile the selector mask with the wanted one — a no-op when
        unchanged, so the steady keep-alive path (read interest on for
        the whole connection lifetime) costs zero epoll_ctl calls per
        request."""
        mask = (selectors.EVENT_READ if read else 0) | \
            (selectors.EVENT_WRITE if write else 0)
        if mask == conn.mask:
            return
        if conn.mask == 0:
            self._sel.register(conn.sock, mask, conn)
        elif mask == 0:
            self._sel.unregister(conn.sock)
        else:
            self._sel.modify(conn.sock, mask, conn)
        conn.mask = mask

    def _backpressured(self, conn: _Conn) -> bool:
        """A connection that keeps streaming pipelined bytes while a
        request is in flight gets its read interest dropped once it has
        buffered one full request's worth — TCP flow control then
        throttles the client; reading resumes when the response drains."""
        return conn.parser.buffered >= \
            self.max_header_bytes + self.max_body_bytes

    def _readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(_READ_CHUNK)
        except BlockingIOError:
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)
            return
        conn.last_activity = time.monotonic()
        conn.parser.feed(data)
        if conn.partial_since is None:
            # Stamped AFTER the feed and only when unset: a drip-fed
            # partial keeps its ORIGINAL arrival stamp (refreshing it per
            # recv would let one byte per second park the connection
            # forever), and leftover bytes behind a completed pipelined
            # request get their own stamp on the recv that brought them.
            conn.partial_since = conn.last_activity
        if (conn.in_flight or conn.out_buf) and self._backpressured(conn):
            self._set_interest(conn, read=False, write=bool(conn.out_buf))
            return
        self._advance(conn)

    def _advance(self, conn: _Conn) -> None:
        """Dispatch buffered requests while the connection is free. One
        request in flight per connection: while it is, the socket is not
        read (backpressure) and buffered pipelined requests wait. The
        ``advancing`` guard makes this iterative: a handler that responds
        synchronously re-enters via the write path, and the outer loop —
        not recursion — picks up the next pipelined request (a hostile
        client packing hundreds of requests into one segment must not
        grow the Python stack)."""
        if conn.advancing:
            return
        conn.advancing = True
        try:
            while not (conn.closed or conn.in_flight or conn.out_buf):
                try:
                    req = conn.parser.next_request()
                except protocol.ProtocolError as exc:
                    conn.in_flight = True
                    conn.partial_since = None
                    responder = Responder(self, conn, keep_alive=False)
                    try:
                        self.app.handle_protocol_error(exc, responder)
                    except Exception:
                        responder.abort()
                    continue
                if req is None:
                    if not conn.parser.has_partial():
                        conn.partial_since = None
                    self._set_interest(
                        conn, read=True, write=bool(conn.out_buf)
                    )
                    return
                conn.in_flight = True
                conn.partial_since = None
                # Read interest deliberately stays ON while the request
                # is in flight: a well-behaved keep-alive client sends
                # nothing until the reply, so the common path costs zero
                # epoll reconfiguration; a pipelining flooder is caught
                # by the _backpressured check in _readable.
                responder = Responder(self, conn, keep_alive=req.keep_alive)
                try:
                    self.app.handle_request(req, responder)
                except Exception as exc:  # the loop survives handler bugs
                    import json

                    responder.send(
                        500, json.dumps(
                            {"error": f"{type(exc).__name__}: {exc}"}
                        ).encode(), "application/json", close=True,
                    )
        finally:
            conn.advancing = False

    def _complete(self, conn: _Conn, data: bytes, close: bool) -> None:
        """Queue response bytes for a dispatched request. Called via the
        responder — possibly from another thread, in which case it is
        posted onto the loop."""
        if threading.get_ident() != self._loop_tid and self._loop_tid \
                is not None:
            self._post(lambda: self._complete_on_loop(conn, data, close))
        else:
            self._complete_on_loop(conn, data, close)

    def _complete_on_loop(self, conn: _Conn, data: bytes,
                          close: bool) -> None:
        if conn.closed:
            return
        conn.out_buf += data
        conn.close_after_write = conn.close_after_write or close
        conn.in_flight = False
        conn.last_activity = time.monotonic()
        self._flush_writes(conn)

    def _writable(self, conn: _Conn) -> None:
        self._flush_writes(conn)

    def _flush_writes(self, conn: _Conn) -> None:
        while conn.out_buf:
            try:
                n = conn.sock.send(conn.out_buf)
            except BlockingIOError:
                self._set_interest(
                    conn, read=not self._backpressured(conn), write=True
                )
                return
            except OSError:
                # Client hung up mid-reply: the request was already
                # accounted (trace/SLO finished before the bytes queued) —
                # just drop the connection.
                self._close_conn(conn)
                return
            if n <= 0:
                self._set_interest(
                    conn, read=not self._backpressured(conn), write=True
                )
                return
            del conn.out_buf[:n]
            # Write progress counts as activity: the idle sweep reaps a
            # client that STOPPED reading, not one draining slowly.
            conn.last_activity = time.monotonic()
        conn.last_activity = time.monotonic()
        if conn.close_after_write:
            self._close_conn(conn)
            return
        # Response fully written: serve the next pipelined request, or go
        # back to reading.
        self._set_interest(conn, read=True, write=False)
        self._advance(conn)

    # -- shutdown ----------------------------------------------------------

    def close_listener(self) -> None:
        """Stop accepting; existing connections keep being served."""
        if self._listener is None:
            return
        try:
            self._sel.unregister(self._listener)
        except (KeyError, ValueError):
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        self._listener = None

    def shutdown(self, flush_timeout_s: float = 10.0) -> None:
        """Stop the loop: close the listener, flush every queued response
        (bounded by ``flush_timeout_s``), then exit ``serve_forever``.
        Safe to call from any thread, more than once."""
        def _request_stop():
            self.close_listener()
            self._stop_requested = True
            self._drain_deadline = time.monotonic() + flush_timeout_s
        if not self._running:
            _request_stop()
            return
        self._post(_request_stop)
        if threading.get_ident() != self._loop_tid:
            self._stopped.wait(flush_timeout_s + 5.0)

    def _teardown(self) -> None:
        for conn in list(self._conns.values()):
            self._close_conn(conn)
        self.close_listener()

    def server_close(self) -> None:
        """Release every socket (idempotent). The listener is closed even
        when the loop never ran — the warmup-failure path — so the port is
        immediately rebindable."""
        if self._closed:
            return
        self.shutdown(flush_timeout_s=2.0)
        self._teardown()
        self._closed = True
        try:
            self._sel.close()
        except Exception:
            pass
        try:
            self._wake_r.close()
            self._wake_w.close()
        except OSError:
            pass
