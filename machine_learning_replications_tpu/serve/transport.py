"""Non-blocking event-loop HTTP transport (``selectors``-based).

The original front end was ``ThreadingHTTPServer``: one OS thread per
connection, each parked in a blocking ``readline``. That model capped the
serving layer at ~130 qps on this hardware — thread creation, stack
memory, and GIL-contended wakeups per connection dominated long before the
engine (2.9 ms single-row, 1.4 M rows/s batched) broke a sweat. This
module replaces it with the standard single-threaded readiness loop
(``selectors.DefaultSelector`` — epoll on Linux):

  * **One loop thread** owns every socket (the contract is annotated
    ``@loop_only`` / ``@cross_thread`` — ``contracts.py`` — and
    statically enforced by graftcheck rule ``loop-discipline``,
    docs/ANALYSIS.md). Reads feed the connection's
    ``protocol.RequestParser``; complete requests are dispatched to the
    application; response bytes queue on a per-connection write buffer
    flushed as the socket accepts them.
  * **Keep-alive pipelining.** A connection's buffered bytes may hold
    several requests; they are served strictly in order, one in flight at
    a time per connection.
  * **Explicit backpressure.** While a connection has a request in flight
    (or unflushed response bytes) the loop STOPS READING its socket: a
    client that floods pipelined requests is throttled by TCP flow
    control instead of ballooning server memory. Read buffers are bounded
    by the protocol caps on top.
  * **Idle reaping.** Connections idle past ``idle_timeout_s`` — including
    slow-loris partials that never complete a request — are swept and
    closed on a periodic tick, so each parked socket costs one fd and a
    small buffer, never a thread.
  * **Thread-safe completion.** Handlers may finish a request from any
    thread (the batcher's flush thread completes ``/predict`` futures):
    ``Responder.send`` marshals the response onto the loop via a wake
    pipe. ``call_later`` schedules deadline callbacks on the loop clock.
  * **Pre-fork sharding.** ``reuse_port=True`` binds with ``SO_REUSEPORT``
    so N worker processes each run their own loop on the same address and
    the kernel load-balances accepted connections across them
    (``cli serve --workers N``).

The application interface is two callbacks (see ``serve.server._App``):
``handle_request(req, responder)`` and
``handle_protocol_error(exc, responder)``. Handlers run ON the loop
thread and must not block — anything slow (device compute, profiler
captures) is handed to another thread and completed through the
responder.

**The outbound leg** (``UpstreamPool``): the fleet router proxies every
``/predict`` to a replica, and for three PRs that upstream hop ran on a
small pool of forwarder threads holding blocking ``http.client``
connections — the same thread-per-request architecture whose removal on
the listener side bought 10.1×. ``UpstreamPool`` moves the upstream leg
onto the SAME loop: non-blocking connect, request bytes written with
explicit backpressure (partial sends re-arm write interest), replies
parsed incrementally by ``protocol.ResponseParser``, and per-replica
keep-alive connection reuse with the strict poisoning rules a proxy
needs (a truncated or over-long reply closes the connection rather than
desyncing the next attempt; an idle pooled connection that receives
unsolicited bytes, or EOF, is dropped on the spot). One loop thread owns
every socket end to end — client side and replica side — with no thread
hand-off per request. A reused connection that dies before yielding a
single response byte gets ONE transparent resend on a fresh connection
(the idle-reap race every keep-alive client has); everything else
surfaces as an ``UpstreamError`` for the application's retry policy.

The listener binds in the constructor and is released by
``server_close()`` on every exit path — including a warmup failure before
the loop ever ran — so a crashed worker never wedges its port
(EADDRINUSE) for the replacement that rebinds it.
"""

from __future__ import annotations

import errno
import heapq
import selectors
import socket
import threading
import time
from collections import deque

from machine_learning_replications_tpu.serve import protocol
from machine_learning_replications_tpu.contracts import (
    cross_thread,
    loop_only,
)

_READ_CHUNK = 65536


class _Timer:
    __slots__ = ("deadline", "fn", "cancelled")

    def __init__(self, deadline: float, fn) -> None:
        self.deadline = deadline
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        # Lazy deletion: the heap entry stays until its deadline pops, but
        # a cancelled timer's callback never runs and the entry is
        # discarded cheaply at pop time.
        self.cancelled = True


class _Conn:
    __slots__ = (
        "sock", "parser", "out_buf", "in_flight", "close_after_write",
        "last_activity", "partial_since", "mask", "closed", "advancing",
    )

    def __init__(self, sock: socket.socket, parser) -> None:
        self.sock = sock
        self.parser = parser
        self.out_buf = bytearray()
        self.in_flight = False
        self.close_after_write = False
        self.last_activity = time.monotonic()
        self.partial_since: float | None = None
        self.mask = 0  # currently registered selector interest
        self.closed = False
        self.advancing = False


class Responder:
    """Exactly-once reply channel for one dispatched request.

    ``send`` may be called from any thread; the transport marshals the
    bytes onto the loop. ``abort`` closes the connection with NOTHING
    written — the explicit-transport-error reply (a partial or garbled
    body would be the one unforgivable failure mode; a dead socket is
    not). The effective keep-alive of the reply is the request's
    keep-alive AND ``close=False``.
    """

    __slots__ = ("_server", "_conn", "_keep_alive", "_done", "_lock")

    def __init__(self, server: "EventLoopHttpServer", conn: _Conn,
                 keep_alive: bool) -> None:
        self._server = server
        self._conn = conn
        self._keep_alive = keep_alive
        self._done = False
        self._lock = threading.Lock()

    def _claim(self) -> bool:
        with self._lock:
            if self._done:
                return False
            self._done = True
            return True

    @cross_thread
    def send(
        self,
        code: int,
        body: bytes,
        content_type: str,
        headers: dict[str, str] | None = None,
        request_id: str | None = None,
        close: bool = False,
    ) -> None:
        if not self._claim():
            return
        keep = self._keep_alive and not close
        data = protocol.build_response(
            code, body, content_type, headers=headers,
            request_id=request_id, keep_alive=keep,
        )
        self._server._complete(self._conn, data, close=not keep)

    @cross_thread
    def send_json(self, code: int, obj, **kw) -> None:
        import json

        self.send(code, json.dumps(obj).encode(), "application/json", **kw)

    @cross_thread
    def abort(self) -> None:
        """Drop the connection without writing a byte."""
        if not self._claim():
            return
        self._server._post(lambda: self._server._close_conn(self._conn))


class EventLoopHttpServer:
    """Single-threaded non-blocking HTTP server over ``selectors``.

    ``app`` provides ``handle_request(req, responder)`` and
    ``handle_protocol_error(exc, responder)``. The listener binds here;
    run the loop with ``serve_forever()`` (blocking) — stop it with
    ``shutdown()`` from another thread, then ``server_close()``.
    """

    def __init__(
        self,
        address: tuple[str, int],
        app,
        backlog: int = 128,
        idle_timeout_s: float = 5.0,
        max_header_bytes: int = protocol.MAX_HEADER_BYTES,
        max_body_bytes: int = protocol.MAX_BODY_BYTES,
        max_connections: int = 8192,
        reuse_port: bool = False,
    ) -> None:
        self.app = app
        self.idle_timeout_s = float(idle_timeout_s)
        self.max_header_bytes = int(max_header_bytes)
        self.max_body_bytes = int(max_body_bytes)
        self.max_connections = int(max_connections)
        self._sel = selectors.DefaultSelector()
        self._conns: dict[socket.socket, _Conn] = {}
        self._timers: list[tuple[float, int, _Timer]] = []
        self._timer_seq = 0
        self._pending: deque = deque()  # cross-thread posted callables
        self._pending_lock = threading.Lock()
        self._running = False
        self._stop_requested = False
        self._drain_deadline: float | None = None
        self._stopped = threading.Event()
        self._stopped.set()  # not running yet
        self._loop_tid: int | None = None
        self._closed = False
        self._pools: list["UpstreamPool"] = []

        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if reuse_port:
                # Pre-fork multi-worker mode: every worker binds the same
                # concrete port; the kernel spreads new connections across
                # the listeners.
                lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            lsock.bind(address)
            # Kernel accept backlog stays at 128 (the r6 lesson): bursts
            # must reach the application-level admission decision, not die
            # as silent SYN drops.
            lsock.listen(backlog)
            lsock.setblocking(False)
        except BaseException:
            lsock.close()
            raise
        self._listener: socket.socket | None = lsock
        self.server_address = lsock.getsockname()
        # Wake pipe: cross-thread posts (flush-thread completions) nudge a
        # sleeping select.
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._sel.register(lsock, selectors.EVENT_READ, "accept")

    # -- cross-thread entry points -----------------------------------------

    @cross_thread
    def _post(self, fn) -> None:
        """Run ``fn`` on the loop thread (soon). Safe from any thread;
        silently dropped once the loop has exited (late completions after
        shutdown must not deadlock their caller)."""
        with self._pending_lock:
            self._pending.append(fn)
            first = len(self._pending) == 1
        if first and threading.get_ident() != self._loop_tid:
            try:
                self._wake_w.send(b"\0")
            except OSError:
                pass

    @loop_only
    def call_later(self, delay_s: float, fn) -> _Timer:
        """Schedule ``fn`` on the loop thread after ``delay_s``. Loop
        thread only (the request handlers run there); returns a handle
        whose ``cancel()`` is safe from any thread."""
        t = _Timer(time.monotonic() + delay_s, fn)
        self._timer_seq += 1
        heapq.heappush(self._timers, (t.deadline, self._timer_seq, t))
        return t

    # -- loop --------------------------------------------------------------

    @loop_only
    def serve_forever(self) -> None:
        self._running = True
        self._stopped.clear()
        self._loop_tid = threading.get_ident()
        next_sweep = time.monotonic() + min(1.0, self.idle_timeout_s / 2)
        try:
            while True:
                now = time.monotonic()
                if self._stop_requested and self._drained(now):
                    break
                timeout = 0.5
                if self._timers:
                    timeout = min(timeout, max(
                        0.0, self._timers[0][0] - now
                    ))
                timeout = min(timeout, max(0.0, next_sweep - now))
                if self._stop_requested:
                    timeout = min(timeout, 0.05)
                for key, mask in self._sel.select(timeout):
                    kind = key.data
                    if kind == "accept":
                        self._accept()
                    elif kind == "wake":
                        try:
                            self._wake_r.recv(4096)
                        except OSError:
                            pass
                    elif type(kind) is _Conn:  # an inbound connection
                        conn = kind
                        if mask & selectors.EVENT_READ:
                            self._readable(conn)
                        if mask & selectors.EVENT_WRITE and not conn.closed:
                            self._writable(conn)
                    else:  # an upstream connection (UpstreamPool)
                        kind.pool._on_io(kind, mask)
                self._run_pending()
                now = time.monotonic()
                self._run_timers(now)
                if now >= next_sweep:
                    self._sweep_idle(now)
                    next_sweep = now + min(1.0, self.idle_timeout_s / 2)
        finally:
            self._running = False
            self._loop_tid = None
            self._teardown()
            self._stopped.set()

    @loop_only
    def _drained(self, now: float) -> bool:
        """Shutdown gate: every enqueued response flushed (or the drain
        deadline passed) — an admitted request's reply must not be cut off
        by shutdown racing the write."""
        if self._drain_deadline is not None and now >= self._drain_deadline:
            return True
        return not any(
            c.in_flight or c.out_buf for c in self._conns.values()
        )

    @loop_only
    def _run_pending(self) -> None:
        while True:
            with self._pending_lock:
                if not self._pending:
                    return
                fn = self._pending.popleft()
            try:
                fn()
            except Exception:
                pass  # a posted completion must never kill the loop

    @loop_only
    def _run_timers(self, now: float) -> None:
        while self._timers and self._timers[0][0] <= now:
            _, _, t = heapq.heappop(self._timers)
            if t.cancelled:
                continue
            try:
                t.fn()
            except Exception:
                pass  # a deadline callback must never kill the loop

    @loop_only
    def _sweep_idle(self, now: float) -> None:
        # In-flight requests are exempt: their lifetime is bounded by the
        # application's own request deadline, and reaping them would cut
        # off an admitted request's reply. Everything else — idle
        # keep-alives, drip-fed partials (stamped at first byte), AND
        # clients that stopped reading their response (out_buf making no
        # progress; _flush_writes refreshes last_activity per successful
        # send) — is bounded by idle_timeout_s.
        stale = [
            c for c in self._conns.values()
            if not c.in_flight
            and (
                now - c.last_activity > self.idle_timeout_s
                or (
                    c.partial_since is not None
                    and now - c.partial_since > self.idle_timeout_s
                )
            )
        ]
        for c in stale:
            self._close_conn(c)

    # -- connection lifecycle ----------------------------------------------

    @loop_only
    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except BlockingIOError:
                return
            except OSError as exc:
                if exc.errno in (errno.EMFILE, errno.ENFILE):
                    # Fd exhaustion: the pending connection stays in the
                    # kernel queue, so the listener would read as ready
                    # on every select and busy-spin the loop. Pause
                    # accepting briefly instead; existing connections
                    # keep being served and closes free fds.
                    lsock = self._listener
                    try:
                        self._sel.unregister(lsock)
                    except (KeyError, ValueError):
                        pass

                    def resume():
                        if self._listener is lsock:
                            try:
                                self._sel.register(
                                    lsock, selectors.EVENT_READ, "accept"
                                )
                            except KeyError:
                                pass
                    self.call_later(0.2, resume)
                return
            if len(self._conns) >= self.max_connections:
                # Fd protection, not admission control (that is the
                # batcher's bounded queue): past the cap the connection is
                # refused at the door.
                sock.close()
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, protocol.RequestParser(
                self.max_header_bytes, self.max_body_bytes
            ))
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)
            conn.mask = selectors.EVENT_READ

    @loop_only
    def _close_conn(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        if conn.mask:
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, ValueError):
                pass
            conn.mask = 0
        self._conns.pop(conn.sock, None)
        try:
            conn.sock.close()
        except OSError:
            pass

    @loop_only
    def _set_interest(self, conn: _Conn, read: bool, write: bool) -> None:
        """Reconcile the selector mask with the wanted one — a no-op when
        unchanged, so the steady keep-alive path (read interest on for
        the whole connection lifetime) costs zero epoll_ctl calls per
        request."""
        mask = (selectors.EVENT_READ if read else 0) | \
            (selectors.EVENT_WRITE if write else 0)
        if mask == conn.mask:
            return
        if conn.mask == 0:
            self._sel.register(conn.sock, mask, conn)
        elif mask == 0:
            self._sel.unregister(conn.sock)
        else:
            self._sel.modify(conn.sock, mask, conn)
        conn.mask = mask

    @loop_only
    def _backpressured(self, conn: _Conn) -> bool:
        """A connection that keeps streaming pipelined bytes while a
        request is in flight gets its read interest dropped once it has
        buffered one full request's worth — TCP flow control then
        throttles the client; reading resumes when the response drains."""
        return conn.parser.buffered >= \
            self.max_header_bytes + self.max_body_bytes

    @loop_only
    def _readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(_READ_CHUNK)
        except BlockingIOError:
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)
            return
        conn.last_activity = time.monotonic()
        conn.parser.feed(data)
        if conn.partial_since is None:
            # Stamped AFTER the feed and only when unset: a drip-fed
            # partial keeps its ORIGINAL arrival stamp (refreshing it per
            # recv would let one byte per second park the connection
            # forever), and leftover bytes behind a completed pipelined
            # request get their own stamp on the recv that brought them.
            conn.partial_since = conn.last_activity
        if (conn.in_flight or conn.out_buf) and self._backpressured(conn):
            self._set_interest(conn, read=False, write=bool(conn.out_buf))
            return
        self._advance(conn)

    @loop_only
    def _advance(self, conn: _Conn) -> None:
        """Dispatch buffered requests while the connection is free. One
        request in flight per connection: while it is, the socket is not
        read (backpressure) and buffered pipelined requests wait. The
        ``advancing`` guard makes this iterative: a handler that responds
        synchronously re-enters via the write path, and the outer loop —
        not recursion — picks up the next pipelined request (a hostile
        client packing hundreds of requests into one segment must not
        grow the Python stack)."""
        if conn.advancing:
            return
        conn.advancing = True
        try:
            while not (conn.closed or conn.in_flight or conn.out_buf):
                try:
                    req = conn.parser.next_request()
                except protocol.ProtocolError as exc:
                    conn.in_flight = True
                    conn.partial_since = None
                    responder = Responder(self, conn, keep_alive=False)
                    try:
                        self.app.handle_protocol_error(exc, responder)
                    except Exception:
                        responder.abort()
                    continue
                if req is None:
                    if not conn.parser.has_partial():
                        conn.partial_since = None
                    self._set_interest(
                        conn, read=True, write=bool(conn.out_buf)
                    )
                    return
                conn.in_flight = True
                conn.partial_since = None
                # Read interest deliberately stays ON while the request
                # is in flight: a well-behaved keep-alive client sends
                # nothing until the reply, so the common path costs zero
                # epoll reconfiguration; a pipelining flooder is caught
                # by the _backpressured check in _readable.
                responder = Responder(self, conn, keep_alive=req.keep_alive)
                try:
                    self.app.handle_request(req, responder)
                except Exception as exc:  # the loop survives handler bugs
                    import json

                    responder.send(
                        500, json.dumps(
                            {"error": f"{type(exc).__name__}: {exc}"}
                        ).encode(), "application/json", close=True,
                    )
        finally:
            conn.advancing = False

    def _complete(self, conn: _Conn, data: bytes, close: bool) -> None:
        """Queue response bytes for a dispatched request. Called via the
        responder — possibly from another thread, in which case it is
        posted onto the loop."""
        if threading.get_ident() != self._loop_tid and self._loop_tid \
                is not None:
            self._post(lambda: self._complete_on_loop(conn, data, close))
        else:
            self._complete_on_loop(conn, data, close)

    @loop_only
    def _complete_on_loop(self, conn: _Conn, data: bytes,
                          close: bool) -> None:
        if conn.closed:
            return
        conn.out_buf += data
        conn.close_after_write = conn.close_after_write or close
        conn.in_flight = False
        conn.last_activity = time.monotonic()
        self._flush_writes(conn)

    @loop_only
    def _writable(self, conn: _Conn) -> None:
        self._flush_writes(conn)

    @loop_only
    def _flush_writes(self, conn: _Conn) -> None:
        while conn.out_buf:
            try:
                n = conn.sock.send(conn.out_buf)
            except BlockingIOError:
                self._set_interest(
                    conn, read=not self._backpressured(conn), write=True
                )
                return
            except OSError:
                # Client hung up mid-reply: the request was already
                # accounted (trace/SLO finished before the bytes queued) —
                # just drop the connection.
                self._close_conn(conn)
                return
            if n <= 0:
                self._set_interest(
                    conn, read=not self._backpressured(conn), write=True
                )
                return
            del conn.out_buf[:n]
            # Write progress counts as activity: the idle sweep reaps a
            # client that STOPPED reading, not one draining slowly.
            conn.last_activity = time.monotonic()
        conn.last_activity = time.monotonic()
        if conn.close_after_write:
            self._close_conn(conn)
            return
        # Response fully written: serve the next pipelined request, or go
        # back to reading.
        self._set_interest(conn, read=True, write=False)
        self._advance(conn)

    # -- shutdown ----------------------------------------------------------

    def close_listener(self) -> None:
        """Stop accepting; existing connections keep being served."""
        if self._listener is None:
            return
        try:
            self._sel.unregister(self._listener)
        except (KeyError, ValueError):
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        self._listener = None

    @cross_thread
    def shutdown(self, flush_timeout_s: float = 10.0) -> None:
        """Stop the loop: close the listener, flush every queued response
        (bounded by ``flush_timeout_s``), then exit ``serve_forever``.
        Safe to call from any thread, more than once."""
        def _request_stop():
            self.close_listener()
            self._stop_requested = True
            self._drain_deadline = time.monotonic() + flush_timeout_s
        if not self._running:
            _request_stop()
            return
        self._post(_request_stop)
        if threading.get_ident() != self._loop_tid:
            self._stopped.wait(flush_timeout_s + 5.0)

    def _teardown(self) -> None:
        for conn in list(self._conns.values()):
            self._close_conn(conn)
        for pool in self._pools:
            pool.close_all()
        self.close_listener()

    def server_close(self) -> None:
        """Release every socket (idempotent). The listener is closed even
        when the loop never ran — the warmup-failure path — so the port is
        immediately rebindable."""
        if self._closed:
            return
        self.shutdown(flush_timeout_s=2.0)
        self._teardown()
        self._closed = True
        try:
            self._sel.close()
        except Exception:
            pass
        try:
            self._wake_r.close()
            self._wake_w.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# the outbound leg: loop-owned upstream connections (the router's data plane)
# ---------------------------------------------------------------------------


class UpstreamError(OSError):
    """Transport-level upstream failure: connect refused, reset, reply
    truncated mid-stream, or unparseable. The application's retry policy
    classifies these; none of them carry a usable response."""


class UpstreamTimeout(UpstreamError):
    """The attempt's own deadline expired before a complete reply."""


#: Upstream connection states.
_CONNECTING, _BUSY, _IDLE = "connecting", "busy", "idle"


class _UpstreamConn:
    __slots__ = (
        "pool", "sock", "key", "parser", "out_buf", "state", "attempt",
        "last_activity", "mask", "served", "closed",
    )

    def __init__(self, pool: "UpstreamPool", sock: socket.socket,
                 key) -> None:
        self.pool = pool
        self.sock = sock
        self.key = key
        self.parser = protocol.ResponseParser(
            pool.max_header_bytes, pool.max_body_bytes
        )
        self.out_buf = bytearray()
        self.state = _CONNECTING
        self.attempt: "UpstreamAttempt | None" = None
        self.last_activity = time.monotonic()
        self.mask = 0
        self.served = 0  # responses completed on this connection
        self.closed = False


class UpstreamAttempt:
    """Handle for one in-flight upstream request. ``cancel()`` (loop
    thread) abandons it: the connection closes (a half-spoken exchange
    can never be pooled) and ``on_done`` is not called. ``reused`` says
    whether the attempt rode a pooled keep-alive connection —
    bench/tests assert reuse across retries and hedges with it."""

    __slots__ = ("pool", "key", "addr", "data", "on_done", "timer", "conn",
                 "done", "reused", "resent")

    def __init__(self, pool, key, addr, data, on_done) -> None:
        self.pool = pool
        self.key = key
        self.addr = addr
        self.data = data
        self.on_done = on_done
        self.timer: _Timer | None = None
        self.conn: _UpstreamConn | None = None
        self.done = False
        self.reused = False
        self.resent = False

    @loop_only
    def cancel(self) -> bool:
        """True when this call actually cancelled the attempt — False
        when it had already completed/failed (its ``on_done`` fired or
        is about to). Callers that track per-attempt state (the
        router's per-replica outstanding counts) settle it exactly once
        based on this."""
        if self.done:
            return False
        self.done = True
        if self.timer is not None:
            self.timer.cancel()
        if self.conn is not None:
            self.pool._close_conn(self.conn)
        return True


class UpstreamPool:
    """Per-key keep-alive upstream connections on the server's event
    loop (see the module docstring's "outbound leg"). All entry points
    are loop-thread-only — the application dispatches requests from its
    handlers and receives ``on_done(result)`` back on the loop, where
    ``result`` is a ``protocol.HttpResponse`` or an ``UpstreamError``.

    Pooling contract: a connection returns to the idle pool only when
    the reply said keep-alive, the request was fully written, AND the
    parser is empty (no trailing bytes — a reply that overran its
    ``Content-Length`` has poisoned the framing and the connection
    closes instead). Idle connections keep read interest so a peer
    close is seen immediately, and are reaped past ``idle_timeout_s``.

    ``configure_sock`` (tests) runs on each fresh socket before connect
    — e.g. shrinking ``SO_SNDBUF`` to force the write-backpressure path
    at loopback speeds.
    """

    def __init__(
        self,
        server: EventLoopHttpServer,
        idle_timeout_s: float = 5.0,
        max_header_bytes: int = protocol.MAX_HEADER_BYTES,
        max_body_bytes: int = protocol.MAX_BODY_BYTES,
        max_idle_per_key: int = 4096,
        configure_sock=None,
    ) -> None:
        # max_idle_per_key sizes with the listener's own connection cap,
        # not against memory: at N concurrent proxied requests the pool
        # legitimately holds ~N upstream connections, and a small cap
        # CHURNS under load — completions overflow it, close pooled
        # connections, and the next dispatch burst pays fresh connects
        # (measured: a 128 cap cost ~1.9k reconnects over a 5k-request
        # 500-connection run). An idle fd is cheap; the reaper shrinks
        # the pool when load actually drops.
        self.server = server
        self.idle_timeout_s = float(idle_timeout_s)
        self.max_header_bytes = int(max_header_bytes)
        self.max_body_bytes = int(max_body_bytes)
        self.max_idle_per_key = int(max_idle_per_key)
        self.configure_sock = configure_sock
        self._idle: dict = {}  # key -> deque[_UpstreamConn]
        self._conns: set[_UpstreamConn] = set()
        self.opened_total = 0
        self.reused_total = 0
        self._closed = False
        self._sweep_timer: _Timer | None = None
        server._pools.append(self)

    # -- public API (loop thread) -------------------------------------------

    @loop_only
    def request(self, key, addr: tuple[str, int], data: bytes,
                timeout_s: float, on_done) -> UpstreamAttempt:
        """Send ``data`` (a fully rendered HTTP request) to ``addr``,
        reusing a pooled connection for ``key`` when one is alive.
        ``on_done`` fires exactly once on the loop thread with the
        parsed response or an ``UpstreamError`` — unless the attempt is
        cancelled first."""
        att = UpstreamAttempt(self, key, addr, data, on_done)
        att.timer = self.server.call_later(
            max(0.0, timeout_s), lambda: self._on_timeout(att)
        )
        self._ensure_sweep()
        conn = self._pop_idle(key)
        if conn is not None:
            att.reused = True
            self.reused_total += 1
            self._bind(att, conn)
        else:
            self._open(att)
        return att

    def stats(self) -> dict:
        return {
            "opened_total": self.opened_total,
            "reused_total": self.reused_total,
            "connections": len(self._conns),
            "idle": sum(len(d) for d in self._idle.values()),
        }

    @loop_only
    def close_all(self) -> None:
        """Drop every connection (loop teardown)."""
        self._closed = True
        if self._sweep_timer is not None:
            self._sweep_timer.cancel()
            self._sweep_timer = None
        for conn in list(self._conns):
            self._close_conn(conn)
        self._idle.clear()

    # -- connection management ----------------------------------------------

    @loop_only
    def _pop_idle(self, key) -> _UpstreamConn | None:
        dq = self._idle.get(key)
        while dq:
            conn = dq.pop()  # LIFO: the most recently used is the most
            if not conn.closed:  # likely to still be alive server-side
                return conn
        return None

    @loop_only
    def _open(self, att: UpstreamAttempt) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self.configure_sock is not None:
                self.configure_sock(sock)
            rc = sock.connect_ex(att.addr)
        except OSError as exc:
            sock.close()
            self._fail(att, UpstreamError(f"upstream connect: {exc}"))
            return
        if rc not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
            sock.close()
            self._fail(att, UpstreamError(
                f"upstream connect: {errno.errorcode.get(rc, rc)}"
            ))
            return
        self.opened_total += 1
        conn = _UpstreamConn(self, sock, att.key)
        self._conns.add(conn)
        att.conn = conn
        conn.attempt = att
        conn.out_buf += att.data
        if rc == 0:
            conn.state = _BUSY
            self._flush(conn)
        else:
            self._set_interest(conn, selectors.EVENT_WRITE)

    @loop_only
    def _bind(self, att: UpstreamAttempt, conn: _UpstreamConn) -> None:
        """Ride a pooled idle connection: the parser is empty by the
        pooling contract, so the next bytes read are this reply's."""
        att.conn = conn
        conn.attempt = att
        conn.state = _BUSY
        conn.out_buf += att.data
        conn.last_activity = time.monotonic()
        self._flush(conn)

    @loop_only
    def _close_conn(self, conn: _UpstreamConn) -> None:
        if conn.closed:
            return
        conn.closed = True
        if conn.mask:
            try:
                self.server._sel.unregister(conn.sock)
            except (KeyError, ValueError):
                pass
            conn.mask = 0
        self._conns.discard(conn)
        try:
            conn.sock.close()
        except OSError:
            pass

    @loop_only
    def _set_interest(self, conn: _UpstreamConn, mask: int) -> None:
        if mask == conn.mask:
            return
        sel = self.server._sel
        if conn.mask == 0:
            sel.register(conn.sock, mask, conn)
        elif mask == 0:
            sel.unregister(conn.sock)
        else:
            sel.modify(conn.sock, mask, conn)
        conn.mask = mask

    # -- I/O (loop thread, dispatched by serve_forever) ----------------------

    @loop_only
    def _on_io(self, conn: _UpstreamConn, mask: int) -> None:
        if conn.closed:
            return
        if mask & selectors.EVENT_WRITE:
            if conn.state == _CONNECTING:
                err = conn.sock.getsockopt(
                    socket.SOL_SOCKET, socket.SO_ERROR
                )
                if err:
                    att = conn.attempt
                    self._close_conn(conn)
                    if att is not None:
                        self._fail(att, UpstreamError(
                            "upstream connect: "
                            f"{errno.errorcode.get(err, err)}"
                        ))
                    return
                conn.state = _BUSY
            self._flush(conn)
            if conn.closed:
                return
        if mask & selectors.EVENT_READ:
            self._readable(conn)

    @loop_only
    def _flush(self, conn: _UpstreamConn) -> None:
        """Write pending request bytes with explicit backpressure: a
        partial send re-arms write interest and the loop resumes when
        the replica's socket drains — no thread ever blocks in send.
        Read interest stays on throughout: a server may reply (413, 400)
        from the headers alone, before the body is fully written."""
        while conn.out_buf:
            try:
                n = conn.sock.send(conn.out_buf)
            except BlockingIOError:
                self._set_interest(
                    conn, selectors.EVENT_READ | selectors.EVENT_WRITE
                )
                return
            except OSError as exc:
                self._conn_died(conn, exc)
                return
            if n <= 0:
                self._set_interest(
                    conn, selectors.EVENT_READ | selectors.EVENT_WRITE
                )
                return
            del conn.out_buf[:n]
            conn.last_activity = time.monotonic()
        self._set_interest(conn, selectors.EVENT_READ)

    @loop_only
    def _readable(self, conn: _UpstreamConn) -> None:
        try:
            data = conn.sock.recv(_READ_CHUNK)
        except BlockingIOError:
            return
        except OSError as exc:
            self._conn_died(conn, exc)
            return
        att = conn.attempt
        if not data:  # EOF
            self._conn_died(conn, None)
            return
        conn.last_activity = time.monotonic()
        if att is None:
            # Unsolicited bytes on an idle pooled connection: the peer
            # is desynced or not speaking our framing — never reuse it.
            self._close_conn(conn)
            return
        conn.parser.feed(data)
        try:
            resp = conn.parser.next_response()
        except protocol.ProtocolError as exc:
            self._close_conn(conn)
            self._fail(att, UpstreamError(f"upstream protocol: {exc}"))
            return
        if resp is None:
            return  # reply still in flight
        self._complete_attempt(conn, att, resp)

    @loop_only
    def _complete_attempt(self, conn: _UpstreamConn, att: UpstreamAttempt,
                  resp) -> None:
        conn.served += 1
        conn.attempt = None
        # Pooling contract: keep-alive reply, request fully written,
        # parser empty. Trailing bytes past the declared Content-Length
        # mean the framing is poisoned — close, never desync the next
        # attempt riding this connection.
        if resp.keep_alive and not conn.out_buf \
                and conn.parser.at_start() and not self._closed:
            conn.state = _IDLE
            conn.last_activity = time.monotonic()
            dq = self._idle.setdefault(conn.key, deque())
            dq.append(conn)
            while len(dq) > self.max_idle_per_key:
                self._close_conn(dq.popleft())
            self._set_interest(conn, selectors.EVENT_READ)
        else:
            self._close_conn(conn)
        if att.done:
            return  # cancelled while the reply was in flight
        att.done = True
        if att.timer is not None:
            att.timer.cancel()
        try:
            att.on_done(resp)
        except Exception:
            pass  # a completion callback must never kill the loop

    # -- failure / retry / timeout -------------------------------------------

    @loop_only
    def _conn_died(self, conn: _UpstreamConn, exc) -> None:
        """EOF or a transport error (reset, EPIPE) on an upstream
        connection — the ONE classification point, so the send path and
        the read path agree: with reply bytes already buffered the
        response is truncated and the attempt FAILS (a transparent
        resend would silently execute the request twice after the
        replica already started answering it); with no reply bytes the
        attempt gets its one transparent fresh-connection resend (the
        stale keep-alive race); an idle pooled connection just closes."""
        att = conn.attempt
        mid_reply = not conn.parser.at_start()
        self._close_conn(conn)
        if att is None:
            return  # idle pooled connection reaped by the peer: fine
        if mid_reply:
            self._fail(att, UpstreamError(
                "upstream closed mid-response (truncated reply)"
                + (f": {exc}" if exc is not None else "")
            ))
        elif not att.resent:
            self._resend(att)
        else:
            self._fail(att, UpstreamError(
                "upstream connection closed before reply"
                + (f": {exc}" if exc is not None else "")
            ))

    @loop_only
    def _resend(self, att: UpstreamAttempt) -> None:
        if att.done:
            return
        att.resent = True
        att.conn = None
        self._open(att)

    @loop_only
    def _fail(self, att: UpstreamAttempt, exc: Exception) -> None:
        if att.done:
            return
        att.done = True
        att.conn = None
        if att.timer is not None:
            att.timer.cancel()

        def deliver():
            try:
                att.on_done(exc)
            except Exception:
                pass

        # Posted, not called: a connect that fails synchronously inside
        # ``request()`` must still complete asynchronously — callers
        # capture the returned attempt handle in their completion
        # closure, and an ``on_done`` firing before ``request`` returns
        # would see a half-constructed caller state.
        self.server._post(deliver)

    @loop_only
    def _on_timeout(self, att: UpstreamAttempt) -> None:
        if att.done:
            return
        if att.conn is not None:
            self._close_conn(att.conn)
        att.conn = None
        att.done = True
        try:
            att.on_done(UpstreamTimeout("upstream attempt timed out"))
        except Exception:
            pass

    # -- idle reaping ---------------------------------------------------------

    @loop_only
    def _ensure_sweep(self) -> None:
        if self._sweep_timer is not None or self._closed:
            return
        self._sweep_timer = self.server.call_later(
            min(1.0, self.idle_timeout_s / 2), self._sweep
        )

    @loop_only
    def _sweep(self) -> None:
        self._sweep_timer = None
        now = time.monotonic()
        for dq in self._idle.values():
            stale = [
                c for c in dq
                if c.closed or now - c.last_activity > self.idle_timeout_s
            ]
            for c in stale:
                try:
                    dq.remove(c)
                except ValueError:
                    pass
                self._close_conn(c)
        if self._conns and not self._closed:
            self._ensure_sweep()
