"""Host-side fast path — the other half of adaptive dual-path scoring.

BENCH config 1 is blunt about the single-patient workload: a 17-feature
closed-form numpy scorer answers in 2.0 ms while the device path pays the
accelerator round trip (~4.7 ms colocated, 72.6 ms over the tunnel) —
"a closed form beats ANY accelerator round-trip". The batcher makes it
worse for singles: a lone request also waits out the coalescing window
before its flush even starts. The fix is not a faster device; it is not
going to the device at all when the request is small and the server is
idle.

``HostScorer`` is that scorer. It is deliberately NOT a reimplementation
of the blend math (a second code path would drift from the served model
the first time anyone touches ``models/``): it wraps the SAME
``BucketedPredictEngine`` — the same ``pipeline.contract_rows_to_x64`` →
``pipeline.impute_select`` → ``stacking.predict_proba1_with_members``
composition, the same pre-resolved imputer block fn — pinned to the host
CPU backend via ``jax.default_device`` and pre-traced at a tiny ladder
(default ``1/8``) by ``warmup()``. On a CPU deployment both paths are
literally the same XLA CPU program, so parity is bit-for-bit by
construction (asserted by the serve parity suite); on an accelerator
host the device path keeps the batch throughput while this path answers
singles without the round trip.

``HostPath`` is the execution side: a small pool of daemon worker
threads fed through a bounded hand-off (one slot per worker by default —
queueing here would re-create exactly the latency the path exists to
remove). ``submit`` returns the same ``Future`` shape as
``MicroBatcher.submit`` so the server's in-flight machinery (deadline
timer, done-callback, 504-cancel) is shared verbatim; when every slot is
busy it raises ``HostBusy`` and the caller falls back to the device
path — saturation routes itself. Routing policy lives in
``serve.batcher.PathRouter``; the taken path is exported as
``serve_path_total{path=host|device}`` and annotated on every request
trace (``path``, plus a ``host_compute`` phase in place of the device
path's queue/assembly/compute phases).
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from machine_learning_replications_tpu.obs.registry import REGISTRY
from machine_learning_replications_tpu.serve.engine import (
    BucketedPredictEngine,
)

#: The routing decision, counted per served request at the moment the
#: request is actually dispatched (a HostBusy fallback counts as device).
PATHS = REGISTRY.counter(
    "serve_path_total",
    "Predict requests by scoring path: host = synchronous CPU fast path "
    "(no batching delay, no accelerator round trip), device = the "
    "micro-batched bucketed engine.",
    labels=("path",),
)
# Materialize both series at import so the first scrape shows the split
# even before traffic (and a zero host count is visible, not absent).
PATHS.labels(path="host")
PATHS.labels(path="device")

#: Host-path computes that failed and were transparently resubmitted
#: through the supervised device path (serve.server._InFlight.on_done):
#: the fallback keeps engine faults flowing into the breaker/watchdog
#: machinery instead of surfacing raw host 500s.
HOST_FALLBACKS = REGISTRY.counter(
    "serve_host_fallback_total",
    "Host fast-path failures retried once through the device path "
    "before any client-visible error.",
)
HOST_FALLBACKS.get()

DEFAULT_HOST_BUCKETS = (1, 8)


class HostBusy(RuntimeError):
    """Every host-path slot is occupied — the caller should take the
    device path (this is load-adaptive routing, not an error)."""


class HostScorer:
    """The pre-traced CPU scorer: a ``BucketedPredictEngine`` pinned to
    the host CPU backend, sharing every line of the device path's math.

    ``quality`` is the same feed object the device engine holds, so
    host-scored rows reach the drift monitor exactly like device-scored
    ones. All calls run under ``jax.default_device(cpu)`` — on CPU-only
    installs that is a no-op; on accelerator hosts it keeps the params
    copy and every compile on the host backend.
    """

    def __init__(
        self,
        params,
        buckets=DEFAULT_HOST_BUCKETS,
        quality=None,
        aot=None,
    ) -> None:
        import jax

        self._cpu = jax.devices("cpu")[0]
        with jax.default_device(self._cpu):
            # ``aot`` is the checkpoint bundle's CPU-backend view
            # (persist.aot, docs/AOT.md): the fast path's tiny ladder
            # restores published executables instead of tracing, same
            # fails-open fallback as the device engine.
            self._engine = BucketedPredictEngine(
                params, buckets=buckets, quality=quality,
                aot=aot, aot_role="host",
            )

    @property
    def warm(self) -> bool:
        return self._engine.warm

    @property
    def buckets(self):
        return self._engine.buckets

    @property
    def trace_counts(self):
        return self._engine.trace_counts

    def warmup(self, say=None):
        import jax

        with jax.default_device(self._cpu):
            return self._engine.warmup(say=say)

    def predict(self, X: np.ndarray) -> np.ndarray:
        import jax

        with jax.default_device(self._cpu):
            return self._engine.predict(X)


class _HostPending:
    __slots__ = ("row", "future", "trace", "t_enqueue", "t_enqueue_perf")

    def __init__(self, row, future, trace) -> None:
        self.row = row
        self.future = future
        self.trace = trace
        self.t_enqueue = time.monotonic()
        self.t_enqueue_perf = time.perf_counter()


class HostPath:
    """Bounded worker pool executing single-row host-path predictions.

    ``submit`` raises ``HostBusy`` the instant all ``max_inflight`` slots
    (default: one per worker) are taken — the host path never queues
    meaningfully, because a queued host request would pay exactly the
    wait the path exists to avoid while the device path would have
    batched it for free. ``metrics`` (a ``ServingMetrics``) receives the
    same latency/queue-wait observations the batcher records, so the
    serving histograms describe all traffic regardless of path.
    """

    def __init__(
        self,
        scorer,
        workers: int = 1,
        max_inflight: int | None = None,
        metrics=None,
    ) -> None:
        if workers < 1:
            raise ValueError("workers must be >= 1")
        self._scorer = scorer
        self._metrics = metrics
        self._max_inflight = int(max_inflight or workers)
        if self._max_inflight < workers:
            raise ValueError("max_inflight must be >= workers")
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q: deque[_HostPending | None] = deque()
        self._inflight = 0
        self._closed = False
        self._threads = [
            threading.Thread(
                target=self._loop, name=f"host-path-{i}", daemon=True
            )
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()

    # -- producer ----------------------------------------------------------

    @property
    def scorer(self):
        return self._scorer

    def swap_scorer(self, scorer) -> None:
        """Rolling-deploy promotion for the fast path: replace the scorer
        with an already-warm one. A bare reference swap — workers read
        ``self._scorer`` once per compute, so in-flight host scores
        finish on the old scorer and the next submission runs the new
        one, mirroring ``SupervisedEngine.swap_engine``."""
        self._scorer = scorer

    @property
    def available(self) -> bool:
        """Router gate: open for submissions and backed by a warm scorer
        (a cold host path would make the first routed single pay a
        compile — worse than the batching delay it was avoiding)."""
        return not self._closed and getattr(self._scorer, "warm", True)

    @property
    def saturated(self) -> bool:
        with self._lock:
            return self._inflight >= self._max_inflight

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def submit(self, row: np.ndarray, trace=None):
        """Enqueue one contract-order row for host scoring; returns a
        ``Future`` resolving to its probability (float). Raises
        ``HostBusy`` when every slot is taken and ``RuntimeError`` after
        ``close``."""
        from concurrent.futures import Future

        row = np.asarray(row, np.float64).ravel()
        with self._lock:
            if self._closed:
                raise RuntimeError("host path is closed")
            if self._inflight >= self._max_inflight:
                raise HostBusy(
                    f"all {self._max_inflight} host-path slots busy"
                )
            self._inflight += 1
            p = _HostPending(row, Future(), trace)
            self._q.append(p)
            self._cv.notify()
        return p.future

    # -- worker ------------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._lock:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q:
                    return  # closed and drained
                p = self._q.popleft()
            if p is None:
                return
            self._run_one(p)

    def _run_one(self, p: _HostPending) -> None:
        t_claim = time.perf_counter()
        t_claim_mono = time.monotonic()
        try:
            # Claimed → can no longer be cancelled by the deadline timer;
            # a cancelled entry is dropped here unserved, same as the
            # batcher's flush-time cancel sweep.
            if not p.future.set_running_or_notify_cancel():
                return
            # ONE read of the swappable scorer reference: the version
            # noted on the trace below must belong to the scorer that
            # produced the bits, even when swap_scorer lands mid-call.
            scorer = self._scorer
            try:
                prob = float(scorer.predict(p.row[None, :])[0])
            except BaseException as exc:
                # No error counter here: the server retries a failed host
                # compute through the device path, whose flush accounts
                # the terminal outcome — counting both would double-book
                # one request.
                self._stamp(p, t_claim, time.perf_counter())
                p.future.set_exception(exc)
                return
            t_done = time.perf_counter()
            self._stamp(p, t_claim, t_done)
            version = getattr(scorer, "model_version", None)
            if version is not None and p.trace is not None:
                p.trace.note(model_version=version)
            if self._metrics is not None:
                now = time.monotonic()
                self._metrics.queue_wait.observe(
                    t_claim_mono - p.t_enqueue
                )
                self._metrics.latency.observe(now - p.t_enqueue)
            p.future.set_result(prob)
        finally:
            with self._lock:
                self._inflight -= 1

    def _stamp(self, p: _HostPending, t_claim: float, t_done: float) -> None:
        """Request-trace phases for the host path: queue_wait is the slot
        wait (parse end → worker claim — near zero unless racing another
        host request), host_compute is the synchronous scorer call. The
        respond phase starts where host_compute ends (``serve.server``),
        so the phases partition the request like the device path's do."""
        if p.trace is None:
            return
        q0 = p.trace.phase_end("parse", p.t_enqueue_perf)
        p.trace.add_phases(
            {"queue_wait": (q0, t_claim), "host_compute": (t_claim, t_done)},
        )

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: float | None = 5.0) -> None:
        """Stop admission, let in-flight work finish, join the workers.
        Anything still queued unclaimed is failed fast."""
        with self._lock:
            self._closed = True
            while self._q:
                p = self._q.pop()
                self._inflight -= 1
                if p.future.set_running_or_notify_cancel():
                    p.future.set_exception(
                        RuntimeError("server shutting down")
                    )
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout)
