"""Warm bucketed-compile predict engine — the device half of the serving layer.

``jax.jit`` specializes on shape, so a naive server compiles a fresh XLA
program for every distinct batch size its batcher happens to flush — an
unbounded compile cache and multi-second tail latencies whenever traffic
finds a new size. The engine instead pads every batch up to a fixed ladder
of bucket sizes (Clipper/TF-Serving practice; default
``1/8/32/64/128/256/512``): the jit cache is bounded at one executable
per bucket, and ``warmup()`` pays every compile at startup so the first
real request never does.

Padding is row-replication (``np.pad`` edge mode). Every predict path the
engine serves — stacking members, bare GBDT, the full pipeline — is a pure
per-row map, so pad rows cannot perturb real rows; they cost device FLOPs,
which ``serve.metrics`` accounts as ``padding_waste``.

**Batch shaping.** Padding waste is not free: the r11 bench campaign
measured mid-size flushes (65–200 rows) padding into the coarse ladder's
512 bucket and burning up to 6× the needed compute. Two fixes compose
here. The default ladder is finer (seven buckets instead of four — still
a bounded, warmable cache), and ``plan_batch`` decomposes each flush into
the cheapest covering sequence of ladder buckets instead of always
padding to one: 65 rows run as a full 64-bucket call plus a 1-bucket
call (zero pad rows) rather than padding 63 rows into 128. The plan is
chosen by a small memoized DP minimizing ``padded_rows +
split_penalty_rows × extra_dispatches`` — each extra compiled call costs
real dispatch overhead (≈2 ms single-row on the bench CPU ≈ 24 rows of
bucket-512 compute, the default penalty), so tiny batches still take one
padded bucket and the split only wins when it saves real work. Every
chunk is a ladder bucket, so the one-compile-per-bucket bound is
untouched.

The engine accepts the same three param families as ``cli.py predict``
(SURVEY.md §2.3 parity oracle):

  * ``stacking.StackingParams`` — the imported-pickle / bare-ensemble case;
    rows are the contractual 17-variable patient vector.
  * ``tree.TreeEnsembleParams`` — ``sweep --save`` checkpoints.
  * ``pipeline.PipelineParams`` — full-pipeline checkpoints; 17-variable
    rows are embedded at their schema positions in a NaN-padded 64-wide
    row and routed through ``pipeline.contract_rows_to_x64`` →
    ``pipeline.impute_select`` → ``stacking.predict_proba1`` — the same
    composition ``pipeline_predict_proba1_contract`` (the CLI path) runs,
    with the ensemble pass jitted here for the per-bucket compile bound —
    so served probabilities match ``predict`` bit-for-bit.
"""

from __future__ import annotations

import bisect
import functools
import time
from typing import Sequence

import numpy as np

from machine_learning_replications_tpu.obs import jaxmon, journal
from machine_learning_replications_tpu.obs.registry import REGISTRY
from machine_learning_replications_tpu.resilience import faults

DEFAULT_BUCKETS = (1, 8, 32, 64, 128, 256, 512)

#: Per-bucket warmup wall seconds, per scoring path (device engine vs the
#: host fast-path scorer). Set every warmup — whether the bucket compiled
#: or restored an AOT executable — so the deploy controller and the
#: autoscaler can read warmup cost off a scrape instead of parsing
#: stderr (the old ad-hoc ``say`` prints).
WARMUP_SECONDS = REGISTRY.gauge(
    "serve_warmup_seconds",
    "Engine warmup wall seconds per bucket (labels: path=device|host, "
    "bucket).",
    labels=("path", "bucket"),
)
#: Per-bucket AOT executable restore wall seconds (deserialize+load of
#: the published blob — the cost that replaces the bucket's XLA compile
#: when a checkpoint ships AOT artifacts, docs/AOT.md).
AOT_RESTORE_SECONDS = REGISTRY.gauge(
    "serve_aot_restore_seconds",
    "AOT executable restore wall seconds per bucket (labels: "
    "path=device|host, bucket).",
    labels=("path", "bucket"),
)
#: AOT restore failures that fell open to tracing, by reason. An entry
#: here is a replica that started CORRECTLY but slowly — the fails-open
#: contract (docs/AOT.md "Fallback semantics").
AOT_FALLBACKS = REGISTRY.counter(
    "serve_aot_fallback_total",
    "AOT restore failures that fell back to tracing, by reason "
    "(fingerprint_mismatch, missing_backend, family_mismatch, "
    "missing_bucket, deserialize_error, exec_error, parity_mismatch, "
    "manifest_unreadable).",
    labels=("reason",),
)
# Every documented reason gets a zero-baseline series at import: an
# alert over any of them must distinguish "never happened" (explicit 0)
# from a scrape that simply predates the first firing.
for _reason in (
    "fingerprint_mismatch", "missing_backend", "family_mismatch",
    "missing_bucket", "deserialize_error", "exec_error",
    "parity_mismatch", "manifest_unreadable",
):
    AOT_FALLBACKS.labels(reason=_reason)

#: Extra-dispatch cost of one more sub-batch, in padded-row equivalents:
#: a single-row engine call measured ~2.1 ms on the r11 bench CPU while
#: the 512 bucket ran ~87 µs/row, so one dispatch ≈ 24 rows of compute.
#: A split must save at least this much padding per extra chunk to win.
DEFAULT_SPLIT_PENALTY_ROWS = 24

#: Sub-batches per flush are capped: each chunk is its own device call,
#: and an unbounded decomposition (worst case: a run of 1-buckets) would
#: trade padding waste for dispatch-overhead waste.
DEFAULT_MAX_SPLIT = 4


@functools.lru_cache(maxsize=4096)
def _tail_plan(
    n: int, buckets: tuple[int, ...], penalty: int, max_chunks: int
) -> tuple[int, ...]:
    """Cheapest covering decomposition of ``n`` rows (0 < n ≤ top bucket)
    into ladder buckets: minimizes ``padded_rows + penalty × (chunks−1)``
    under the chunk cap, ties broken toward fewer chunks. Full chunks come
    first; only the final, covering chunk can pad."""
    cover = buckets[bisect.bisect_left(buckets, n)]
    best_plan = (cover,)
    best_cost = cover - n
    if max_chunks > 1:
        for b in reversed(buckets):
            if b >= n:
                continue
            sub = _tail_plan(n - b, buckets, penalty, max_chunks - 1)
            cost = (b + sum(sub) - n) + penalty * len(sub)
            if cost < best_cost or (
                cost == best_cost and 1 + len(sub) < len(best_plan)
            ):
                best_plan = (b,) + sub
                best_cost = cost
    return best_plan


def family_core(params):
    """``(family, core, n_outputs)`` — the pure per-family jit core the
    engine compiles once per bucket: ``core(arg, X)`` where ``arg`` is
    the ensemble for pipeline checkpoints and the params pytree
    otherwise. The AOT exporter (``persist.aot``) lowers exactly THIS
    function at the engine's shapes, so a published executable is
    bit-identical to the one warmup would trace."""
    from machine_learning_replications_tpu.models import (
        pipeline, stacking, tree,
    )

    if isinstance(params, pipeline.PipelineParams):
        return (
            "pipeline",
            lambda ens, X: stacking.predict_proba1_with_members(ens, X),
            2,
        )
    if isinstance(params, tree.TreeEnsembleParams):
        return "tree", lambda p, X: tree.predict_proba1(p, X), 1
    if isinstance(params, stacking.StackingParams):
        return (
            "stacking",
            lambda p, X: stacking.predict_proba1_with_members(p, X),
            2,
        )
    raise TypeError(
        f"cannot serve params of type {type(params).__name__}; "
        "expected PipelineParams, TreeEnsembleParams, or StackingParams"
    )


def oracle_proba1(params, rows) -> np.ndarray:
    """The eager single-request composition — the exact route
    ``cli predict`` takes — as the parity oracle for deploy candidates
    (``serve.server._verify_parity``) and AOT-restored executables
    (``BucketedPredictEngine.warmup``)."""
    from machine_learning_replications_tpu.models import (
        pipeline, stacking, tree,
    )

    if isinstance(params, pipeline.PipelineParams):
        out = pipeline.pipeline_predict_proba1_contract(params, rows)
    elif isinstance(params, tree.TreeEnsembleParams):
        out = tree.predict_proba1(params, rows)
    else:
        out = stacking.predict_proba1(params, rows)
    return np.asarray(out, np.float64)


def parity_tolerance() -> tuple[float, float]:
    """``(rtol, atol)`` for engine-vs-eager-oracle parity: XLA fusion may
    regroup float ops vs op-by-op dispatch, so the bound is
    precision-dependent — 1e-12 relative under x64 (the serve parity
    suite's documented bound), 1e-5 under default float32 (fusion noise
    ~1e-7 relative there; wrong weights differ at 1e-1)."""
    import jax

    return (1e-12, 1e-15) if jax.config.jax_enable_x64 else (1e-5, 1e-8)


class BucketedPredictEngine:
    """Compiled batched predict with a bounded, warm bucket ladder.

    ``trace_counts`` maps bucket size → number of times the engine's jitted
    core was *traced* at that size (tracing happens exactly once per XLA
    compile), so tests can assert the compile-cache bound directly instead
    of inferring it from timing.

    ``aot`` (a ``persist.aot.AotView``, docs/AOT.md) lets ``warmup``
    restore published per-bucket executables instead of tracing them —
    the compile wall becomes a deserialize. Restores are journaled and
    fail OPEN: any per-bucket failure (fingerprint mismatch, corrupt
    blob, a restored executable that disagrees with the eager oracle)
    falls back to tracing that bucket, so a bad artifact can cost time,
    never correctness or availability. ``aot_role`` labels the engine's
    telemetry (``device`` for the batch engine, ``host`` for the
    fast-path scorer).
    """

    def __init__(
        self,
        params,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        quality=None,
        split_penalty_rows: int = DEFAULT_SPLIT_PENALTY_ROWS,
        max_split: int = DEFAULT_MAX_SPLIT,
        aot=None,
        aot_role: str = "device",
    ) -> None:
        import jax
        import jax.tree_util as jtu

        from machine_learning_replications_tpu.models import (
            pipeline, stacking, tree,
        )

        buckets = sorted({int(b) for b in buckets})
        if not buckets or buckets[0] < 1:
            raise ValueError(f"bucket ladder must be positive ints, got {buckets!r}")
        if split_penalty_rows < 0 or max_split < 1:
            raise ValueError(
                "need split_penalty_rows >= 0 and max_split >= 1"
            )
        self.buckets = tuple(buckets)
        self.split_penalty_rows = int(split_penalty_rows)
        self.max_split = int(max_split)
        self.params = params
        self.trace_counts: dict[int, int] = {}
        self.warm = False
        self.n_features = 17  # the predict_hf.py:5-27 contract width
        self.aot = aot
        self.aot_role = str(aot_role)
        # bucket -> AOT-restored executable; populated by warmup, read by
        # _run_core on every call (a bucket not in here runs the jitted
        # trace path — the two are bit-identical by the export contract).
        self._aot_execs: dict[int, object] = {}
        # obs.quality.QualityMonitor (or None): every predict() feeds it
        # the batch's REAL rows in the model's input space — post-impute
        # post-select for the pipeline route, the contract rows themselves
        # for bare ensembles — plus blended and per-member probabilities.
        # Warmup bypasses predict(), so synthetic warmup rows never touch
        # the drift window.
        self.quality = quality

        self.family, base_core, n_out = family_core(params)
        # Params ride as jit ARGUMENTS (not closure constants — numpy
        # constants cannot be fancy-indexed by tracers inside the staged
        # program), device_put ONCE here so the ensemble is not re-uploaded
        # host-to-device on every flushed batch. Same shapes and dtypes
        # every call, so the executable cache still keys only on the batch
        # shape — one compile per bucket. The obs wrapper accounts the
        # upload's bytes (jax_transfer_bytes_total{direction="h2d"}).
        dparams = jaxmon.device_put(params)

        def core(a, X):
            # Executes at trace time only; AOT-restored executables never
            # trace, so trace_counts stays a pure compile count.
            self._note_trace(int(X.shape[0]))
            return base_core(a, X)

        self._jit_core = jax.jit(core)
        if isinstance(params, pipeline.PipelineParams):
            # ... except the support mask, which stays host-resident:
            # impute_select np.where's it per call, and a device mask
            # would cost a blocking device-to-host sync per flushed batch.
            dparams = dparams.replace(
                support_mask=np.asarray(params.support_mask)
            )
            # Contract rows are all-finite (validate_patient), so every
            # served x64 batch misses exactly the non-schema columns:
            # resolve the imputer's pattern-specialised block fn ONCE —
            # resolution reduces the donor NaN mask on device and blocks
            # on its fetch, a cost that must not recur per flushed batch
            # (it would dominate the max_wait_ms budget on remote
            # backends). Shared with the bulk-scoring pipeline.
            contract_block_fn = pipeline.resolve_contract_block_fn(params)
            # Full-pipeline route: host-orchestrated imputation feeding
            # the jitted stacked-probability core. One imputer compile +
            # one core compile per bucket. The core also returns the
            # member meta-features: they are intermediates of the blended
            # probability anyway, and the quality monitor's ensemble-
            # agreement signal needs them per batch.
            core_arg = dparams.ensemble

            def impl(X17: np.ndarray):
                x64 = pipeline.contract_rows_to_x64(params, X17)
                # NaN in a 17-var position (possible only for direct
                # predict() callers — the HTTP path rejects it) widens
                # the pattern past the pre-resolved fn: fall back to
                # per-call resolution rather than serve an unimputed NaN.
                fn = None if np.isnan(X17).any() else contract_block_fn
                X17sel = pipeline.impute_select(dparams, x64, block_fn=fn)
                p1, members = self._run_core(X17sel)
                # The quality rows are the POST-impute post-select matrix —
                # the space the reference profile was built over.
                return p1, members, X17sel

        elif isinstance(params, tree.TreeEnsembleParams):
            # Bare GBDT (`sweep --save`): one jitted call, no member
            # outputs to disagree over.
            core_arg = dparams

            def impl(X):
                return self._run_core(X), None, X

        else:
            # stacking.StackingParams: rows are already the member
            # ensemble's 17-column input.
            core_arg = dparams

            def impl(X):
                p1, members = self._run_core(X)
                return p1, members, X

        self._impl = impl
        self._core_arg = core_arg
        # Call-tree templates for AOT executable restore: structure only
        # (shapes are per-blob), reconstructed from the LIVE params so a
        # serialized executable can only load against a structurally
        # matching checkpoint (persist.aot.AotView.load_exec).
        self._aot_in_tree = jtu.tree_structure(
            ((core_arg, np.zeros(1)), {})
        )
        self._aot_out_tree = jtu.tree_structure(
            (np.zeros(1), np.zeros(1)) if n_out == 2 else np.zeros(1)
        )

    def _run_core(self, X):
        """One per-bucket core call: the AOT-restored executable when the
        batch's bucket has one, the jitted trace path otherwise — the two
        are bit-identical by the export contract (docs/AOT.md)."""
        fn = self._aot_execs.get(int(X.shape[0]))
        if fn is not None:
            return fn(self._core_arg, X)
        return self._jit_core(self._core_arg, X)

    def _note_trace(self, rows: int) -> None:
        # Executes at trace time only (the body is staged out afterwards),
        # so each increment corresponds to exactly one XLA compile.
        self.trace_counts[rows] = self.trace_counts.get(rows, 0) + 1

    def compile_count(self) -> int:
        """Total engine compiles so far. The batcher samples this around
        each flush: a flush that moves it paid a cold bucket compile —
        the attribution request traces carry as ``cold_compile``."""
        return sum(self.trace_counts.values())

    def bucket_for(self, n: int) -> int:
        """Smallest ladder bucket holding ``n`` rows (the largest bucket
        for anything bigger — ``predict`` chunks such batches)."""
        i = bisect.bisect_left(self.buckets, n)
        return self.buckets[min(i, len(self.buckets) - 1)]

    def plan_batch(self, n: int) -> tuple[int, ...]:
        """The bucket sequence an ``n``-row batch will actually run as:
        whole top-bucket chunks for anything oversize, then the cheapest
        covering decomposition of the remainder (module docstring "Batch
        shaping"). Deterministic, so the batcher can account padding and
        annotate traces with the exact shape ``predict`` executes.
        ``sum(plan) − n`` is the flush's padded-row count; only the final
        chunk pads."""
        if n <= 0:
            return ()
        top = self.buckets[-1]
        q, r = divmod(n, top)
        plan = (top,) * q
        if r:
            plan += _tail_plan(
                r, self.buckets, self.split_penalty_rows, self.max_split
            )
        return plan

    def predict(self, X: np.ndarray) -> np.ndarray:
        """P(class 1) for ``X[n, 17]`` contract-order rows; any ``n`` ≥ 0.

        The batch runs as the ``plan_batch`` chunk sequence (order
        preserving — row i of the input is row i of the result): batches
        beyond the largest bucket become sequential top-bucket chunks,
        and mid-size remainders split into best-fit sub-batches instead
        of padding into one oversized bucket. Every chunk is a ladder
        bucket, so the compile cache stays bounded no matter what the
        batcher (or a caller) hands in.
        """
        X = np.asarray(X, np.float64)
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"expected [n, {self.n_features}] contract rows, got "
                f"{X.shape}"
            )
        n = X.shape[0]
        if n == 0:
            return np.empty((0,), np.float64)
        # Faultpoint: the device-compute injection site. A raise here is a
        # failing compute (feeds the supervisor's breaker streak); a delay
        # is a wedged device — it burns inside the supervisor's watchdog
        # window, the canonical chaos drill. Free when nothing is armed.
        faults.fire("engine.compute")
        feed = self.quality is not None
        probs_parts: list[np.ndarray] = []
        member_parts: list[np.ndarray] | None = [] if feed else None
        qrow_parts: list[np.ndarray] = []
        off = 0
        for b in self.plan_batch(n):
            take = min(b, n - off)
            Xc = X[off:off + take]
            if take < b:
                Xc = np.pad(Xc, ((0, b - take), (0, 0)), mode="edge")
            p1, members, qrows = self._impl(Xc)
            probs_parts.append(np.asarray(p1, np.float64)[:take])
            if feed:
                # Quality-feed inputs fetched ONLY when a monitor is
                # attached: on the pipeline route qrows/members are
                # device arrays, and an unconditional np.asarray would
                # bill every quality-off flush a device→host transfer.
                # Pad rows sliced off BEFORE anything downstream sees
                # them: edge-replicated rows would double-weight the
                # last real patient in the drift window.
                qrow_parts.append(np.asarray(qrows)[:take])
                if members is None:
                    member_parts = None
                elif member_parts is not None:
                    member_parts.append(
                        np.asarray(members, np.float64)[:take]
                    )
            off += take
        probs = (
            probs_parts[0] if len(probs_parts) == 1
            else np.concatenate(probs_parts)
        )
        if self.quality is not None:
            try:
                self.quality.observe_batch(
                    qrow_parts[0] if len(qrow_parts) == 1
                    else np.concatenate(qrow_parts),
                    probs,
                    None if member_parts is None
                    else (
                        member_parts[0] if len(member_parts) == 1
                        else np.concatenate(member_parts)
                    ),
                )
            except Exception as exc:
                # Telemetry must never take serving down: the prediction
                # already succeeded, so a monitor failure (mis-sized
                # profile, NaN rows from a direct predict() caller)
                # quarantines the feed — journaled once — instead of
                # failing every batch forever. disable() makes the
                # quarantine visible on /healthz and /debug/quality, which
                # keep their reference to the monitor; frozen stats
                # presented as live 'ok' would be a silent monitoring gap.
                msg = f"{type(exc).__name__}: {exc}"
                journal.event("quality_feed_disabled", error=msg)
                disable = getattr(self.quality, "disable", None)
                if disable is not None:
                    disable(f"feed quarantined: {msg}")
                self.quality = None
        return probs

    def warmup(self, say=None) -> dict[int, float]:
        """Make every ladder bucket hot up front (example-patient rows,
        each blocked to completion); returns per-bucket wall seconds.
        After warmup, steady-state traffic never waits on a compile.

        With an ``aot`` view attached, published executables restore
        FIRST (``docs/AOT.md``): each bucket then runs the deserialized
        program instead of tracing, and its first output is probed
        against the eager oracle (``oracle_proba1``) before the engine
        may be marked warm — a restored executable that cannot reproduce
        the oracle is discarded, journaled (``aot_fallback``), and the
        bucket re-traces. Per-bucket timings flow through the shared
        ``journal.stage_scope`` path and the ``serve_warmup_seconds`` /
        ``serve_aot_restore_seconds`` gauges (``say`` is kept for
        interface compatibility; timing no longer prints through it)."""
        import jax

        from machine_learning_replications_tpu.data.examples import patient_row

        # Faultpoint: a raise here makes a supervised restart attempt fail
        # (the factory re-warms), exercising the bounded-backoff retry.
        faults.fire("engine.warmup")
        row = patient_row()
        if self.aot is not None and not self._aot_execs:
            self._restore_aot()
        oracle_p = (
            float(oracle_proba1(self.params, row)[0])
            if self._aot_execs else None
        )
        times: dict[int, float] = {}
        for b in self.buckets:
            times[b] = self._warm_bucket(jax, b, row, oracle_p)
        self.warm = True
        return times

    def _restore_aot(self) -> None:
        """Load the bundle's per-bucket executables (fails open per
        bucket: a failed load journals + counts a fallback and leaves the
        bucket on the trace path). The bundle-level gate — platform
        fingerprint, model family, backend coverage — runs once."""
        try:
            bad = self.aot.unusable_reason(self.family)
        except Exception as exc:  # a torn manifest must not kill warmup
            bad = (
                "manifest_unreadable", f"{type(exc).__name__}: {exc}",
            )
        if bad is not None:
            # (code, detail) from AotView; a bare string from a legacy
            # view reads as the platform-skew bucket.
            code, detail = (
                bad if isinstance(bad, tuple)
                else ("fingerprint_mismatch", bad)
            )
            self._aot_fallback(code, detail=detail)
            return
        for b in self.buckets:
            t0 = time.monotonic()
            try:
                fn = self.aot.load_exec(
                    b, self._aot_in_tree, self._aot_out_tree
                )
            except Exception as exc:
                self._aot_fallback(
                    "deserialize_error", bucket=b,
                    detail=f"{type(exc).__name__}: {exc}",
                )
                continue
            if fn is None:
                self._aot_fallback("missing_bucket", bucket=b)
                continue
            dt = time.monotonic() - t0
            self._aot_execs[b] = fn
            AOT_RESTORE_SECONDS.set(dt, path=self.aot_role, bucket=str(b))
            journal.event(
                "aot_restore", role=self.aot_role, bucket=b,
                seconds=round(dt, 4),
            )

    def _aot_fallback(self, reason: str, bucket=None, detail=None) -> None:
        # Journal key is `role` (device|host), deliberately NOT `path`:
        # persist.aot's emits carry `path` as a filesystem path, and one
        # journal key must not mean two things across emit sites.
        AOT_FALLBACKS.inc(reason=reason)
        journal.event(
            "aot_fallback", reason=reason, role=self.aot_role,
            bucket=bucket, detail=detail,
        )

    def _warm_bucket(self, jax, b: int, row, oracle_p) -> float:
        """One bucket's warmup pass: run + block the impl (AOT executable
        or trace+compile), verify an AOT bucket against the oracle, and
        re-trace on any AOT failure. Returns the bucket's total warmup
        wall seconds (fallback re-trace included — the honest cost)."""
        X = np.repeat(row, b, axis=0)
        via_aot = b in self._aot_execs
        t0 = time.monotonic()
        out = None
        with journal.stage_scope(f"serve_warmup:{self.aot_role}:b{b}"):
            try:
                out = self._impl(X)
                jax.block_until_ready(out)
            except Exception:
                if not via_aot:
                    raise  # a trace-path failure is a real engine failure
                out = None
        fallback = None
        if via_aot:
            if out is None:
                fallback = "exec_error"
            else:
                # Whole-vector check: the warmup rows are b copies of one
                # patient, so EVERY output lane must equal the oracle — a
                # blob miscompiled past lane 0 must not slip through a
                # row-0-only probe.
                rtol, atol = parity_tolerance()
                p1 = np.asarray(out[0], np.float64)
                if p1.shape != (b,) or not np.allclose(
                    p1, oracle_p, rtol=rtol, atol=atol
                ):
                    fallback = "parity_mismatch"
        if fallback is not None:
            # Fails open: drop the bad executable, journal, re-trace the
            # bucket — slower start, never a wrong (or absent) answer.
            self._aot_fallback(fallback, bucket=b)
            del self._aot_execs[b]
            with journal.stage_scope(
                f"serve_warmup:{self.aot_role}:b{b}:retrace"
            ):
                jax.block_until_ready(self._impl(X))
        dt = time.monotonic() - t0
        WARMUP_SECONDS.set(dt, path=self.aot_role, bucket=str(b))
        return dt
