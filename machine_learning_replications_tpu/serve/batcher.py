"""Thread-safe micro-batcher with bounded admission and graceful drain.

The throughput story of the serving layer (Clipper, NSDI '17): individual
requests arriving within a small window are coalesced into one batched
device call, because ``pipeline_predict_proba1`` scales with batch size
while per-call dispatch overhead does not. Flush policy is the standard
two-knob one — a batch goes to the engine when it reaches
``max_batch_size`` rows OR the oldest queued request has waited
``max_wait_ms`` — so light traffic pays at most the wait bound and heavy
traffic gets full buckets.

Admission is BOUNDED: at most ``max_queue`` requests may be waiting. Past
that, ``submit`` raises ``Overloaded`` immediately — the server turns that
into an explicit 503 — instead of queueing unboundedly and converting
overload into unbounded latency for every client (the load-shedding
contract; the shed rate is a first-class metric).

``close(drain=True)`` stops admission, flushes everything already
admitted, and joins the flush thread: an admitted request is never dropped
by shutdown.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from machine_learning_replications_tpu.obs import journal, spans


class Overloaded(RuntimeError):
    """Admission queue full — the request was shed, not queued."""


class _Pending:
    __slots__ = ("row", "future", "t_enqueue")

    def __init__(self, row: np.ndarray) -> None:
        self.row = row
        self.future: Future = Future()
        self.t_enqueue = time.monotonic()


class MicroBatcher:
    """Coalesce single-row predict requests into engine-sized batches.

    ``engine`` needs ``predict(X[n, F]) -> p[n]``; when it also exposes
    ``bucket_for`` (the bucketed engine does), each flush records its
    padding waste. ``metrics`` is a ``serve.metrics.ServingMetrics`` (or
    None to run unobserved, e.g. in unit tests).
    """

    def __init__(
        self,
        engine,
        max_batch_size: int = 64,
        max_wait_ms: float = 5.0,
        max_queue: int = 256,
        metrics=None,
    ) -> None:
        if max_batch_size < 1 or max_queue < 1:
            raise ValueError("max_batch_size and max_queue must be >= 1")
        self._engine = engine
        self._max_batch = int(max_batch_size)
        self._max_wait_s = float(max_wait_ms) / 1000.0
        self._max_queue = int(max_queue)
        self._metrics = metrics
        self._cv = threading.Condition()
        self._q: deque[_Pending] = deque()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="micro-batcher", daemon=True
        )
        self._thread.start()

    # -- producer side -----------------------------------------------------

    def submit(self, row: np.ndarray) -> Future:
        """Enqueue one contract-order feature row; resolves to its
        probability (float). Raises ``Overloaded`` when the admission
        queue is full and ``RuntimeError`` after ``close``."""
        row = np.asarray(row, np.float64).ravel()
        want = getattr(self._engine, "n_features", None)
        if want is not None and row.shape[0] != want:
            # Reject at the door: a mis-shaped row admitted here would
            # only fail later inside a coalesced batch, taking its
            # batchmates down with it.
            raise ValueError(
                f"expected a {want}-feature row, got {row.shape[0]}"
            )
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._q) >= self._max_queue:
                if self._metrics is not None:
                    self._metrics.shed_total.inc()
                raise Overloaded(
                    f"admission queue full ({self._max_queue} waiting)"
                )
            p = _Pending(row)
            self._q.append(p)
            if self._metrics is not None:
                self._metrics.requests_total.inc()
                self._metrics.queue_depth.set(len(self._q))
            self._cv.notify()
        return p.future

    @property
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._q)

    # -- consumer side -----------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q and self._closed:
                    return
                # Wait out the coalescing window (unless the batch is
                # already full, or we are draining a closed batcher —
                # drain flushes at full speed).
                deadline = self._q[0].t_enqueue + self._max_wait_s
                while (
                    len(self._q) < self._max_batch
                    and not self._closed
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                batch = [
                    self._q.popleft()
                    for _ in range(min(len(self._q), self._max_batch))
                ]
                if self._metrics is not None:
                    self._metrics.queue_depth.set(len(self._q))
            self._flush(batch)

    def _flush(self, batch: list[_Pending]) -> None:
        # Claim each entry (queued → running). A False return means the
        # server cancelled it on client-deadline expiry — drop it here so
        # the engine never computes answers nobody will read. A claimed
        # future can no longer be cancelled, so set_result below is safe.
        batch = [p for p in batch if p.future.set_running_or_notify_cancel()]
        if not batch:
            return
        try:
            # np.stack inside the try: a mis-shaped row slipping past
            # submit must fail its batch's futures, not kill the flush
            # thread (which would wedge the batcher permanently).
            with spans.span("serve:flush", rows=len(batch)):
                X = np.stack([p.row for p in batch])
                probs = np.asarray(self._engine.predict(X), np.float64)
        except Exception as exc:
            if self._metrics is not None:
                self._metrics.errors_total.inc(len(batch))
            journal.event(
                "flush", rows=len(batch), ok=False,
                error=f"{type(exc).__name__}: {exc}",
            )
            for p in batch:
                p.future.set_exception(exc)
            return
        now = time.monotonic()
        journal.event(
            "flush", rows=len(batch), ok=True,
            oldest_wait_s=round(now - batch[0].t_enqueue, 6),
        )
        if self._metrics is not None:
            self._metrics.batches_total.inc()
            self._metrics.batch_size.observe(len(batch))
            bucket_for = getattr(self._engine, "bucket_for", None)
            if bucket_for is not None:
                self._metrics.padding_waste.observe(
                    max(bucket_for(len(batch)) - len(batch), 0)
                )
            for p in batch:
                self._metrics.latency.observe(now - p.t_enqueue)
        for p, prob in zip(batch, probs):
            p.future.set_result(float(prob))

    # -- shutdown ----------------------------------------------------------

    def close(self, drain: bool = True, timeout: float | None = 30.0) -> None:
        """Stop admission; with ``drain`` (default) flush every admitted
        request before returning, otherwise fail them fast."""
        with self._cv:
            self._closed = True
            if not drain:
                while self._q:
                    p = self._q.popleft()
                    if p.future.set_running_or_notify_cancel():
                        p.future.set_exception(
                            RuntimeError("server shutting down")
                        )
                if self._metrics is not None:
                    self._metrics.queue_depth.set(0)
            self._cv.notify_all()
        self._thread.join(timeout)
