"""Thread-safe micro-batcher with bounded admission and graceful drain.

The throughput story of the serving layer (Clipper, NSDI '17): individual
requests arriving within a small window are coalesced into one batched
device call, because ``pipeline_predict_proba1`` scales with batch size
while per-call dispatch overhead does not. Flush policy is the standard
two-knob one — a batch goes to the engine when it reaches
``max_batch_size`` rows OR the oldest queued request has waited
``max_wait_ms`` — so light traffic pays at most the wait bound and heavy
traffic gets full buckets.

Admission is BOUNDED: at most ``max_queue`` requests may be waiting. Past
that, ``submit`` raises ``Overloaded`` immediately — the server turns that
into an explicit 503 — instead of queueing unboundedly and converting
overload into unbounded latency for every client (the load-shedding
contract; the shed rate is a first-class metric).

``close(drain=True)`` stops admission, flushes everything already
admitted, and joins the flush thread: an admitted request is never dropped
by shutdown.

``PathRouter`` (dual-path scoring, docs/SERVING.md) also lives here: the
routing decision is a function of batcher state — queue depth and whether
a flush is mid-compute — plus host-path availability and the request's
deadline, and this module owns that state.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from machine_learning_replications_tpu.obs import jaxmon, journal, spans
from machine_learning_replications_tpu.resilience import faults
from machine_learning_replications_tpu.resilience.supervisor import BreakerOpen


class Overloaded(RuntimeError):
    """Admission queue full — the request was shed, not queued."""


class _Pending:
    __slots__ = ("row", "future", "t_enqueue", "t_enqueue_perf", "trace")

    def __init__(self, row: np.ndarray, trace=None) -> None:
        self.row = row
        self.future: Future = Future()
        self.t_enqueue = time.monotonic()
        # perf_counter twin of t_enqueue: request traces stamp every phase
        # on one clock (obs.reqtrace uses perf_counter throughout).
        self.t_enqueue_perf = time.perf_counter()
        self.trace = trace


class MicroBatcher:
    """Coalesce single-row predict requests into engine-sized batches.

    ``engine`` needs ``predict(X[n, F]) -> p[n]``; when it also exposes
    ``bucket_for`` (the bucketed engine does), each flush records its
    padding waste. ``metrics`` is a ``serve.metrics.ServingMetrics`` (or
    None to run unobserved, e.g. in unit tests).
    """

    def __init__(
        self,
        engine,
        max_batch_size: int = 64,
        max_wait_ms: float = 5.0,
        max_queue: int = 256,
        metrics=None,
    ) -> None:
        if max_batch_size < 1 or max_queue < 1:
            raise ValueError("max_batch_size and max_queue must be >= 1")
        self._engine = engine
        self._max_batch = int(max_batch_size)
        self._max_wait_s = float(max_wait_ms) / 1000.0
        self._max_queue = int(max_queue)
        self._metrics = metrics
        self._cv = threading.Condition()
        self._q: deque[_Pending] = deque()
        self._flush_seq = 0  # flush-thread-only; correlates traces↔flushes
        # Routing signal (PathRouter): True while the flush thread is out
        # of the queue lock running a batch. Written by the flush thread
        # only; racy reads are fine — the router treats it as a hint.
        self._flushing = False
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="micro-batcher", daemon=True
        )
        self._thread.start()

    # -- producer side -----------------------------------------------------

    def submit(self, row: np.ndarray, trace=None, count: bool = True) -> Future:
        """Enqueue one contract-order feature row; resolves to its
        probability (float). Raises ``Overloaded`` when the admission
        queue is full and ``RuntimeError`` after ``close``.

        ``trace`` is an optional ``obs.reqtrace.RequestTrace``: the flush
        thread stamps its queue-wait / batch-assembly / device-compute
        phases and flush annotations (sequence, bucket, cold-compile) —
        the batcher never *finishes* a trace; request lifecycle stays
        with the caller. ``count=False`` skips the ``requests_total``
        increment: the host-path failure fallback resubmits a request
        that was already counted at its first admission, and one logical
        request must move the counter once."""
        row = np.asarray(row, np.float64).ravel()
        want = getattr(self._engine, "n_features", None)
        if want is not None and row.shape[0] != want:
            # Reject at the door: a mis-shaped row admitted here would
            # only fail later inside a coalesced batch, taking its
            # batchmates down with it.
            raise ValueError(
                f"expected a {want}-feature row, got {row.shape[0]}"
            )
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._q) >= self._max_queue:
                if self._metrics is not None:
                    self._metrics.shed_total.inc()
                raise Overloaded(
                    f"admission queue full ({self._max_queue} waiting)"
                )
            p = _Pending(row, trace=trace)
            self._q.append(p)
            qlen = len(self._q)
            if self._metrics is not None:
                if count:
                    self._metrics.requests_total.inc()
                self._metrics.queue_depth.set(qlen)
            # Wake the flush thread only when it could act on the wake:
            # the first request of an empty queue (it is parked in the
            # outer wait) or a full batch (it may cut the coalescing wait
            # short). Everything in between is covered by the flush
            # loop's own deadline timeout, and an unconditional notify
            # per submit is measurable at event-loop ingest rates.
            if qlen == 1 or qlen >= self._max_batch:
                self._cv.notify()
        return p.future

    @property
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._q)

    @property
    def flush_in_progress(self) -> bool:
        """Whether the flush thread is currently running a batch (hint for
        the path router; see ``PathRouter.decide``)."""
        return self._flushing

    # -- consumer side -----------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q and self._closed:
                    return
                # Wait out the coalescing window (unless the batch is
                # already full, or we are draining a closed batcher —
                # drain flushes at full speed).
                deadline = self._q[0].t_enqueue + self._max_wait_s
                while (
                    len(self._q) < self._max_batch
                    and not self._closed
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                batch = [
                    self._q.popleft()
                    for _ in range(min(len(self._q), self._max_batch))
                ]
                if self._metrics is not None:
                    self._metrics.queue_depth.set(len(self._q))
            self._flushing = True
            try:
                self._flush(batch)
            finally:
                self._flushing = False

    def _note_flush_phases(
        self, batch: list[_Pending], t_claim: float, t_c0: float,
        t_c1: float, annotations: dict,
    ) -> None:
        """Stamp each traced batch member's flush-side phases: queue wait
        (enqueue → claim), batch assembly (claim → engine call, including
        the cancel sweep and np.stack), device compute (the engine call,
        which blocks through np.asarray). ``flush_index`` is the member's
        batch position — the trace-merge slice allocator keys on it."""
        for i, p in enumerate(batch):
            if p.trace is None:
                continue
            # Queue wait starts where the caller's parse phase ended (so
            # the phases partition the request with no gap — submit's
            # lock wait is queueing too), falling back to the enqueue
            # stamp for direct batcher callers with bare traces. All
            # three phases + annotations land under one trace lock.
            q0 = p.trace.phase_end("parse", p.t_enqueue_perf)
            p.trace.add_phases(
                {
                    "queue_wait": (q0, t_claim),
                    "batch_assembly": (t_claim, t_c0),
                    "device_compute": (t_c0, t_c1),
                },
                flush_index=i, **annotations,
            )

    def _flush(self, batch: list[_Pending]) -> None:
        # Claim each entry (queued → running). A False return means the
        # server cancelled it on client-deadline expiry — drop it here so
        # the engine never computes answers nobody will read. A claimed
        # future can no longer be cancelled, so set_result below is safe.
        t_claim = time.perf_counter()
        t_claim_mono = time.monotonic()
        batch = [p for p in batch if p.future.set_running_or_notify_cancel()]
        if not batch:
            return
        self._flush_seq += 1  # flush thread only — no lock needed
        flush_seq = self._flush_seq
        tracer = spans.get_tracer()
        # Batch shape accounting: the engine's plan (the exact chunk
        # sequence predict will run — ``engine.plan_batch``) when it has
        # one, else the legacy single covering bucket. ``bucket`` stays
        # the plan's largest chunk so existing trace/journal consumers
        # keep a scalar; multi-chunk plans additionally carry ``shape``.
        plan_for = getattr(self._engine, "plan_batch", None)
        bucket_for = getattr(self._engine, "bucket_for", None)
        if plan_for is not None:
            plan = tuple(plan_for(len(batch)))
        elif bucket_for is not None:
            plan = (bucket_for(len(batch)),)
        else:
            plan = None
        bucket = max(plan) if plan else None
        padded = (sum(plan) - len(batch)) if plan else 0
        shape = list(plan) if plan and len(plan) > 1 else None
        # Cold-compile attribution: a flush that grows the engine's
        # compile count (or, failing that instrument, the process
        # compile counter) paid an XLA compile — THE canonical
        # tail-latency outlier, worth naming on every trace it delayed.
        engine_compiles = getattr(self._engine, "compile_count", None)
        count_compiles = (
            engine_compiles if engine_compiles is not None
            else jaxmon.compile_count
        )
        compiles0 = count_compiles()
        if self._metrics is not None:
            # One lock acquisition for the whole batch: at event-loop
            # throughput, per-row histogram locking is measurable.
            self._metrics.queue_wait.observe_many(
                [t_claim_mono - p.t_enqueue for p in batch]
            )
        t_c0 = t_c1 = None
        try:
            # np.stack inside the try: a mis-shaped row slipping past
            # submit must fail its batch's futures, not kill the flush
            # thread (which would wedge the batcher permanently). The
            # faultpoint rides inside the same try for the same reason —
            # an injected flush fault fails THIS batch's futures
            # explicitly, never the loop.
            with spans.span("serve:flush", rows=len(batch)) as sp:
                faults.fire("batcher.flush")
                X = np.stack([p.row for p in batch])
                t_c0 = time.perf_counter()
                # predict_tagged (supervised engines) pairs the probs
                # with the computing engine's model version, captured
                # atomically with the engine reference — around a warm
                # swap, reply headers must name the version of THESE
                # bits, not whatever the handle says at respond time.
                # Unsupervised engines cannot be swapped (deploys require
                # supervision), so a plain attribute read is exact there.
                tagged = getattr(self._engine, "predict_tagged", None)
                if tagged is not None:
                    out, model_version = tagged(X)
                else:
                    out = self._engine.predict(X)
                    model_version = getattr(
                        self._engine, "model_version", None
                    )
                probs = np.asarray(out, np.float64)
                t_c1 = time.perf_counter()
                cold = count_compiles() > compiles0
                sp.note(flush_seq=flush_seq, bucket=bucket,
                        cold_compile=cold)
        except Exception as exc:
            # A BreakerOpen from the supervised engine is a degraded-mode
            # SHED of requests admitted before the breaker opened — the
            # engine was never invoked and the client gets the same
            # explicit 503 + Retry-After as the pre-admission path. It
            # must count in shed_total, not errors_total ('failed inside
            # the engine'), or every degraded window fires error-rate
            # alerts for contract-conforming sheds while the shed rate
            # under-reports.
            shed = isinstance(exc, BreakerOpen)
            if self._metrics is not None:
                counter = (
                    self._metrics.shed_total if shed
                    else self._metrics.errors_total
                )
                counter.inc(len(batch))
            journal.event(
                "flush", seq=flush_seq, rows=len(batch), ok=False,
                shed=shed, error=f"{type(exc).__name__}: {exc}",
            )
            # Partial phase record: queue wait and assembly happened, and
            # the compute interval ends where the engine raised — a
            # sampled failure trace still says where the time went.
            t_err = time.perf_counter()
            self._note_flush_phases(
                batch, t_claim, t_c0 if t_c0 is not None else t_err,
                t_c1 if t_c1 is not None else t_err,
                {
                    "flush_seq": flush_seq, "batch_rows": len(batch),
                    "bucket": bucket,
                    "flush_tid": (
                        tracer.current_tid() if tracer is not None else None
                    ),
                },
            )
            for p in batch:
                p.future.set_exception(exc)
            return
        now = time.monotonic()
        journal.event(
            "flush", seq=flush_seq, rows=len(batch), ok=True,
            bucket=bucket, cold_compile=cold,
            oldest_wait_s=round(now - batch[0].t_enqueue, 6),
            **({"shape": shape} if shape is not None else {}),
        )
        self._note_flush_phases(batch, t_claim, t_c0, t_c1, {
            "flush_seq": flush_seq, "batch_rows": len(batch),
            "bucket": bucket, "cold_compile": cold,
            "padded_rows": max(padded, 0),
            **({"shape": shape} if shape is not None else {}),
            **({"model_version": model_version}
               if model_version is not None else {}),
            "flush_tid": tracer.current_tid() if tracer is not None else None,
        })
        if self._metrics is not None:
            self._metrics.batches_total.inc()
            self._metrics.batch_size.observe(len(batch))
            if plan is not None:
                self._metrics.padding_waste.observe(max(padded, 0))
            self._metrics.latency.observe_many(
                [now - p.t_enqueue for p in batch]
            )
        for p, prob in zip(batch, probs):
            p.future.set_result(float(prob))

    # -- shutdown ----------------------------------------------------------

    def close(self, drain: bool = True, timeout: float | None = 30.0) -> None:
        """Stop admission; with ``drain`` (default) flush every admitted
        request before returning, otherwise fail them fast."""
        with self._cv:
            self._closed = True
            if not drain:
                while self._q:
                    p = self._q.popleft()
                    if p.future.set_running_or_notify_cancel():
                        p.future.set_exception(
                            RuntimeError("server shutting down")
                        )
                if self._metrics is not None:
                    self._metrics.queue_depth.set(0)
            self._cv.notify_all()
        self._thread.join(timeout)


class PathRouter:
    """The dual-path routing decision (docs/SERVING.md "Dual-path
    scoring"): host fast path or device micro-batch, per request.

    The policy is deliberately small and fully deterministic given the
    observed state — every branch is unit-testable by forcing that state:

      * no host path (unsupported family, disabled, not warm) → device;
      * host saturated (every ``HostPath`` slot busy) → device — at
        saturation the batcher's coalescing is the whole throughput
        story, and the host path self-limits by its slot bound;
      * a *tight* request deadline (``deadline_s`` at or under
        ``tight_deadline_s``) → host: such a request cannot afford the
        coalescing window plus a possibly-mid-flight flush ahead of it;
      * queued rows already coalescing (``queue_depth`` ≥
        ``burst_depth``) → device: joining a forming batch costs no
        extra wait and buys the batch economics;
      * otherwise (idle queue — even with a flush mid-compute, which a
        new device request would serialize behind) → host.

    ``decide`` returns ``(path, reason)``; the caller counts the path it
    actually dispatched (a ``HostBusy`` race falls back to device) in
    ``serve_path_total`` and stamps both on the request trace.
    """

    def __init__(
        self,
        batcher: MicroBatcher,
        host,
        burst_depth: int = 1,
        tight_deadline_s: float = 0.05,
    ) -> None:
        if burst_depth < 1:
            raise ValueError("burst_depth must be >= 1")
        self.batcher = batcher
        self.host = host
        self.burst_depth = int(burst_depth)
        self.tight_deadline_s = float(tight_deadline_s)

    def decide(self, deadline_s: float | None = None) -> tuple[str, str]:
        host = self.host
        if host is None:
            return "device", "no_host_path"
        if not getattr(host, "available", True):
            return "device", "host_unavailable"
        if host.saturated:
            return "device", "host_saturated"
        if deadline_s is not None and deadline_s <= self.tight_deadline_s:
            return "host", "tight_deadline"
        depth = self.batcher.queue_depth
        if depth >= self.burst_depth:
            return "device", "coalescing"
        if self.batcher.flush_in_progress:
            return "host", "flush_in_progress"
        return "host", "idle"
