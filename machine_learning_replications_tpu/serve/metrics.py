"""Serving metrics — the observability half of the serving contract.

A model server that sheds load needs numbers to prove the shedding was
correct: offered vs served throughput, latency quantiles, how deep the
admission queue ran, and how much device work the bucket ladder wasted on
padding. Everything here is stdlib + numpy, one lock per instrument, and
renders in Prometheus text exposition format on ``/metrics``
(``serve.server``); ``snapshot()`` is the same data as a dict for JSON
consumers and tests.

Quantiles come from a bounded ring of recent observations (default 8192)
rather than streaming sketches: a serving process answering p99 questions
about *recent* traffic wants a sliding window anyway, and the ring keeps
the memory bound explicit (one f64 per slot) — the same
bounded-over-unbounded discipline as the batcher's admission queue.
"""

from __future__ import annotations

import threading
import time
from typing import Sequence

import numpy as np


class Counter:
    """Monotonic counter (thread-safe)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value (thread-safe)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Cumulative-bucket histogram plus a quantile ring.

    ``buckets`` are upper bounds (``le``) in ascending order; an implicit
    +Inf bucket catches the tail. ``quantile`` interpolates over the ring
    of the most recent ``ring_size`` observations (numpy percentile,
    linear interpolation), so p50/p95/p99 track current traffic instead of
    the process's whole life.
    """

    def __init__(self, buckets: Sequence[float], ring_size: int = 8192) -> None:
        self._lock = threading.Lock()
        self._bounds = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self._bounds) + 1)  # +Inf tail
        self._sum = 0.0
        self._count = 0
        self._ring = np.empty(ring_size, np.float64)
        self._ring_n = 0  # total ever written; ring index = n % size

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            i = 0
            while i < len(self._bounds) and v > self._bounds[i]:
                i += 1
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._ring[self._ring_n % self._ring.shape[0]] = v
            self._ring_n += 1

    def quantile(self, q: float | Sequence[float]):
        """Quantile(s) in [0, 1] over the recent-observation ring
        (NaN when empty)."""
        with self._lock:
            n = min(self._ring_n, self._ring.shape[0])
            window = self._ring[:n].copy()
        if n == 0:
            return (
                float("nan")
                if isinstance(q, float)
                else [float("nan")] * len(list(q))
            )
        out = np.percentile(window, np.asarray(q, np.float64) * 100.0)
        return float(out) if isinstance(q, float) else [float(x) for x in out]

    def snapshot(self) -> dict:
        with self._lock:
            cum, acc = [], 0
            for c in self._counts:
                acc += c
                cum.append(acc)
            return {
                "buckets": {
                    **{str(b): cum[i] for i, b in enumerate(self._bounds)},
                    "+Inf": cum[-1],
                },
                "sum": self._sum,
                "count": self._count,
            }


# Latency buckets in seconds: sub-ms through 10 s, roughly log-spaced — wide
# enough for a cold-compile outlier, fine enough to see micro-batch wait.
LATENCY_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class ServingMetrics:
    """The fixed instrument set the serving layer exports.

    ``requests_total`` counts admitted requests; ``shed_total`` counts
    admission-queue rejections (the explicit "overloaded" replies);
    ``errors_total`` counts requests that failed inside the engine;
    ``timeouts_total`` counts admitted requests whose client deadline
    expired before the batcher reached them (replied 504 and cancelled, so
    the engine never computes them). Batch instruments are per flushed
    micro-batch: ``batch_size`` is real rows, ``padding_waste`` is
    ``bucket − real rows`` (device rows computed and thrown away — the
    cost of the bounded compile cache).
    """

    def __init__(
        self,
        batch_buckets: Sequence[float] = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
    ) -> None:
        self.requests_total = Counter()
        self.shed_total = Counter()
        self.errors_total = Counter()
        self.timeouts_total = Counter()
        self.batches_total = Counter()
        self.queue_depth = Gauge()
        self.latency = Histogram(LATENCY_BUCKETS_S)
        self.batch_size = Histogram(batch_buckets)
        self.padding_waste = Histogram(batch_buckets)
        self.started_at = time.time()

    def snapshot(self) -> dict:
        # Empty-window quantiles become None (JSON null): a bare NaN token
        # is not strict JSON, and this dict feeds /metrics?format=json.
        p50, p95, p99 = (
            None if v != v else v
            for v in self.latency.quantile((0.5, 0.95, 0.99))
        )
        lat = self.latency.snapshot()
        return {
            "requests_total": self.requests_total.value,
            "shed_total": self.shed_total.value,
            "errors_total": self.errors_total.value,
            "timeouts_total": self.timeouts_total.value,
            "batches_total": self.batches_total.value,
            "queue_depth": self.queue_depth.value,
            "latency_seconds": {
                "p50": p50, "p95": p95, "p99": p99,
                "sum": lat["sum"], "count": lat["count"],
            },
            "batch_size": self.batch_size.snapshot(),
            "padding_waste": self.padding_waste.snapshot(),
            "uptime_seconds": time.time() - self.started_at,
        }

    def render_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) of every instrument."""
        lines: list[str] = []

        def counter(name: str, help_: str, v: float) -> None:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {v}")

        def histogram(name: str, help_: str, h: Histogram) -> None:
            snap = h.snapshot()
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} histogram")
            for le, c in snap["buckets"].items():
                lines.append(f'{name}_bucket{{le="{le}"}} {c}')
            lines.append(f"{name}_sum {snap['sum']}")
            lines.append(f"{name}_count {snap['count']}")

        counter("serve_requests_total", "Admitted predict requests.",
                self.requests_total.value)
        counter("serve_shed_total",
                "Requests rejected by admission control (overloaded).",
                self.shed_total.value)
        counter("serve_errors_total", "Requests failed inside the engine.",
                self.errors_total.value)
        counter("serve_timeouts_total",
                "Admitted requests whose deadline expired before flush "
                "(504, cancelled unserved).",
                self.timeouts_total.value)
        counter("serve_batches_total", "Micro-batches flushed to the engine.",
                self.batches_total.value)
        lines.append("# HELP serve_queue_depth Admission queue depth after "
                     "the last flush.")
        lines.append("# TYPE serve_queue_depth gauge")
        lines.append(f"serve_queue_depth {self.queue_depth.value}")
        # Quantiles live under their OWN family name: summary-style samples
        # inside the histogram family (metadata after samples / duplicate
        # family) make the whole exposition unparseable to a strict
        # Prometheus scraper.
        lines.append("# HELP serve_request_latency_quantile_seconds "
                     "Recent-window latency quantiles (ring of last 8192).")
        lines.append("# TYPE serve_request_latency_quantile_seconds gauge")
        for q, v in zip((0.5, 0.95, 0.99),
                        self.latency.quantile((0.5, 0.95, 0.99))):
            val = "NaN" if v != v else repr(v)
            lines.append(
                f'serve_request_latency_quantile_seconds{{quantile="{q}"}} '
                f"{val}"
            )
        histogram("serve_request_latency_seconds",
                  "Request latency from enqueue to flush completion "
                  "(excludes HTTP reply serialization).",
                  self.latency)
        histogram("serve_batch_size_rows", "Real rows per flushed micro-batch.",
                  self.batch_size)
        histogram("serve_padding_waste_rows",
                  "Pad rows per flushed micro-batch (bucket minus real rows).",
                  self.padding_waste)
        return "\n".join(lines) + "\n"
