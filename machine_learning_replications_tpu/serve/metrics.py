"""Serving metrics — the observability half of the serving contract.

A model server that sheds load needs numbers to prove the shedding was
correct: offered vs served throughput, latency quantiles, how deep the
admission queue ran, and how much device work the bucket ladder wasted on
padding. Everything here is stdlib + numpy, one lock per instrument, and
renders in Prometheus text exposition format on ``/metrics``
(``serve.server``); ``snapshot()`` is the same data as a dict for JSON
consumers and tests.

Quantiles come from a bounded ring of recent observations (default 8192)
rather than streaming sketches: a serving process answering p99 questions
about *recent* traffic wants a sliding window anyway, and the ring keeps
the memory bound explicit (one f64 per slot) — the same
bounded-over-unbounded discipline as the batcher's admission queue.

The primitive instruments (``Counter`` / ``Gauge`` / ``Histogram``) now
live in ``obs.registry`` — the process-global metrics layer the whole
stack shares — and are re-exported here unchanged for backward
compatibility; every ``serve_*`` metric name and its exposition stay
byte-identical. The serving ``/metrics`` page additionally appends the
global registry's exposition (jax compile/transfer accounting —
``obs.jaxmon``); see ``serve.server``.
"""

from __future__ import annotations

import time
from typing import Sequence

# Re-exported: the serving layer's instruments are the shared obs
# primitives (import sites and pickles of these classes keep working).
from machine_learning_replications_tpu.obs.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
)


# Latency buckets in seconds: sub-ms through 10 s, roughly log-spaced — wide
# enough for a cold-compile outlier, fine enough to see micro-batch wait.
LATENCY_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# Queue-wait buckets: tuned around the flush interval (--max-wait-ms,
# default 5 ms). A healthy server's waits cluster at or below that knob
# (sub-bucket resolution on both sides of it); the tail buckets exist to
# make queueing collapse visible — waits 10–1000× the flush interval are
# the overload signature tail sampling attributes per request, and this
# histogram shows in aggregate on every scrape.
QUEUE_WAIT_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.0075, 0.01, 0.025, 0.05,
    0.1, 0.25, 1.0, 5.0,
)


class ServingMetrics:
    """The fixed instrument set the serving layer exports.

    ``requests_total`` counts admitted requests; ``shed_total`` counts
    admission-queue rejections (the explicit "overloaded" replies);
    ``errors_total`` counts requests that failed inside the engine;
    ``timeouts_total`` counts admitted requests whose client deadline
    expired before the batcher reached them (replied 504 and cancelled, so
    the engine never computes them). Batch instruments are per flushed
    micro-batch: ``batch_size`` is real rows, ``padding_waste`` is
    ``bucket − real rows`` (device rows computed and thrown away — the
    cost of the bounded compile cache).
    """

    def __init__(
        self,
        batch_buckets: Sequence[float] = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
    ) -> None:
        self.requests_total = Counter()
        self.shed_total = Counter()
        self.errors_total = Counter()
        self.timeouts_total = Counter()
        self.batches_total = Counter()
        self.queue_depth = Gauge()
        self.latency = Histogram(LATENCY_BUCKETS_S)
        self.queue_wait = Histogram(QUEUE_WAIT_BUCKETS_S)
        self.batch_size = Histogram(batch_buckets)
        self.padding_waste = Histogram(batch_buckets)
        # Monotonic: uptime is duration arithmetic, and the wall
        # clock jumps (NTP) — rule monotonic-clock.
        self.started_monotonic = time.monotonic()

    def uptime_seconds(self) -> float:
        return time.monotonic() - self.started_monotonic

    def snapshot(self) -> dict:
        # Empty-window quantiles become None (JSON null): a bare NaN token
        # is not strict JSON, and this dict feeds /metrics?format=json.
        p50, p95, p99 = (
            None if v != v else v
            for v in self.latency.quantile((0.5, 0.95, 0.99))
        )
        lat = self.latency.snapshot()
        return {
            "requests_total": self.requests_total.value,
            "shed_total": self.shed_total.value,
            "errors_total": self.errors_total.value,
            "timeouts_total": self.timeouts_total.value,
            "batches_total": self.batches_total.value,
            "queue_depth": self.queue_depth.value,
            "latency_seconds": {
                "p50": p50, "p95": p95, "p99": p99,
                "sum": lat["sum"], "count": lat["count"],
            },
            "queue_wait_seconds": self.queue_wait.snapshot(),
            "batch_size": self.batch_size.snapshot(),
            "padding_waste": self.padding_waste.snapshot(),
            "uptime_seconds": self.uptime_seconds(),
        }

    def render_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) of every instrument."""
        lines: list[str] = []

        def counter(name: str, help_: str, v: float) -> None:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {v}")

        def histogram(name: str, help_: str, h: Histogram) -> None:
            snap = h.snapshot()
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} histogram")
            for le, c in snap["buckets"].items():
                lines.append(f'{name}_bucket{{le="{le}"}} {c}')
            lines.append(f"{name}_sum {snap['sum']}")
            lines.append(f"{name}_count {snap['count']}")

        counter("serve_requests_total", "Admitted predict requests.",
                self.requests_total.value)
        counter("serve_shed_total",
                "Requests rejected by admission control (overloaded).",
                self.shed_total.value)
        counter("serve_errors_total", "Requests failed inside the engine.",
                self.errors_total.value)
        counter("serve_timeouts_total",
                "Admitted requests whose deadline expired before flush "
                "(504, cancelled unserved).",
                self.timeouts_total.value)
        counter("serve_batches_total", "Micro-batches flushed to the engine.",
                self.batches_total.value)
        lines.append("# HELP serve_queue_depth Admission queue depth after "
                     "the last flush.")
        lines.append("# TYPE serve_queue_depth gauge")
        lines.append(f"serve_queue_depth {self.queue_depth.value}")
        # Quantiles live under their OWN family name: summary-style samples
        # inside the histogram family (metadata after samples / duplicate
        # family) make the whole exposition unparseable to a strict
        # Prometheus scraper.
        lines.append("# HELP serve_request_latency_quantile_seconds "
                     "Recent-window latency quantiles (ring of last 8192).")
        lines.append("# TYPE serve_request_latency_quantile_seconds gauge")
        for q, v in zip((0.5, 0.95, 0.99),
                        self.latency.quantile((0.5, 0.95, 0.99))):
            val = "NaN" if v != v else repr(v)
            lines.append(
                f'serve_request_latency_quantile_seconds{{quantile="{q}"}} '
                f"{val}"
            )
        histogram("serve_request_latency_seconds",
                  "Request latency from enqueue to flush completion "
                  "(excludes HTTP reply serialization).",
                  self.latency)
        histogram("serve_queue_wait_seconds",
                  "Admission-queue wait per flushed request (enqueue to "
                  "flush claim) — tail queueing visible without a "
                  "sampled trace.",
                  self.queue_wait)
        histogram("serve_batch_size_rows", "Real rows per flushed micro-batch.",
                  self.batch_size)
        histogram("serve_padding_waste_rows",
                  "Pad rows per flushed micro-batch (bucket minus real rows).",
                  self.padding_waste)
        return "\n".join(lines) + "\n"
