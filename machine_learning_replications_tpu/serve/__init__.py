"""L7 — inference serving.

The reference's only inference surface is a synchronous single-patient
script (``predict_hf.py``); the ROADMAP's "serving heavy traffic" half had
no subsystem behind it. This package is that subsystem, stdlib-only:

  ``engine``    warm compiled batched predict over a fixed bucket ladder
                (bounded jit cache, startup warmup, Orbax + pickle params)
  ``batcher``   thread-safe micro-batching (max-batch / max-wait flush),
                bounded admission with explicit load shedding, graceful
                drain
  ``protocol``  pure HTTP/1.1 parse/respond rules — incremental parser
                (pipelining, split reads), framing guards (400/413/431 +
                desync closes), response builder; no sockets
  ``transport`` the non-blocking ``selectors`` event loop: one thread per
                worker owns every socket, keep-alive pipelining, explicit
                backpressure, idle/slow-loris reaping, ``SO_REUSEPORT``
                pre-fork sharding (``cli serve --workers N``)
  ``server``    the application: ``/predict`` (17-variable patient JSON),
                ``/healthz`` (liveness) + ``/readyz`` (readiness),
                ``/metrics``, and the guarded ``/debug/*`` surfaces
                (requests, profile, quality, faults)

The engine runs supervised by default (``resilience.supervisor``):
watchdog deadline per flush, circuit breaker, degraded-mode 503 +
``Retry-After`` shedding, and bounded-backoff restart —
docs/RESILIENCE.md.
  ``metrics``  latency quantiles, queue depth, batch-size and
               padding-waste histograms (instrument primitives shared
               with — and re-exported from — ``obs.registry``; /metrics
               also appends the global registry's jax compile/transfer
               accounting, docs/OBSERVABILITY.md)

Entry point: ``python -m machine_learning_replications_tpu serve``; load
generator: ``tools/loadgen.py``. Architecture notes: ``docs/SERVING.md``.
"""

from machine_learning_replications_tpu.serve.batcher import (
    MicroBatcher,
    Overloaded,
    PathRouter,
)
from machine_learning_replications_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    BucketedPredictEngine,
)
from machine_learning_replications_tpu.serve.hostpath import (
    HostBusy,
    HostPath,
    HostScorer,
)
from machine_learning_replications_tpu.serve.metrics import ServingMetrics
from machine_learning_replications_tpu.serve.server import (
    ServerHandle,
    make_server,
)

__all__ = [
    "BucketedPredictEngine",
    "DEFAULT_BUCKETS",
    "HostBusy",
    "HostPath",
    "HostScorer",
    "MicroBatcher",
    "Overloaded",
    "PathRouter",
    "ServingMetrics",
    "ServerHandle",
    "make_server",
]
