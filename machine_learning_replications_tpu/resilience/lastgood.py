"""Last-known-good checkpoint retention and rollback.

A model deploy is a checkpoint swap, and a checkpoint is a directory of
files any of which can be torn by a crash, a partial copy, or bit rot.
``persist.orbax_io`` publishes checkpoints atomically (build in a temp
dir, checksum, rename into place) and calls ``retain`` in the same
transaction: the checkpoint previously at the path is *rotated to a
sibling ``<path>.lastgood`` directory* instead of deleted.

``restore_with_fallback`` is the read side: when loading the primary
checkpoint fails — integrity mismatch, torn files, a crash that left only
the rotated-away previous version — it falls back to the retained
last-known-good, journals a ``checkpoint_rollback`` event, and counts it
(``resilience_checkpoint_rollbacks_total``). A bad deploy therefore
degrades to serving the *previous* model (loudly: the journal and metrics
say so) instead of a dead server.
"""

from __future__ import annotations

import os
import shutil

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY

LASTGOOD_SUFFIX = ".lastgood"

CHECKPOINT_ROLLBACKS = REGISTRY.counter(
    "resilience_checkpoint_rollbacks_total",
    "Checkpoint loads that fell back to the retained last-known-good "
    "after the primary failed to restore.",
)


def lastgood_path(path: str | os.PathLike) -> str:
    """The sibling directory where a checkpoint's previous version is
    retained (``<path>.lastgood``)."""
    return os.path.abspath(os.fspath(path)).rstrip(os.sep) + LASTGOOD_SUFFIX


def retain(path: str | os.PathLike) -> bool:
    """Rotate the existing checkpoint at ``path`` (if any) into its
    last-known-good slot, replacing an older retained version. Called by
    the atomic publish in ``persist.orbax_io`` just before the new
    checkpoint is renamed into place; True when something was retained."""
    path = os.path.abspath(os.fspath(path))
    if not os.path.isdir(path):
        return False
    lg = lastgood_path(path)
    if os.path.isdir(lg):
        shutil.rmtree(lg)
    os.rename(path, lg)
    return True


def restore_with_fallback(path: str | os.PathLike, loader):
    """``loader(path)``, falling back to ``loader(lastgood_path(path))``
    when the primary raises and a retained last-known-good exists.

    The rollback is LOUD: journaled (``checkpoint_rollback`` with the
    primary's error) and counted — serving yesterday's model silently
    would be as dangerous as the corruption itself. Without a retained
    fallback the original failure propagates unchanged."""
    path = os.path.abspath(os.fspath(path))
    try:
        return loader(path)
    except Exception as exc:
        lg = lastgood_path(path)
        if not os.path.isdir(lg):
            raise
        err = f"{type(exc).__name__}: {exc}"
        out = loader(lg)  # a bad lastgood raises here — nothing to hide
        CHECKPOINT_ROLLBACKS.inc()
        journal.event(
            "checkpoint_rollback", path=path, lastgood=lg, error=err,
        )
        from machine_learning_replications_tpu.utils.trace import stage_say

        stage_say(
            f"checkpoint {path!r} failed to restore ({err}) — rolled back "
            f"to last-known-good {lg!r}"
        )
        return out
