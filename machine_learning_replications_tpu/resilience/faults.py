"""Deterministic process-global fault injection (chaos-engineering primitive).

A robustness claim that was never exercised is a guess: "the server sheds
instead of hanging when the device wedges" is only true once a wedged
device has actually been simulated against a live server and the 503s
counted. This module is the injection half of that loop.

**Sites.** A faultpoint is a named call to ``fire(site)`` woven into a hot
path. The catalog (``SITES``) is closed — arming an unknown site is an
error, so a typo'd chaos spec fails at arm time, not by silently injecting
nothing. graftcheck's ``faultpoint-coherence`` rule (docs/ANALYSIS.md)
keeps the three views — ``fire()`` sites in code, this catalog, and the
docs/RESILIENCE.md table — in exact agreement:

  ==================  =============================================  ==========
  site                where it fires                                 modes
  ==================  =============================================  ==========
  server.parse        ``serve/server.py`` request admission, before  raise delay
                      the body is parsed
  server.respond      before the 200 reply body is written           raise delay
  batcher.flush       ``serve/batcher.py`` flush, before the batch   raise delay
                      is stacked and handed to the engine
  engine.compute      ``serve/engine.py`` ``predict``, before the    raise delay
                      device computation (inside the supervisor's
                      watchdog window — a long delay here IS a
                      wedged device)
  engine.warmup       ``serve/engine.py`` ``warmup`` entry (makes    raise delay
                      supervised restarts fail and retry)
  persist.save        ``persist/orbax_io.py`` after the checkpoint   raise delay
                      tree is written but before it is checksummed   corrupt
                      and published (raise = save interrupted
                      mid-write; corrupt = bytes torn after
                      checksumming)
  persist.restore     ``persist/orbax_io.py`` restore entry           raise delay
                      (corrupt = flip bytes on disk so integrity     corrupt
                      verification must catch it)
  persist.aot_restore ``persist/aot.py`` per-bucket AOT executable   raise delay
                      load (raise = a failing restore; corrupt =     corrupt
                      the blob's bytes torn before deserialization
                      — both must resolve to the engine's journaled
                      fails-open fallback to tracing, docs/AOT.md)
  lifecycle.spawn     ``fleet/lifecycle.py`` replica spawn entry     raise delay
                      (raise = the spawn attempt itself fails;       corrupt
                      corrupt = the manager launches a replica that
                      can never become ready — the ready-deadline
                      branch must catch it and fail closed)
  lifecycle.drain     ``fleet/lifecycle.py`` drain-first retirement  raise delay
                      entry (raise = the retirement is aborted and   corrupt
                      retried; corrupt = the graceful SIGTERM is
                      suppressed, simulating a replica that refuses
                      to drain — the kill-deadline escalation must
                      fire)
  ==================  =============================================  ==========

**Modes.** ``raise`` throws ``InjectedFault`` from the faultpoint;
``delay=SECONDS`` sleeps there; ``corrupt`` returns True from ``fire`` and
the call site applies its own, site-defined corruption (only sites with a
defined corruption accept it — arming ``corrupt`` elsewhere fails).

**Schedules.** Deterministic by construction so a chaos run is replayable:
every call (default), ``@n=K`` (only the K-th call), ``@p=F,seed=S``
(seeded per-arm Bernoulli), ``@once`` (disarm after the first firing),
``@count=K`` (disarm after K firings).

**Spec grammar** (the ``cli serve --inject`` flag and the guarded
``POST /debug/faults`` endpoint both take it)::

    SITE:MODE[=ARG][@OPT[,OPT...]]

    engine.compute:raise                 fail every device compute
    engine.compute:delay=2.5@n=3         wedge only the 3rd compute 2.5 s
    batcher.flush:delay=0.05@p=0.1,seed=7   seeded 10% slow flushes
    persist.restore:corrupt@once         tear the next checkpoint read

Every firing is journaled (``fault_injected``) and counted in the
process-global ``fault_injected_total{site}`` family, so a chaos run's
injections are joinable against the breaker/rollback events they caused.

**Hot-path cost.** ``fire`` with nothing armed is one module-dict truthiness
check — no lock, no allocation — so leaving the faultpoints compiled into
production paths costs nothing measurable (asserted by the serve bench).
"""

from __future__ import annotations

import random
import threading
import time

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY


class InjectedFault(RuntimeError):
    """Raised by an armed raise-mode faultpoint."""


#: site -> modes it supports ("corrupt" only where the call site defines
#: a corruption to apply).
SITES: dict[str, tuple[str, ...]] = {
    "server.parse": ("raise", "delay"),
    "server.respond": ("raise", "delay"),
    "batcher.flush": ("raise", "delay"),
    "engine.compute": ("raise", "delay"),
    "engine.warmup": ("raise", "delay"),
    "persist.save": ("raise", "delay", "corrupt"),
    "persist.restore": ("raise", "delay", "corrupt"),
    "persist.aot_restore": ("raise", "delay", "corrupt"),
    "lifecycle.spawn": ("raise", "delay", "corrupt"),
    "lifecycle.drain": ("raise", "delay", "corrupt"),
}

# Registered at import so the family (and its exposition metadata) exists
# on the first /metrics scrape of a chaos run, before anything fires
# (rule metrics-catalog).
FAULTS_INJECTED = REGISTRY.counter(
    "fault_injected_total",
    "Armed faultpoint firings by injection site (resilience.faults).",
    labels=("site",),
)


class FaultSpec:
    """One parsed injection directive: site, mode, and firing schedule."""

    __slots__ = ("site", "mode", "delay_s", "nth", "prob", "seed", "once",
                 "count")

    def __init__(
        self,
        site: str,
        mode: str,
        delay_s: float = 0.0,
        nth: int | None = None,
        prob: float | None = None,
        seed: int | None = None,
        once: bool = False,
        count: int | None = None,
    ) -> None:
        if site not in SITES:
            raise ValueError(
                f"unknown faultpoint site {site!r}; sites: "
                f"{', '.join(sorted(SITES))}"
            )
        if mode not in SITES[site]:
            raise ValueError(
                f"site {site!r} does not support mode {mode!r} "
                f"(supported: {', '.join(SITES[site])})"
            )
        if mode == "delay" and not delay_s > 0:
            raise ValueError("delay mode needs a positive seconds arg "
                             "(e.g. batcher.flush:delay=0.5)")
        if nth is not None and nth < 1:
            raise ValueError(f"@n must be >= 1, got {nth}")
        if prob is not None and not 0.0 < prob <= 1.0:
            raise ValueError(f"@p must be in (0, 1], got {prob}")
        if count is not None and count < 1:
            raise ValueError(f"@count must be >= 1, got {count}")
        if nth is not None and prob is not None:
            raise ValueError("@n and @p are mutually exclusive")
        self.site = site
        self.mode = mode
        self.delay_s = float(delay_s)
        self.nth = nth
        self.prob = prob
        self.seed = seed
        self.once = once
        self.count = count

    def describe(self) -> str:
        """Round-trippable spec string (the journal/snapshot rendering)."""
        s = f"{self.site}:{self.mode}"
        if self.mode == "delay":
            s += f"={self.delay_s:g}"
        opts = []
        if self.nth is not None:
            opts.append(f"n={self.nth}")
        if self.prob is not None:
            opts.append(f"p={self.prob:g}")
        if self.seed is not None:
            opts.append(f"seed={self.seed}")
        if self.once:
            opts.append("once")
        if self.count is not None:
            opts.append(f"count={self.count}")
        return s + ("@" + ",".join(opts) if opts else "")


def parse_spec(text: str) -> FaultSpec:
    """``SITE:MODE[=ARG][@OPT,...]`` -> FaultSpec (see module docstring)."""
    head, _, opts = text.strip().partition("@")
    site, sep, mode = head.partition(":")
    if not sep or not mode:
        raise ValueError(
            f"bad fault spec {text!r}: expected SITE:MODE[=ARG][@OPTS]"
        )
    mode, _, arg = mode.partition("=")
    delay_s = 0.0
    if mode == "delay":
        if not arg:
            raise ValueError(
                f"bad fault spec {text!r}: delay needs seconds "
                "(delay=SECONDS)"
            )
        delay_s = float(arg)
    elif arg:
        raise ValueError(
            f"bad fault spec {text!r}: mode {mode!r} takes no argument"
        )
    kw: dict = {}
    if opts:
        for opt in opts.split(","):
            key, has_val, val = opt.strip().partition("=")
            if key == "once" and not has_val:
                kw["once"] = True
            elif key == "n" and has_val:
                kw["nth"] = int(val)
            elif key == "p" and has_val:
                kw["prob"] = float(val)
            elif key == "seed" and has_val:
                kw["seed"] = int(val)
            elif key == "count" and has_val:
                kw["count"] = int(val)
            else:
                raise ValueError(
                    f"bad fault spec option {opt.strip()!r} "
                    "(known: n=K, p=F, seed=S, once, count=K)"
                )
    return FaultSpec(site.strip(), mode, delay_s=delay_s, **kw)


class _Armed:
    __slots__ = ("spec", "calls", "fires", "rng")

    def __init__(self, spec: FaultSpec) -> None:
        self.spec = spec
        self.calls = 0
        self.fires = 0
        # Seeded per-arm: a probabilistic schedule replays exactly.
        self.rng = random.Random(spec.seed if spec.seed is not None else 0)


_lock = threading.Lock()
_armed: dict[str, _Armed] = {}
_endpoint_enabled = False


def arm(spec: FaultSpec | str) -> FaultSpec:
    """Arm (or re-arm, replacing) a site's injection. Accepts a parsed
    ``FaultSpec`` or the spec-grammar string."""
    if isinstance(spec, str):
        spec = parse_spec(spec)
    with _lock:
        _armed[spec.site] = _Armed(spec)
    journal.event("fault_armed", site=spec.site, spec=spec.describe())
    return spec


def disarm(site: str) -> bool:
    """Disarm a site; True when something was armed there."""
    with _lock:
        was = _armed.pop(site, None)
    if was is not None:
        journal.event("fault_disarmed", site=site)
    return was is not None


def reset() -> None:
    """Disarm every site (firing counters in the registry are kept —
    counters are monotonic). Journaled like arm/disarm: the injection
    timeline must show WHERE injections stopped, or the chaos replay
    cannot tie recovery to the disarm."""
    with _lock:
        sites = sorted(_armed)
        _armed.clear()
    if sites:
        journal.event("faults_reset", sites=sites)


def snapshot() -> dict:
    """Armed sites with their specs and call/fire counts (the
    ``/debug/faults`` payload)."""
    with _lock:
        return {
            "endpoint_enabled": _endpoint_enabled,
            "armed": {
                site: {
                    "spec": a.spec.describe(),
                    "mode": a.spec.mode,
                    "calls": a.calls,
                    "fires": a.fires,
                }
                for site, a in sorted(_armed.items())
            },
        }


def enable_endpoint() -> None:
    """Allow ``/debug/faults`` to arm/disarm over HTTP. Off by default and
    one-way for the process lifetime: a production server must opt into
    being chaos-driven (``cli serve --inject``/``--fault-endpoint``)."""
    global _endpoint_enabled
    with _lock:
        _endpoint_enabled = True


def endpoint_enabled() -> bool:
    return _endpoint_enabled


def fire(site: str) -> bool:
    """The faultpoint. No-op (and near-free: one dict truthiness check)
    while nothing is armed anywhere. When this site is armed and its
    schedule hits: journal + count the firing, then raise
    (``InjectedFault``), sleep (delay mode), or return True (corrupt mode
    — the call site applies its corruption). Returns False otherwise."""
    if not _armed:  # hot path: unlocked read is exact enough (GIL dict op)
        return False
    with _lock:
        a = _armed.get(site)
        if a is None:
            return False
        a.calls += 1
        spec = a.spec
        if spec.nth is not None:
            hit = a.calls == spec.nth
        elif spec.prob is not None:
            hit = a.rng.random() < spec.prob
        else:
            hit = True
        if not hit:
            return False
        a.fires += 1
        fires = a.fires
        # Exhausted schedules self-disarm: @once and @n fire exactly once
        # by definition, @count after its quota.
        if spec.once or spec.nth is not None or (
            spec.count is not None and fires >= spec.count
        ):
            del _armed[site]
    FAULTS_INJECTED.inc(site=site)
    journal.event(
        "fault_injected", site=site, mode=spec.mode, fire=fires,
        spec=spec.describe(),
    )
    if spec.mode == "delay":
        time.sleep(spec.delay_s)
        return False
    if spec.mode == "raise":
        raise InjectedFault(f"injected fault at {site}")
    return True  # corrupt
