"""Supervised serving engine: watchdog, circuit breaker, backoff restart.

The serving failure mode the batcher alone cannot survive is a *wedged*
engine: a device computation that never returns (driver hang, injected
``engine.compute:delay``) blocks the flush thread forever — the queue
fills, every client stalls to its timeout, and ``/predict`` is down while
``/healthz`` still says ok. The second-worst is a *repeatedly failing*
engine: each flush burns a batch of requests with 500s while the server
keeps admitting more.

``SupervisedEngine`` wraps ``serve.engine.BucketedPredictEngine`` with the
standard production trio:

  * **Watchdog** — every ``predict`` runs on a dedicated worker thread
    with a per-flush deadline. A compute that misses it is abandoned
    (the thread is daemonic and unreachable; the engine is presumed
    wedged) and the caller gets ``ComputeDeadlineExceeded`` — an explicit
    failure in bounded time instead of an unbounded hang.
  * **Circuit breaker** — a deadline miss, or ``breaker_failures``
    consecutive compute failures, opens the breaker. While open,
    ``predict`` raises ``BreakerOpen`` immediately (no device call): the
    server turns that into 503 + ``Retry-After`` — *degraded mode*, load
    shed explicitly while recovery runs off the request path.
  * **Supervised restart** — a daemon restarter rebuilds the engine via
    the factory (fresh executor, fresh jit cache, re-warmed buckets)
    under bounded exponential backoff. Success closes the breaker and —
    if the model-quality feed had been quarantined
    (``quality_feed_disabled``) — re-enables it, journaled
    (``quality_feed_reenabled``). Failure (warmup raising, an armed
    ``engine.warmup`` fault) retries at the capped backoff forever: the
    process stays alive, shedding, until the engine heals.

Every transition is journaled (``breaker_open`` / ``engine_restart`` /
``breaker_close``) and exported through the process-global registry
(``resilience_*`` families), so a chaos run can assert the
open -> shed -> recover arc from the journal and ``/metrics`` alone.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeout

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY

# Registered at import: the families (and their exposition metadata) must
# exist on the first scrape, before any fault ever trips the breaker.
BREAKER_STATE = REGISTRY.gauge(
    "resilience_breaker_state",
    "Serving circuit breaker: 0 closed (healthy), 1 open (degraded, "
    "shedding while the engine restarts).",
)
BREAKER_TRANSITIONS = REGISTRY.counter(
    "resilience_breaker_transitions_total",
    "Circuit-breaker transitions by destination state.",
    labels=("to",),
)
ENGINE_RESTARTS = REGISTRY.counter(
    "resilience_engine_restarts_total",
    "Supervised engine rebuild attempts by result.",
    labels=("result",),
)
WATCHDOG_TRIPS = REGISTRY.counter(
    "resilience_watchdog_trips_total",
    "Flush computations abandoned for missing the per-flush deadline "
    "(wedged-engine detections).",
)
DEGRADED_SHEDS = REGISTRY.counter(
    "resilience_degraded_sheds_total",
    "Requests shed with 503 + Retry-After because the breaker was open.",
)
BREAKER_STATE.get().set(0.0)


class BreakerOpen(RuntimeError):
    """The breaker is open: the request was shed, not computed. Carries
    the server's ``Retry-After`` estimate."""

    def __init__(self, retry_after_s: float) -> None:
        super().__init__(
            "engine degraded: circuit breaker open, restart in progress "
            f"(retry after ~{retry_after_s:.0f}s)"
        )
        self.retry_after_s = retry_after_s


class ComputeDeadlineExceeded(RuntimeError):
    """The flush's device computation missed the watchdog deadline and was
    abandoned (the engine is presumed wedged; the breaker is now open)."""


class _Worker:
    """One daemon thread executing submitted calls in order.

    Deliberately NOT ``ThreadPoolExecutor``: its threads are non-daemonic
    and joined at interpreter exit, so one wedged computation would hang
    process shutdown forever — the exact failure this module exists to
    bound. A wedged ``_Worker`` is simply abandoned (daemon threads die
    with the process) and replaced on restart."""

    def __init__(self) -> None:
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._thread = threading.Thread(
            target=self._loop, name="engine-worker", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                # Stop: fail anything that raced in behind the sentinel —
                # a silently unexecuted future would stall its caller the
                # full watchdog deadline for nothing.
                while True:
                    try:
                        leftover = self._q.get_nowait()
                    except queue.Empty:
                        return
                    if leftover is None:
                        continue
                    _fn, _args, fut = leftover
                    if fut.set_running_or_notify_cancel():
                        fut.set_exception(
                            RuntimeError("engine worker stopped")
                        )
            fn, args, fut = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as exc:  # delivered, never kills the loop
                fut.set_exception(exc)

    def submit(self, fn, *args) -> Future:
        fut: Future = Future()
        self._q.put((fn, args, fut))
        return fut

    def stop(self) -> None:
        self._q.put(None)


class SupervisedEngine:
    """Watchdog + circuit breaker + backoff-restart wrapper around a
    bucketed predict engine. Drop-in for the batcher/server: ``predict``,
    ``bucket_for``, ``warmup``, ``compile_count`` and the introspection
    attributes all delegate to the current engine.

    ``engine`` is the initial (possibly still cold — ``make_server`` warms
    after binding) engine; ``factory()`` must build **and warm** a
    replacement, and is only ever called off the request path by the
    restarter thread.
    """

    def __init__(
        self,
        engine,
        factory,
        flush_deadline_s: float = 20.0,
        breaker_failures: int = 3,
        restart_backoff_s: float = 0.5,
        restart_backoff_max_s: float = 30.0,
    ) -> None:
        if flush_deadline_s <= 0:
            raise ValueError("flush_deadline_s must be > 0")
        if breaker_failures < 1:
            raise ValueError("breaker_failures must be >= 1")
        if restart_backoff_s <= 0 or restart_backoff_max_s < restart_backoff_s:
            raise ValueError(
                "need 0 < restart_backoff_s <= restart_backoff_max_s"
            )
        self._engine = engine
        self._factory = factory
        self._deadline_s = float(flush_deadline_s)
        self._breaker_failures = int(breaker_failures)
        self._backoff_s = float(restart_backoff_s)
        self._backoff_max_s = float(restart_backoff_max_s)
        self._lock = threading.Lock()
        self._state = "closed"
        self._fail_streak = 0
        self._opened_at: float | None = None
        self._open_reason: str | None = None
        self._restart_attempts = 0
        self._restarts_completed = 0
        self._next_attempt_at: float | None = None
        self._closed = False
        self._worker = _Worker()
        # NO gauge reset here: the breaker-state series is process-global
        # and initialized once at module import — a second in-process
        # server constructing its supervisor must not publish a phantom
        # 'closed' over another server's open breaker.

    # -- delegation ---------------------------------------------------------
    # The current engine can be swapped by the restarter at any moment, so
    # every delegate reads self._engine exactly once (reference swap is
    # atomic under the GIL).

    @property
    def params(self):
        return self._engine.params

    @property
    def buckets(self):
        return self._engine.buckets

    @property
    def warm(self) -> bool:
        return self._engine.warm

    @property
    def n_features(self) -> int:
        return self._engine.n_features

    @property
    def quality(self):
        return self._engine.quality

    @property
    def trace_counts(self):
        return self._engine.trace_counts

    def bucket_for(self, n: int) -> int:
        return self._engine.bucket_for(n)

    def plan_batch(self, n: int) -> tuple[int, ...]:
        inner = getattr(self._engine, "plan_batch", None)
        if inner is not None:
            return inner(n)
        # Test doubles without shaping: one covering bucket, the
        # pre-shaping contract.
        return (self._engine.bucket_for(n),)

    def compile_count(self) -> int:
        return self._engine.compile_count()

    def warmup(self, say=None):
        """Initial warmup (make_server, after the listener binds) — not
        deadline-guarded: startup compiles are legitimately long."""
        return self._engine.warmup(say=say)

    # -- breaker ------------------------------------------------------------

    @property
    def breaker_open(self) -> bool:
        return self._state == "open"

    def retry_after_s(self) -> float:
        """The degraded-mode ``Retry-After`` estimate: time to the next
        restart attempt (floor 1 s — clients should not stampede)."""
        with self._lock:
            if self._state != "open":
                return 0.0
            eta = (
                self._next_attempt_at - time.monotonic()
                if self._next_attempt_at is not None else self._backoff_s
            )
        return max(1.0, eta)

    def snapshot(self) -> dict:
        """Breaker/restart state for ``/healthz`` and chaos assertions."""
        with self._lock:
            open_for = (
                round(time.monotonic() - self._opened_at, 3)
                if self._opened_at is not None and self._state == "open"
                else None
            )
            return {
                "state": self._state,
                "fail_streak": self._fail_streak,
                "open_reason": self._open_reason,
                "open_for_seconds": open_for,
                "restart_attempts": self._restart_attempts,
                "restarts_completed": self._restarts_completed,
                "flush_deadline_seconds": self._deadline_s,
            }

    def _trip(self, reason: str, wedged: bool = False) -> None:
        with self._lock:
            if self._state == "open":
                return  # already degraded; the restarter is running
            self._state = "open"
            self._opened_at = time.monotonic()
            self._open_reason = reason
            self._restart_attempts = 0
            if wedged:
                # The worker thread is stuck inside the computation:
                # abandon it and give the restarter a fresh one. The
                # sentinel lets the old loop exit once the stuck call
                # finally returns — without it, every wedge recovery
                # would leak an idle thread (and its captured engine)
                # for the process lifetime.
                self._worker.stop()
                self._worker = _Worker()
            # State gauge/journal emitted INSIDE the lock: an open and a
            # close racing on the lock boundary must publish in the order
            # they happened, or /metrics could read 'closed' (and the
            # journal end on breaker_close) while the breaker is open.
            BREAKER_STATE.get().set(1.0)
            BREAKER_TRANSITIONS.inc(to="open")
            journal.event("breaker_open", reason=reason, wedged=wedged)
        threading.Thread(
            target=self._restart_loop, name="engine-restarter", daemon=True
        ).start()

    def _restart_loop(self) -> None:
        attempt = 0
        while not self._closed:
            # Exponent clamped: the cap is reached within ~30 doublings,
            # and an unbounded 2**attempt would eventually overflow float
            # range and kill the restarter — leaving the breaker open
            # forever with nobody retrying.
            delay = min(
                self._backoff_max_s,
                self._backoff_s * (2 ** min(attempt, 30)),
            )
            with self._lock:
                self._next_attempt_at = time.monotonic() + delay
            time.sleep(delay)
            if self._closed:
                return  # supervisor shut down mid-backoff: stop rebuilding
            attempt += 1
            with self._lock:
                self._restart_attempts = attempt
            t0 = time.monotonic()
            try:
                # factory() builds AND warms; warming doubles as the probe
                # (it runs a blocked predict per bucket), so a closed
                # breaker means real computes succeeded.
                engine = self._factory()
            except BaseException as exc:
                ENGINE_RESTARTS.inc(result="failed")
                journal.event(
                    "engine_restart", attempt=attempt, ok=False,
                    seconds=round(time.monotonic() - t0, 3),
                    error=f"{type(exc).__name__}: {exc}",
                )
                continue
            with self._lock:
                self._engine = engine
                self._state = "closed"
                self._fail_streak = 0
                self._restarts_completed += 1
                opened_at = self._opened_at
                self._opened_at = None
                self._next_attempt_at = None
                # Close bookkeeping under the lock, mirroring _trip: a
                # flush that re-trips the instant the state flips must
                # serialize AFTER these, so the published order is always
                # close-then-open and the gauge never reads 0 while open.
                ENGINE_RESTARTS.inc(result="ok")
                BREAKER_STATE.get().set(0.0)
                BREAKER_TRANSITIONS.inc(to="closed")
                journal.event(
                    "engine_restart", attempt=attempt, ok=True,
                    seconds=round(time.monotonic() - t0, 3),
                )
                journal.event(
                    "breaker_close", attempts=attempt,
                    open_seconds=(
                        round(time.monotonic() - opened_at, 3)
                        if opened_at is not None else None
                    ),
                )
            # Supervised quality-feed re-enable: the engine quarantines a
            # crashing feed (sets engine.quality = None, monitor disabled).
            # The rebuilt engine holds a fresh reference; clear the
            # monitor's quarantine so monitoring resumes instead of
            # latching dead until process restart.
            monitor = getattr(engine, "quality", None)
            reenable = getattr(monitor, "reenable", None)
            if reenable is not None and reenable():
                journal.event("quality_feed_reenabled", after="engine_restart")
            return

    # -- rolling deploy -------------------------------------------------------

    def swap_engine(self, engine, factory=None) -> None:
        """Atomically replace the live engine with an already-built,
        already-WARM one — the rolling-deploy promotion step
        (``serve.server`` /admin/deploy; docs/FLEET.md). The swap is a
        reference assignment under the breaker lock, so in-flight flushes
        finish on the engine they were submitted to and the next flush
        runs the new one: no request ever observes a half-switched state.

        ``factory`` (when given) also becomes the supervised-restart
        rebuild path — without this, a post-deploy breaker trip would
        "recover" by resurrecting the PREVIOUS model version.

        Refused while the breaker is open: the restarter is concurrently
        rebuilding the OLD engine and the two swaps would race; a
        degraded replica is out of rotation anyway, so the deploy
        controller retries it after recovery."""
        with self._lock:
            if self._state == "open":
                raise RuntimeError(
                    "cannot swap engines while the breaker is open "
                    "(supervised restart in progress)"
                )
            self._engine = engine
            if factory is not None:
                self._factory = factory
            self._fail_streak = 0
            journal.event("engine_swap", warm=bool(engine.warm))

    # -- the guarded compute path -------------------------------------------

    def predict(self, X):
        """``engine.predict`` behind the watchdog and breaker. Raises
        ``BreakerOpen`` instantly while degraded and
        ``ComputeDeadlineExceeded`` on a wedged compute; engine exceptions
        propagate unchanged (after feeding the failure streak)."""
        return self.predict_tagged(X)[0]

    def predict_tagged(self, X):
        """``predict`` plus the ``model_version`` of the engine that ran
        the compute, captured under the same lock ``swap_engine`` takes —
        the ONLY read that is guaranteed consistent with the bits. Around
        a rolling deploy, handle-level version state can already name the
        next version while an in-flight flush finishes on the old engine;
        reply headers must be built from this tag, not that state."""
        with self._lock:
            # Check + submit under ONE lock acquisition: a wedge trip
            # swapping workers serializes against this, so a submit can
            # never land on a worker after its stop sentinel (the
            # lost-future would otherwise stall its flush the full
            # deadline against a healthy post-restart engine).
            if self._state == "open":
                retry_after = (
                    self._next_attempt_at - time.monotonic()
                    if self._next_attempt_at is not None
                    else self._backoff_s
                )
                raise BreakerOpen(max(1.0, retry_after))
            engine = self._engine
            fut = self._worker.submit(engine.predict, X)
        try:
            out = fut.result(timeout=self._deadline_s)
        except FuturesTimeout:
            WATCHDOG_TRIPS.inc()
            msg = (
                f"compute exceeded the {self._deadline_s:g}s flush "
                "deadline; engine presumed wedged"
            )
            self._trip(msg, wedged=True)
            raise ComputeDeadlineExceeded(msg) from None
        except BaseException as exc:
            with self._lock:
                self._fail_streak += 1
                streak = self._fail_streak
            if streak >= self._breaker_failures:
                self._trip(
                    f"{streak} consecutive compute failures "
                    f"(last: {type(exc).__name__}: {exc})"
                )
            raise
        with self._lock:
            self._fail_streak = 0
        return out, getattr(engine, "model_version", None)

    def close(self) -> None:
        """Stop the worker thread AND any in-flight restarter (idempotent).
        Without the flag, a supervisor shut down while the breaker is
        open would keep rebuilding and re-warming engines — full jit
        compiles every backoff interval — for the process lifetime,
        serving nobody."""
        self._closed = True
        self._worker.stop()
