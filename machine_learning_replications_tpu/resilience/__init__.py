"""Resilience layer — fault injection, supervised serving, checkpoint rollback.

The serving path (serve/) and the observability stack (obs/) can *see*
failures; this package makes the system *survive* them, and proves it the
only credible way: by injecting the faults deterministically and asserting
the degradation contract under test (``tests/test_resilience.py``,
``tools/chaos_drill.py``, the CI chaos job).

  * ``faults`` — a process-global, deterministic fault-injection registry.
    Named sites woven into serve/persist hot paths can be armed to raise,
    delay, or corrupt on a seeded schedule; every firing is journaled and
    counted (``fault_injected_total{site}``). Zero measurable cost while
    nothing is armed.
  * ``supervisor`` — ``SupervisedEngine`` wraps the bucketed predict
    engine with a per-flush watchdog deadline and a circuit breaker:
    a wedged or repeatedly-failing compute trips the breaker, ``/predict``
    sheds with an explicit 503 + ``Retry-After`` while a bounded
    exponential-backoff restart rebuilds and re-warms the engine off the
    request path, and every transition is journaled and exported
    (``resilience_*`` metric families).
  * ``lastgood`` — last-known-good checkpoint retention and rollback:
    ``persist.orbax_io`` publishes checkpoints atomically with a content
    checksum manifest and retains the previous checkpoint; a torn or
    corrupt restore falls back to it (journaled ``checkpoint_rollback``)
    so a bad deploy degrades to the previous model, not a dead server.

The degradation contract, chaos-verified end to end: under every injected
fault class a client gets either a correct answer or an explicit shed —
never a wrong answer, never a hang (docs/RESILIENCE.md).
"""

from machine_learning_replications_tpu.resilience.faults import (  # noqa: F401
    InjectedFault,
    arm,
    disarm,
    fire,
    parse_spec,
    reset,
)
from machine_learning_replications_tpu.resilience.supervisor import (  # noqa: F401
    BreakerOpen,
    ComputeDeadlineExceeded,
    SupervisedEngine,
)
