"""Dense linear-algebra primitives shaped for the MXU.

Pairwise distances and kernel matrices are written as one big matmul plus
rank-1 corrections (``‖x−y‖² = ‖x‖² + ‖y‖² − 2x·y``) so XLA tiles them onto
the systolic array — the TPU replacement for libsvm's scalar kernel loops
(reference reaches them via ``SVC`` at ``train_ensemble_public.py:44``).
"""

from __future__ import annotations

import jax.numpy as jnp


def pairwise_sq_dists(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """``out[i, j] = ‖x_i − y_j‖²`` via a single (n,d)·(d,m) matmul.

    Clamped at 0 to kill the small negative values the rank-1 form can
    produce in low precision.
    """
    xx = jnp.sum(x * x, axis=-1, keepdims=True)
    yy = jnp.sum(y * y, axis=-1, keepdims=True)
    d2 = xx + yy.T - 2.0 * (x @ y.T)
    return jnp.maximum(d2, 0.0)


def rbf_kernel(x: jnp.ndarray, y: jnp.ndarray, gamma) -> jnp.ndarray:
    """``exp(-γ‖x−y‖²)`` — the SVC kernel as an MXU matmul + fused exp."""
    return jnp.exp(-gamma * pairwise_sq_dists(x, y))


def masked_pairwise_sq_dists_dense_query(
    x: jnp.ndarray, y: jnp.ndarray
) -> jnp.ndarray:
    """``masked_pairwise_sq_dists`` for the case where every query row is
    fully observed (or entirely NaN — the chunk-padding sentinel, which
    propagates to NaN distances as required).

    The query-side mask machinery collapses: mutual presence equals donor
    presence, the per-pair rescale factor depends only on the donor, and
    the three masked matmuls become one real matmul plus rank-1
    corrections — measured 6.8× (81 → 12 ms on a [2048, 17] × [400, 17]
    block) on the bulk-scoring imputer's contract-pattern hot path, where
    this exact shape runs once per streamed chunk. Same semantics:
    ``n_features / n_present`` rescale, 0-clamp, NaN where the pair shares
    no coordinate.
    """
    my = ~jnp.isnan(y)
    y0 = jnp.where(my, y, 0.0)
    sq = (
        (x * x) @ my.T.astype(x.dtype)
        - 2.0 * (x @ y0.T)
        + jnp.sum(y0 * y0, axis=1)[None, :]
    )
    n_present = jnp.sum(my, axis=1).astype(x.dtype)  # [m] — donor-only
    scale = x.shape[-1] / jnp.maximum(n_present, 1.0)
    d2 = jnp.maximum(sq * scale[None, :], 0.0)  # NaN queries propagate
    return jnp.where(n_present[None, :] > 0, d2, jnp.nan)


def masked_pairwise_sq_dists(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """NaN-aware squared distances, scaled by the fraction of usable coords.

    Matches sklearn's ``nan_euclidean_distances`` (squared=True) semantics
    used by ``KNNImputer`` (reference: ``train_ensemble_public.py:37``):
    coordinates missing in either row are dropped and the sum is rescaled by
    ``n_features / n_present``. Pairs with no shared coordinate come out NaN.

    Written as three matmuls over NaN-zeroed copies so it stays on the MXU.
    """
    mx = ~jnp.isnan(x)
    my = ~jnp.isnan(y)
    x0 = jnp.where(mx, x, 0.0)
    y0 = jnp.where(my, y, 0.0)
    # Σ over present-in-both coords of (x² + y² − 2xy), via masked matmuls.
    xx = (x0 * x0) @ my.T.astype(x0.dtype)
    yy = mx.astype(y0.dtype) @ (y0 * y0).T
    xy = x0 @ y0.T
    d2 = xx + yy - 2.0 * xy
    n_present = mx.astype(x0.dtype) @ my.T.astype(x0.dtype)
    scale = x.shape[-1] / jnp.maximum(n_present, 1.0)
    d2 = jnp.maximum(d2 * scale, 0.0)
    return jnp.where(n_present > 0, d2, jnp.nan)
