"""L0 — device compute primitives.

The reference reaches its native compute through sklearn's C/C++/Cython
internals (SURVEY.md §2.4); this package is their TPU-native replacement:
MXU-friendly dense linear algebra, histogram/split kernels (XLA and Pallas
backends), and device-side metrics.
"""

from machine_learning_replications_tpu.ops.linalg import (
    pairwise_sq_dists,
    rbf_kernel,
)

__all__ = ["pairwise_sq_dists", "rbf_kernel"]
