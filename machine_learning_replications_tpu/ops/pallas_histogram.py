"""Pallas TPU kernel for per-(node, feature, bin) gradient histograms.

This is the hot op of histogram-based tree growing — the TPU-native
replacement for sklearn's Cython ``BestSplitter`` statistics pass
(SURVEY.md §2.4: "Pallas kernels for per-tree histogram construction"),
and the kernel named by BASELINE.json's north star.

Design (why it looks like this):

  * TPU scatters serialize onto the scalar unit, so the scatter-add that a
    histogram "wants" is recast as **one-hot × values matmuls on the MXU**:
    for each feature, rows one-hot-encode their (node, bin) cell and a
    ``[4, R] × [R, K·B]`` contraction accumulates all four statistics
    (Σg, Σh, Σg², count) in a single pass through the systolic array.
  * The grid walks row blocks; the output block is **revisited** by every
    grid step (constant index map) so partials accumulate in VMEM and HBM
    is touched once — the reference's equivalent loop re-walks main memory
    per node (sklearn ``DepthFirstTreeBuilder``).
  * Inactive rows (parked at an ancestor leaf, or padding) carry zeroed
    values, so they fall out of the contraction arithmetically — no masks
    in the inner loop, no divergent control flow.

The kernel runs in Mosaic on TPU and in interpret mode elsewhere (the CPU
test mesh), selected automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from machine_learning_replications_tpu.ops.histogram import NodeHistograms

# Per-block VMEM budget for the one-hot operand (bytes). The one-hot block
# is [R, K·B] in the accumulation dtype; R adapts to stay under this.
_ONEHOT_VMEM_BUDGET = 4 * 1024 * 1024


def _row_block(kb: int, itemsize: int) -> int:
    r = _ONEHOT_VMEM_BUDGET // max(kb * itemsize, 1)
    r = max(8, min(1024, r))
    return (r // 8) * 8  # sublane-aligned


def _histogram_kernel(binned_ref, seg_ref, vals_ref, out_ref, *, n_feat, kb):
    """One row block: per feature, one-hot (node,bin) cells and contract.

    binned_ref: [R, F] integer bin ids (any width; widened in-register)
    seg_ref:    [R, 1] int32 — node·B offset (clamped; inactive rows have
                zeroed vals so their cell contribution vanishes)
    vals_ref:   [R, S] — per-row statistics (S is static; the node path
                stacks (grad, hess, grad², active), the stump path only
                (grad, hess))
    out_ref:    [S, F, K·B] — accumulated across the row-block grid
    """
    step = pl.program_id(0)
    vals = vals_ref[:]                                   # [R, S]
    dtype = vals.dtype
    bb = binned_ref[:].astype(jnp.int32)                 # [R, F]
    col = jax.lax.broadcasted_iota(jnp.int32, (bb.shape[0], kb), 1)
    node_off = seg_ref[:]                                # [R, 1]
    partials = []
    for f in range(n_feat):
        seg_f = node_off + bb[:, f][:, None]             # [R, 1]
        onehot = (seg_f == col).astype(dtype)            # [R, K·B]
        partials.append(jax.lax.dot_general(
            vals, onehot,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=dtype,
            # One-hot entries are exact {0,1}: full f32 passes keep the
            # accumulated statistics at f32 precision (a single bf16 MXU
            # pass costs ~3 decimal digits on the sums).
            precision=jax.lax.Precision.HIGHEST,
        ))                                               # each [S, K·B]
    block = jnp.stack(partials, axis=1)                  # [S, F, K·B]

    @pl.when(step == 0)
    def _():
        out_ref[:] = block

    @pl.when(step != 0)
    def _():
        out_ref[:] = out_ref[:] + block


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _stats_histograms(binned, seg, vals, kb: int, interpret: bool):
    """Shared pallas_call wrapper: ``[n, F]`` bins + ``[n, 1]`` segment
    offsets + ``[n, S]`` stats → ``[S, F, kb]`` accumulated sums. Rows are
    padded to the adaptive block size; pad rows carry zeroed stats."""
    n, F = binned.shape
    S = vals.shape[1]
    dtype = vals.dtype
    R = _row_block(kb, jnp.dtype(dtype).itemsize)
    n_pad = ((n + R - 1) // R) * R
    pad = n_pad - n
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
        seg = jnp.pad(seg, ((0, pad), (0, 0)))
    return pl.pallas_call(
        functools.partial(_histogram_kernel, n_feat=F, kb=kb),
        grid=(n_pad // R,),
        in_specs=[
            pl.BlockSpec((R, F), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((R, S), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (S, F, kb), lambda i: (0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((S, F, kb), dtype),
        interpret=interpret,
    )(binned, seg, vals)


@functools.partial(
    jax.jit, static_argnames=("n_nodes", "max_bins", "interpret")
)
def node_histograms_pallas(
    binned: jnp.ndarray,      # [n, F] int32
    node_local: jnp.ndarray,  # [n] int32 — local node index, −1 ⇒ inactive
    grad: jnp.ndarray,        # [n]
    hess: jnp.ndarray,        # [n]
    n_nodes: int,
    max_bins: int,
    interpret: bool | None = None,
) -> NodeHistograms:
    """Drop-in Pallas replacement for ``ops.histogram.node_histograms``."""
    if interpret is None:
        interpret = _use_interpret()
    n, F = binned.shape
    K, B = n_nodes, max_bins
    dtype = jnp.result_type(grad.dtype, jnp.float32)

    active = (node_local >= 0).astype(dtype)
    g = grad.astype(dtype) * active
    h = hess.astype(dtype) * active
    vals = jnp.stack([g, h, g * g, active], axis=1)          # [n, 4]
    seg = (jnp.maximum(node_local, 0).astype(jnp.int32) * B)[:, None]

    out = _stats_histograms(
        binned.astype(jnp.int32), seg, vals, K * B, interpret
    )
    # [4, F, K, B] → per-stat [K, F, B]
    stats = out.reshape(4, F, K, B).transpose(0, 2, 1, 3)
    return NodeHistograms(
        grad=stats[0], hess=stats[1], grad2=stats[2], count=stats[3]
    )


@functools.partial(jax.jit, static_argnames=("max_bins", "interpret"))
def stump_histograms_pallas(
    binned: jnp.ndarray,  # [n, F] integer bin ids (u8 at the fused call site)
    grad: jnp.ndarray,    # [n]
    hess: jnp.ndarray,    # [n]
    max_bins: int,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """K=1 specialization feeding the fused depth-1 boosting stage: only
    the two per-stage statistics travel through the MXU (counts are static
    per fit and Σg² is a scalar the caller reduces directly), halving the
    contraction FLOPs vs the 4-stat node kernel. Returns ``[2, F, B]``
    (grad, hess). ``binned`` keeps its narrow dtype end to end — at bench
    scale the u8 bin matrix is the only O(n·F) array the stage reads."""
    if interpret is None:
        interpret = _use_interpret()
    dtype = jnp.result_type(grad.dtype, jnp.float32)
    vals = jnp.stack([grad.astype(dtype), hess.astype(dtype)], axis=1)
    seg = jnp.zeros((binned.shape[0], 1), jnp.int32)
    return _stats_histograms(binned, seg, vals, max_bins, interpret)
