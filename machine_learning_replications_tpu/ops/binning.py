"""Feature quantization for histogram-based tree growing.

sklearn's ``BestSplitter`` (Cython, reached via ``GradientBoostingClassifier``
at ``train_ensemble_public.py:45``) enumerates *exact* sorted thresholds per
node. The TPU-native replacement quantizes each feature once, up-front, into
at most ``n_bins`` ordered bins; split search then scans bin boundaries
(``ops.histogram``). Two regimes, one representation:

  * ``n_unique <= n_bins`` — bins are the unique values themselves and the
    candidate thresholds are the midpoints between adjacent unique values,
    which is **bit-identical to sklearn's exact enumeration**. The HF
    cohort's 17 features are mostly binary (SURVEY.md §7 "Hard parts"), so
    the reference workload always runs in this exact regime.
  * ``n_unique > n_bins`` — quantile-spaced subset of the midpoints
    (XGBoost/LightGBM-style approximate splitting) for the scaled configs.

Binning is host-side numpy at ingest time (one pass, like a quantile
sketch); training afterwards touches only the int32 bin matrix on device.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np


@dataclasses.dataclass(frozen=True)
class BinnedFeatures:
    """Quantized design matrix + the threshold table to decode splits."""

    binned: np.ndarray      # [n, F] int32 — bin index per value
    thresholds: np.ndarray  # [F, n_bins-1] float64 — candidate split values,
                            # +inf past the last real boundary of a feature
    n_bins: np.ndarray      # [F] int32 — real bin count per feature

    @property
    def max_bins(self) -> int:
        return self.thresholds.shape[1] + 1


def bin_features(X: np.ndarray, n_bins: int | None = 256) -> BinnedFeatures:
    """Quantize ``X[n, F]`` column-wise into at most ``n_bins`` bins.

    ``n_bins=None`` disables the cap: every unique-value midpoint becomes a
    candidate threshold — the exact-enumeration regime of sklearn's
    ``BestSplitter`` at any cardinality (``GBDTConfig.splitter='exact'``).

    A value lands in bin ``b`` = number of thresholds strictly below it;
    "split at boundary b" then means "go left iff bin <= b", and the
    real-valued threshold stored in the fitted tree is ``thresholds[f, b]``
    (a midpoint, matching sklearn's ``(v_i + v_{i+1})/2``).
    """
    n, F = X.shape
    uniques = []
    for f in range(F):
        u = np.unique(X[:, f])  # sorted, NaN would sort last — reject it
        if np.isnan(u).any():
            raise ValueError(f"feature {f} contains NaN; impute before binning")
        if n_bins is not None and u.size > n_bins:
            # Quantile-spaced representative subset (keep extremes).
            q = np.linspace(0, 1, n_bins)
            idx = np.unique((q * (u.size - 1)).round().astype(int))
            u = u[idx]
        uniques.append(u)
    width = max(max(u.size for u in uniques) - 1, 1)
    thresholds = np.full((F, width), np.inf)
    counts = np.ones(F, np.int32)
    binned = np.zeros((n, F), np.int32)
    for f, u in enumerate(uniques):
        mids = (u[:-1] + u[1:]) / 2.0
        # sklearn guard (BestSplitter): if the midpoint rounds up to the upper
        # value, use the lower value as the threshold so the upper sample
        # still routes right under "x <= t goes left".
        mids = np.where(mids == u[1:], u[:-1], mids)
        thresholds[f, : mids.size] = mids
        counts[f] = u.size
        # bin(v) = #{mids < v}, except v exactly equal to a midpoint stays in
        # the left bin — searchsorted(side='left') gives precisely that.
        binned[:, f] = np.searchsorted(mids, X[:, f], side="left")
    return BinnedFeatures(binned=binned, thresholds=thresholds, n_bins=counts)


def rebin_with_thresholds(
    X: np.ndarray, thresholds: np.ndarray, n_bins: np.ndarray | None = None
) -> np.ndarray:
    """Bin ALL rows of ``X`` against an existing threshold table: bin id =
    number of real thresholds strictly below the value (the exact
    convention ``bin_features`` uses, so rows that were in the table's fit
    set reproduce their original ids bit-for-bit). Rows outside the fit
    set land in the nearest edge bin — the per-fold-binning path uses this
    to give every (masked) row an id under each fold's own candidates.

    ``n_bins`` (per-feature real bin counts) selects the real boundaries as
    ``thresholds[f, :n_bins[f]-1]`` — required for exactness when a
    feature's data contains ±inf (a −inf midpoint is a REAL boundary that
    an isfinite filter would drop, shifting every id down by one). Without
    it, boundaries are taken as the finite entries (valid whenever the
    data itself is finite, since the pad value is +inf).
    """
    n, F = X.shape
    out = np.zeros((n, F), np.int32)
    for f in range(F):
        thr = thresholds[f]
        if n_bins is not None:
            thr = thr[: int(n_bins[f]) - 1]
        else:
            thr = thr[np.isfinite(thr)]
        out[:, f] = np.searchsorted(thr, X[:, f], side="left")
    return out


def feature_bin_counts(bins: BinnedFeatures) -> tuple[int, ...]:
    """Static per-feature bin counts — the matmul histogram backend's
    traffic lever (it sizes each feature's one-hot to its real bin range)."""
    return tuple(int(x) for x in np.asarray(bins.n_bins))


ROW_CHUNK = 65_536


def chunked_row_reduce(Xj, per_chunk_fn, pad_value=0, chunk: int = ROW_CHUNK):
    """Apply ``per_chunk_fn([chunk, F]) -> [chunk-reduced ...]`` over row
    chunks of ``Xj [n, F]`` via ``lax.map`` and stack the results.

    Shared scaffolding for dense compare+reduce passes (quantile binning,
    ``left_count`` histograms) whose broadcast intermediate ``[n, B, F]``
    must never materialize at full n: rows pad to a chunk multiple with
    ``pad_value`` (pick one the reduction ignores), and the caller either
    un-pads positional output or relies on the pad value's neutrality.
    Returns ``(mapped, n_pad)`` — ``mapped`` has leading dim n_pad//chunk.
    """
    import jax
    import jax.numpy as jnp

    n = Xj.shape[0]
    if n == 0:
        raise ValueError("chunked_row_reduce: zero-row input")
    # Equalize chunk sizes (rounded to a lane-friendly 1024) instead of
    # padding the tail to a full ROW_CHUNK: n just past a chunk boundary
    # would otherwise waste up to a whole chunk of dense compare+reduce
    # (31% at n=100k); this caps the waste at <1024 rows per chunk (<1.6%).
    n_chunks = max(1, -(-n // chunk))
    chunk = -(-(-(-n // n_chunks)) // 1024) * 1024
    n_pad = n_chunks * chunk
    Xp = jnp.pad(
        Xj, ((0, n_pad - n),) + ((0, 0),) * (Xj.ndim - 1),
        constant_values=pad_value,
    )
    mapped = jax.lax.map(
        per_chunk_fn, Xp.reshape((n_pad // chunk, chunk) + Xj.shape[1:])
    )
    return mapped, n_pad


def device_binning_core(Xj, n_bins: int):
    """Traced body of ``bin_features_device``: pure jnp, safe to call inside
    an enclosing ``jax.jit`` (the fused depth-1 fit inlines it so binning,
    layout, and boosting ship to the device as ONE program — each separate
    blocking dispatch costs a full round trip on a tunneled backend).

    Returns ``(binned [n,F] int32, mids [n_bins-1, F], nan_flag scalar
    bool)``. The NaN *check* is the caller's job — a traced value cannot
    raise — so callers sync on ``nan_flag`` exactly once, after everything
    is enqueued.
    """
    import jax
    import jax.numpy as jnp

    n, F = Xj.shape
    if n == 0:  # shape is static — this raises at trace time, not runtime
        raise ValueError("device binning: zero-row input")
    nan_flag = jnp.isnan(Xj).any()
    Xs = jnp.sort(Xj, axis=0)                              # [n, F]
    q_idx = jnp.round(
        jnp.linspace(0.0, 1.0, n_bins) * (n - 1)
    ).astype(jnp.int32)
    u = Xs[q_idx, :]                                       # [B, F] candidates
    mids = (u[:-1] + u[1:]) / 2.0
    # sklearn BestSplitter guard: a midpoint that rounds up to the upper
    # value would mis-route the upper sample under "x <= t goes left".
    mids = jnp.where(mids == u[1:], u[:-1], mids)          # [B-1, F]
    # bin(v) = #{mids < v} (== searchsorted side='left' on sorted mids; a
    # value equal to a midpoint stays in the left bin). Computed as a
    # broadcast compare + sum instead of searchsorted: the binary search
    # lowers to log(B) serialized dynamic gathers on TPU (~0.27 s at
    # 200k×17, the single biggest piece of the fit), while compare+reduce
    # fuses into one dense VPU pass over [chunk, B-1, F], row-chunked via
    # ``chunked_row_reduce`` so the broadcast intermediate never
    # materializes at full n.
    def _bin_chunk(xc):                                    # [chunk, F]
        return jnp.sum(
            xc[:, None, :] > mids[None, :, :], axis=1, dtype=jnp.int32
        )
    mapped, n_pad = chunked_row_reduce(Xj, _bin_chunk)
    binned = mapped.reshape(n_pad, F)[:n]                  # [n, F] int32
    return binned, mids, nan_flag


@functools.lru_cache(maxsize=None)
def _device_binning_core_jit():
    """Cached ``jit`` of the binning core: eager execution issues one
    tunneled dispatch per op on the remote TPU backend (~30 s of round
    trips at 1M rows for ~0.1 s of device work, measured r3); jax stays a
    function-local import per this module's loading discipline."""
    import jax

    return jax.jit(device_binning_core, static_argnums=1)


def bin_features_device(X, n_bins: int = 256) -> BinnedFeatures:
    """Device-side quantile binning for the scaled regime.

    ``bin_features`` runs ``np.unique`` per column — ~20 s of host time at
    10M rows, dwarfing the sharded fit it feeds. This variant sorts each
    column on device and takes ``n_bins`` *empirical* quantile candidates
    (duplicates weighted, LightGBM-style) rather than unique-value
    quantiles. Duplicate candidates yield duplicate midpoints — harmless:
    the extra boundaries describe the same row partition, so split gains
    tie and selection's first-index tie-break picks a boundary whose
    threshold routes identically. The returned ``BinnedFeatures`` carries
    device arrays; ``n_bins`` is reported as the candidate count (bin ids
    still index midpoints the same way as the host build).
    """
    import jax.numpy as jnp

    Xj = jnp.asarray(X)
    binned, mids, nan_flag = _device_binning_core_jit()(Xj, n_bins)
    # Same contract as the host path: binning NaNs silently distorts the
    # candidate set (they sort last), so refuse — impute first. One sync,
    # after the whole pipeline above is already in flight.
    if bool(nan_flag):
        raise ValueError("input contains NaN; impute before binning")
    counts = np.full(Xj.shape[1], n_bins, np.int32)
    return BinnedFeatures(binned=binned, thresholds=mids.T, n_bins=counts)
