"""Feature quantization for histogram-based tree growing.

sklearn's ``BestSplitter`` (Cython, reached via ``GradientBoostingClassifier``
at ``train_ensemble_public.py:45``) enumerates *exact* sorted thresholds per
node. The TPU-native replacement quantizes each feature once, up-front, into
at most ``n_bins`` ordered bins; split search then scans bin boundaries
(``ops.histogram``). Two regimes, one representation:

  * ``n_unique <= n_bins`` — bins are the unique values themselves and the
    candidate thresholds are the midpoints between adjacent unique values,
    which is **bit-identical to sklearn's exact enumeration**. The HF
    cohort's 17 features are mostly binary (SURVEY.md §7 "Hard parts"), so
    the reference workload always runs in this exact regime.
  * ``n_unique > n_bins`` — quantile-spaced subset of the midpoints
    (XGBoost/LightGBM-style approximate splitting) for the scaled configs.

Binning is host-side numpy at ingest time (one pass, like a quantile
sketch); training afterwards touches only the int32 bin matrix on device.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class BinnedFeatures:
    """Quantized design matrix + the threshold table to decode splits."""

    binned: np.ndarray      # [n, F] int32 — bin index per value
    thresholds: np.ndarray  # [F, n_bins-1] float64 — candidate split values,
                            # +inf past the last real boundary of a feature
    n_bins: np.ndarray      # [F] int32 — real bin count per feature

    @property
    def max_bins(self) -> int:
        return self.thresholds.shape[1] + 1


def bin_features(X: np.ndarray, n_bins: int | None = 256) -> BinnedFeatures:
    """Quantize ``X[n, F]`` column-wise into at most ``n_bins`` bins.

    ``n_bins=None`` disables the cap: every unique-value midpoint becomes a
    candidate threshold — the exact-enumeration regime of sklearn's
    ``BestSplitter`` at any cardinality (``GBDTConfig.splitter='exact'``).

    A value lands in bin ``b`` = number of thresholds strictly below it;
    "split at boundary b" then means "go left iff bin <= b", and the
    real-valued threshold stored in the fitted tree is ``thresholds[f, b]``
    (a midpoint, matching sklearn's ``(v_i + v_{i+1})/2``).
    """
    n, F = X.shape
    uniques = []
    for f in range(F):
        u = np.unique(X[:, f])  # sorted, NaN would sort last — reject it
        if np.isnan(u).any():
            raise ValueError(f"feature {f} contains NaN; impute before binning")
        if n_bins is not None and u.size > n_bins:
            # Quantile-spaced representative subset (keep extremes).
            q = np.linspace(0, 1, n_bins)
            idx = np.unique((q * (u.size - 1)).round().astype(int))
            u = u[idx]
        uniques.append(u)
    width = max(max(u.size for u in uniques) - 1, 1)
    thresholds = np.full((F, width), np.inf)
    counts = np.ones(F, np.int32)
    binned = np.zeros((n, F), np.int32)
    for f, u in enumerate(uniques):
        mids = (u[:-1] + u[1:]) / 2.0
        # sklearn guard (BestSplitter): if the midpoint rounds up to the upper
        # value, use the lower value as the threshold so the upper sample
        # still routes right under "x <= t goes left".
        mids = np.where(mids == u[1:], u[:-1], mids)
        thresholds[f, : mids.size] = mids
        counts[f] = u.size
        # bin(v) = #{mids < v}, except v exactly equal to a midpoint stays in
        # the left bin — searchsorted(side='left') gives precisely that.
        binned[:, f] = np.searchsorted(mids, X[:, f], side="left")
    return BinnedFeatures(binned=binned, thresholds=thresholds, n_bins=counts)


def feature_bin_counts(bins: BinnedFeatures) -> tuple[int, ...]:
    """Static per-feature bin counts — the matmul histogram backend's
    traffic lever (it sizes each feature's one-hot to its real bin range)."""
    return tuple(int(x) for x in np.asarray(bins.n_bins))


def bin_features_device(X, n_bins: int = 256) -> BinnedFeatures:
    """Device-side quantile binning for the scaled regime.

    ``bin_features`` runs ``np.unique`` per column — ~20 s of host time at
    10M rows, dwarfing the sharded fit it feeds. This variant sorts each
    column on device and takes ``n_bins`` *empirical* quantile candidates
    (duplicates weighted, LightGBM-style) rather than unique-value
    quantiles. Duplicate candidates yield duplicate midpoints — harmless:
    the extra boundaries describe the same row partition, so split gains
    tie and selection's first-index tie-break picks a boundary whose
    threshold routes identically. The returned ``BinnedFeatures`` carries
    device arrays; ``n_bins`` is reported as the candidate count (bin ids
    still index midpoints the same way as the host build).
    """
    import jax
    import jax.numpy as jnp

    Xj = jnp.asarray(X)
    n, F = Xj.shape
    # Same contract as the host path: binning NaNs silently distorts the
    # candidate set (they sort last), so refuse — impute first.
    if bool(jnp.isnan(Xj).any()):
        raise ValueError("input contains NaN; impute before binning")
    Xs = jnp.sort(Xj, axis=0)                              # [n, F]
    q_idx = jnp.round(
        jnp.linspace(0.0, 1.0, n_bins) * (n - 1)
    ).astype(jnp.int32)
    u = Xs[q_idx, :]                                       # [B, F] candidates
    mids = (u[:-1] + u[1:]) / 2.0
    # sklearn BestSplitter guard: a midpoint that rounds up to the upper
    # value would mis-route the upper sample under "x <= t goes left".
    mids = jnp.where(mids == u[1:], u[:-1], mids)          # [B-1, F]
    binned = jax.vmap(
        lambda m, col: jnp.searchsorted(m, col, side="left"),
        in_axes=(1, 1), out_axes=1,
    )(mids, Xj).astype(jnp.int32)                          # [n, F]
    counts = np.full(F, n_bins, np.int32)
    return BinnedFeatures(binned=binned, thresholds=mids.T, n_bins=counts)
