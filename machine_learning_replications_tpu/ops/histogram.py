"""Histogram construction and split search — the hot op of GBDT training.

Replaces sklearn's Cython ``DepthFirstTreeBuilder``/``BestSplitter``
(SURVEY.md §2.4) with vectorized, branch-free device code:

  * ``node_histograms`` — per-(node, feature, bin) sums of gradient,
    hessian-proxy, squared gradient and counts, via one flattened
    ``segment_sum`` (XLA lowers this to scatter-adds; under ``pjit`` with
    rows sharded on 'data' the partials combine with an all-reduce; a
    Pallas kernel backend accumulates in VMEM instead).
  * ``best_splits`` — friedman-MSE split selection over cumulative
    histograms, matching sklearn's proxy ``diff² · wL · wR`` ordering and
    its leaf conditions (node variance ≤ eps, n < min_samples_split).

All shapes are static: K nodes × F features × B bins.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from machine_learning_replications_tpu.ops import binning

# sklearn's impurity-is-zero leaf test: impurity <= EPSILON (np.finfo(double).eps)
IMPURITY_EPS = 2.220446049250313e-16
_IMPURITY_EPS = IMPURITY_EPS

# sklearn _update_terminal_region zero guard on the Newton denominator
NEWTON_DEN_GUARD = 1e-150


def newton_leaf_value(num: jnp.ndarray, den: jnp.ndarray) -> jnp.ndarray:
    """Guarded Newton leaf value ``num/den`` (0 when |den| underflows) —
    shared by the single-device and sharded trainers so their forests stay
    bit-identical."""
    tiny = jnp.abs(den) < NEWTON_DEN_GUARD
    return jnp.where(tiny, 0.0, num / jnp.where(tiny, 1.0, den))


class StumpData(NamedTuple):
    """Replicated-sorted-layout training set for depth-1 boosting.

    TPU hates scatters *and* gathers (both serialize onto the scalar unit),
    but the bin matrix never changes across boosting stages — so we pay
    memory instead of memory traffic: hold the label/score vectors in **F
    copies, each pre-sorted by one feature's bins**. Every stage is then
    pure dense work — elementwise math on ``[F, n]``, a cumsum, and *static*
    boundary lookups — with F-fold redundant flops (trivial) and zero
    dynamic indexing. ``bins_x`` carries every feature's bins in every sort
    order so split routing is a dense compare too.

    Under ``shard_map`` each data shard builds this structure from its local
    rows; per-shard cumulative sums combine with one tiny ``psum`` of
    ``[F, B-1]`` per stage (SURVEY.md §2.5: histogram partials over ICI).
    """

    bins_x: jnp.ndarray      # [F_query, F_sort, n] uint8/16/32 — bins of feature
                             #   f_q for rows in f_s's sorted order
    y_sorted: jnp.ndarray    # [F, n] — labels in each sort order
    left_count: jnp.ndarray  # [F, B-1] int — #rows with bin ≤ b (static CL)
    thresholds: jnp.ndarray  # [F, B-1] — real-valued candidate thresholds


def build_stump_data(bins, y, dtype=None) -> StumpData:
    """Host-side precompute (numpy, once per dataset) from BinnedFeatures."""
    import numpy as np

    b = np.asarray(bins.binned)
    n, F = b.shape
    # Narrowest dtype that holds the bin ids (uint8 covers the capped 'hist'
    # regime; wider types serve 'exact' enumeration at high cardinality).
    bin_dtype = (
        np.uint8 if bins.max_bins <= 256
        else np.uint16 if bins.max_bins <= 65536
        else np.int32
    )
    order = np.argsort(b, axis=0, kind="stable")  # [n, F] — rows by each feature
    bins_x = np.empty((F, F, n), bin_dtype)
    y_sorted = np.empty((F, n), np.asarray(y).dtype)
    for fs in range(F):
        bins_x[:, fs, :] = b[order[:, fs], :].T
        y_sorted[fs] = np.asarray(y)[order[:, fs]]
    counts = np.stack(
        [np.bincount(b[:, f], minlength=bins.max_bins) for f in range(F)]
    )
    left_count = np.cumsum(counts, axis=1)[:, :-1]
    thresholds = jnp.asarray(bins.thresholds)
    ys = jnp.asarray(y_sorted)
    if dtype is not None:
        thresholds = thresholds.astype(dtype)
        ys = ys.astype(dtype)
    return StumpData(
        bins_x=jnp.asarray(bins_x),
        y_sorted=ys,
        left_count=jnp.asarray(left_count.astype(np.int32)),
        thresholds=thresholds,
    )


def is_binary_labels(y) -> "bool | jnp.ndarray":
    """The label contract behind ``assume_binary_y`` packing, in ONE place:
    every label exactly 0 or 1. Host arrays return a Python bool; traced /
    device arrays return a device scalar (callers decide when to sync).
    The packed representation itself is ``y > 0.5`` — consistent with this
    predicate by construction (0 → 0, 1 → 1)."""
    import numpy as np

    if isinstance(y, np.ndarray):
        return bool(np.all((y == 0) | (y == 1)))
    return jnp.all((y == 0) | (y == 1))


def build_stump_data_device(
    bins, y, dtype=None, assume_binary_y: bool = False
) -> StumpData:
    """``build_stump_data`` with the heavy work (argsort + layout gathers)
    on device instead of host numpy.

    The host build cost dominated the whole fit at bench scale (measured
    ~5 s of a 5.8 s 200k-row fit on v5e; the device loop itself is ~0.1 s).
    ``jnp.argsort(stable=True)`` matches ``np.argsort(kind='stable')``, so
    the layout — and therefore the fitted forest — is identical to the host
    build's. ``bins.binned``/``bins.thresholds`` may be numpy or device
    arrays (the device-binning path passes device arrays straight through).

    ``assume_binary_y=True`` lets the labels ride the ``bins_x`` row gather
    as one extra packed bin-id column instead of paying a separate
    scattered gather into every sort order (TPU gathers cost per gathered
    row — the label gather was ~20% of the layout wall at bench scale).
    ONLY valid when every label is exactly 0 or 1 (binomial-deviance
    training data); callers must enforce that — the fused fit folds a
    device-side check into its post-dispatch flag.
    """
    b = jnp.asarray(bins.binned)
    n, F = b.shape
    B = int(bins.max_bins)
    bin_dtype = (
        jnp.uint8 if B <= 256 else jnp.uint16 if B <= 65536 else jnp.int32
    )
    bb = b.astype(bin_dtype)  # narrow BEFORE the layout gather: it moves
    #   F× the matrix, and gathering int32 just to cast after measured ~2×
    #   the bytes and time of gathering the narrow ids (v5e, 1M rows)
    order = jnp.argsort(b, axis=0, stable=True)          # [n, F]
    yj = jnp.asarray(y)
    if assume_binary_y:
        ybit = (yj > 0.5).astype(bin_dtype)
        bplus = jnp.concatenate([bb, ybit[:, None]], axis=1)   # [n, F+1]
        # G[c, fs, i] = bplus[order[i, fs], c]: one gather + transpose
        # carries bins AND labels through the same gathered rows.
        G = jnp.transpose(bplus[order.T, :], (2, 0, 1))        # [F+1, F, n]
        bins_x = G[:F]
        y_sorted = G[F].astype(yj.dtype)                       # [F, n]
    else:
        # bins_x[fq, fs, i] = b[order[i, fs], fq]: one gather + transpose.
        bins_x = jnp.transpose(bb[order.T, :], (2, 0, 1))
        y_sorted = jnp.take_along_axis(
            jnp.broadcast_to(yj[None, :], (F, n)), order.T, axis=1
        )
    # left_count[f, b] = #rows with bin ≤ b — order-independent, so it comes
    # from a chunked compare+sum histogram over the UNSORTED ids (one dense
    # VPU pass) rather than a row gather into sorted order + searchsorted
    # (TPU serializes both the 17M-element gather and the binary-search
    # gathers; measured slower than the rest of the layout build combined).
    boundaries = jnp.arange(B - 1, dtype=b.dtype)
    # padding rows must not count: bin B-1 exceeds every boundary (they run
    # to B-2 only), so the pad value is reduction-neutral by construction.
    mapped, _ = binning.chunked_row_reduce(
        b,
        lambda bc: jnp.sum(
            bc[:, None, :] <= boundaries[None, :, None],
            axis=0, dtype=jnp.int32,
        ),
        pad_value=B - 1,
    )
    left_count = jnp.sum(mapped, axis=0).T.astype(jnp.int32)  # [F, B-1]
    thresholds = jnp.asarray(bins.thresholds)
    ys = y_sorted
    if dtype is not None:
        thresholds = thresholds.astype(dtype)
        ys = ys.astype(dtype)
    return StumpData(
        bins_x=bins_x, y_sorted=ys,
        left_count=left_count, thresholds=thresholds,
    )


_BLOCKED_BOUNDARY_MIN_N = 16_384
_BOUNDARY_BLOCK = 512


def to_blocks(a: jnp.ndarray, n: int) -> jnp.ndarray:
    """``[..., n] → [..., nb, _BOUNDARY_BLOCK]`` zero-padded block layout —
    the single copy of the block arithmetic, used by the flat-input
    ``cumulative_boundary_sums`` wrapper (its only caller; see
    ``boundary_sums_3d``'s docstring for why the stump loops deliberately
    do NOT call this themselves). New padding slots hold exact zeros, as
    ``boundary_sums_3d`` requires."""
    blk = _BOUNDARY_BLOCK
    nb = -(-n // blk)
    widths = [(0, 0)] * (a.ndim - 1) + [(0, nb * blk - n)]
    return jnp.pad(a, widths).reshape(*a.shape[:-1], nb, blk)


def cumulative_boundary_sums(
    v_sorted: jnp.ndarray, left_count: jnp.ndarray
) -> jnp.ndarray:
    """``out[f, b] = Σ v over rows with bin[f] ≤ b`` from per-feature-sorted
    values. ``v_sorted`` is ``[F, n]``; ``left_count`` holds the boundary
    positions (counts in ``[0, n]``).

    Small n: one cumsum + a static lookup — sequential summation order,
    bitwise-stable against the parity oracles. Large n: TPU lowers the full
    cumsum to O(log n) whole-array passes, which dominated the boosting
    stage (~1.3 ms/stage at 200k rows), yet only B−1 prefix values are ever
    read. The blocked path does one block-sum pass + a tiny per-block
    cumsum, then reconstructs each boundary as (exclusive block prefix) +
    (masked partial of one block) — 2 passes over the data instead of
    log n. Summation regroups per block, so float results can differ from
    the sequential path in the last ulp; the threshold keeps every parity
    regime (reference cohort, fold tests) on the sequential path.
    """
    F, n = v_sorted.shape
    if n < _BLOCKED_BOUNDARY_MIN_N:
        csum = jnp.cumsum(v_sorted, axis=1)
        padded = jnp.concatenate(
            [jnp.zeros((csum.shape[0], 1), csum.dtype), csum], axis=1
        )
        return jnp.take_along_axis(padded, left_count, axis=1)

    return boundary_sums_3d(to_blocks(v_sorted, n), left_count)


def boundary_sums_3d(vb: jnp.ndarray, left_count: jnp.ndarray) -> jnp.ndarray:
    """Blocked boundary sums from values ALREADY in block shape:
    ``vb [F, nb, blk]`` (slots past the real row count must hold exact
    zeros) + boundary positions ``left_count [F, B-1]`` in ``[0, n]`` →
    ``out[f, b] = Σ vb.flat[f, :left_count[f, b]]``.

    Reached through the flat-input wrapper above, whose pad+reshape XLA
    fuses into the surrounding stage at no measured runtime cost. Keeping
    the stump loops' stage arrays block-resident to call this directly was
    ablated on v5e (r3, re-confirmed neutral on CPU r4): zero runtime gain
    and an O(n) compile blowup when a large pad+reshape feeds a while loop
    — see docs/SCALING.md "Lowerings" before moving the block conversion."""
    F, nb, blk = vb.shape
    block_sums = jnp.sum(vb, axis=2)                      # [F, nb]
    excl = jnp.cumsum(block_sums, axis=1) - block_sums    # exclusive prefix
    p = left_count                                        # [F, B-1]
    bidx = jnp.minimum(p // blk, nb - 1)                  # clamp p == n edge
    offset = p - bidx * blk                               # in [0, blk]
    part = jnp.take_along_axis(vb, bidx[:, :, None], axis=1)  # [F, B-1, blk]
    within = jnp.arange(blk, dtype=p.dtype)[None, None, :] < offset[:, :, None]
    partial = jnp.sum(jnp.where(within, part, 0), axis=2)
    return jnp.take_along_axis(excl, bidx, axis=1) + partial


class NodeHistograms(NamedTuple):
    grad: jnp.ndarray   # [K, F, B] Σ residual
    hess: jnp.ndarray   # [K, F, B] Σ p(1−p)  (Newton denominator terms)
    grad2: jnp.ndarray  # [K, F, B] Σ residual² (for the impurity leaf test)
    count: jnp.ndarray  # [K, F, B] sample counts


class Splits(NamedTuple):
    do_split: jnp.ndarray   # [K] bool — node splits (vs becomes/stays a leaf)
    feature: jnp.ndarray    # [K] int32
    boundary: jnp.ndarray   # [K] int32 — bin boundary b (left ⇔ bin ≤ b)
    threshold: jnp.ndarray  # [K] float — real-valued split threshold
    gain: jnp.ndarray       # [K] float — friedman proxy of the chosen split


def node_histograms(
    binned: jnp.ndarray,      # [n, F] int32
    node_local: jnp.ndarray,  # [n] int32 — local node index, −1 ⇒ inactive row
    grad: jnp.ndarray,        # [n]
    hess: jnp.ndarray,        # [n]
    n_nodes: int,
    max_bins: int,
) -> NodeHistograms:
    """One `segment_sum` over n·F (node, feature, bin) cells.

    Inactive rows (parked at an ancestor leaf, or padding) go to a dump
    segment past the real range.
    """
    n, F = binned.shape
    B = max_bins
    f_idx = jnp.arange(F, dtype=jnp.int32)
    seg = (node_local[:, None] * F + f_idx[None, :]) * B + binned  # [n, F]
    seg = jnp.where(node_local[:, None] >= 0, seg, n_nodes * F * B)
    seg = seg.reshape(-1)
    num_segments = n_nodes * F * B + 1

    def acc(v):
        flat = jnp.broadcast_to(v[:, None], (n, F)).reshape(-1)
        s = jax.ops.segment_sum(flat, seg, num_segments=num_segments)
        return s[:-1].reshape(n_nodes, F, B)

    ones = jnp.ones_like(grad)
    return NodeHistograms(
        grad=acc(grad), hess=acc(hess), grad2=acc(grad * grad), count=acc(ones)
    )


def node_histograms_matmul(
    binned: jnp.ndarray,      # [n, F] int32
    node_local: jnp.ndarray,  # [n] int32 — local node index, −1 ⇒ inactive row
    grad: jnp.ndarray,        # [n]
    hess: jnp.ndarray,        # [n]
    n_nodes: int,
    max_bins: int,
    chunk: int = 4096,
    feature_bins: tuple[int, ...] | None = None,
) -> NodeHistograms:
    """Histogram statistics as one-hot MXU contractions (no scatters).

    TPU lowers ``segment_sum`` to serialized scatter-adds (measured 170 ms
    at 200k rows × 17 features × K=8 on v5e); here each row-chunk builds a
    per-feature ``[c, K·B_f]`` one-hot of its (node, bin) cell and
    contracts ``[4, c] × [c, K·B_f]`` on the systolic array, accumulating
    partials over a ``lax.scan`` of row chunks. Unlike the Pallas kernel
    this is plain jnp, so it composes with ``vmap`` — the fold-fan-out
    paths (``gbdt.fit_folds``, the CV sweep) use it on TPU.

    ``feature_bins`` (static per-feature bin counts, ``bins.n_bins``) is
    the big lever: the cost is the one-hot's HBM traffic, n·K·Σ_f B_f
    floats, and on the HF cohort (14 of 17 features binary) Σ_f B_f is
    ~8× smaller than F·max_bins — measured 75 ms → ~10 ms at 200k rows.
    Without it every feature pays ``max_bins``.

    f32 throughout (dots forced to HIGHEST: the TPU's default f32 matmul
    rounds operands to bf16, which truncated gradient sums by ~1e-1 at
    200k rows — far beyond tie-break noise). Only f32 accumulation order
    differs vs ``segment_sum``: near-tied split gains may resolve
    differently (the documented model-level parity contract).
    """
    n, F = binned.shape
    dtype = grad.dtype
    widths = tuple(feature_bins) if feature_bins is not None else (max_bins,) * F
    assert len(widths) == F
    n_pad = -(-n // chunk) * chunk
    valid = (node_local >= 0).astype(dtype)
    stats = jnp.stack(
        [grad * valid, hess * valid, grad * grad * valid, valid], axis=0
    )  # [4, n] — inactive/padding rows contribute nothing
    stats = jnp.pad(stats, ((0, 0), (0, n_pad - n)))
    node0 = jnp.pad(jnp.maximum(node_local, 0), (0, n_pad - n))
    binned_p = jnp.pad(binned, ((0, n_pad - n), (0, 0)))

    def body(accs, args):
        stats_c, node_c, bins_c = args  # [4, c], [c], [c, F]
        parts = []
        for f in range(F):
            bf = widths[f]
            cell_f = node_c * bf + bins_c[:, f]  # [c] ∈ [0, K·B_f)
            onehot_f = (
                cell_f[:, None] == jnp.arange(n_nodes * bf, dtype=cell_f.dtype)
            ).astype(dtype)
            parts.append(
                jax.lax.dot(
                    stats_c, onehot_f, precision=jax.lax.Precision.HIGHEST
                )
            )  # [4, K·B_f]
        return tuple(a + p for a, p in zip(accs, parts)), None

    acc0 = tuple(jnp.zeros((4, n_nodes * bf), dtype) for bf in widths)
    accs, _ = jax.lax.scan(
        body,
        acc0,
        (
            stats.reshape(4, n_pad // chunk, chunk).transpose(1, 0, 2),
            node0.reshape(n_pad // chunk, chunk),
            binned_p.reshape(n_pad // chunk, chunk, F),
        ),
    )
    # Assemble [4, K, F, max_bins] (zero-padded past each feature's B_f).
    cols = [
        jnp.pad(a.reshape(4, n_nodes, bf), ((0, 0), (0, 0), (0, max_bins - bf)))
        for a, bf in zip(accs, widths)
    ]
    out = jnp.stack(cols, axis=2)  # [4, K, F, B]
    return NodeHistograms(grad=out[0], hess=out[1], grad2=out[2], count=out[3])


def stump_histograms(
    binned: jnp.ndarray,  # [n, F] integer bin ids (narrow dtype preserved)
    grad: jnp.ndarray,    # [n]
    hess: jnp.ndarray,    # [n]
    max_bins: int,
    backend: str = "xla",
    chunk: int = 8192,
) -> jnp.ndarray:
    """Root-node (K=1) gradient/hessian histograms → ``[2, F, B]``.

    The per-stage statistics pass of the UNSORTED depth-1 formulation:
    ``out[0, f, b] = Σ_i grad[i]·[binned[i, f] == b]`` and likewise for
    hess. Cumulative sums of these over bins reproduce the sorted layout's
    boundary sums exactly up to f32 regrouping — the r5 trace showed the
    sorted path spending ~70% of each stage on pad/reshape/copy data
    formatting (docs/SCALING.md "Roofline"), which this formulation has
    none of: per stage it reads the (loop-invariant, u8) bin matrix plus
    O(n) vectors.

    Backends mirror ``node_histograms*``: 'xla' → two segment_sums
    (compiled scatter-adds, the CPU pick); 'matmul' → chunked one-hot MXU
    contraction (dense ``[2, c] × [c, B]`` per feature, f32-HIGHEST);
    'pallas' → the VMEM-accumulating kernel (``stump_histograms_pallas``).
    """
    n, F = binned.shape
    B = max_bins
    dtype = jnp.result_type(grad.dtype, jnp.float32)
    if backend == "pallas":
        from machine_learning_replications_tpu.ops.pallas_histogram import (
            stump_histograms_pallas,
        )

        return stump_histograms_pallas(binned, grad, hess, B)
    if backend == "xla":
        seg = (
            jnp.arange(F, dtype=jnp.int32)[None, :] * B
            + binned.astype(jnp.int32)
        ).reshape(-1)

        def acc(v):
            flat = jnp.broadcast_to(v[:, None], (n, F)).reshape(-1)
            return jax.ops.segment_sum(flat, seg, num_segments=F * B)

        return jnp.stack(
            [acc(grad.astype(dtype)), acc(hess.astype(dtype))]
        ).reshape(2, F, B)
    if backend != "matmul":
        raise ValueError(f"unknown stump histogram backend {backend!r}")

    n_pad = -(-n // chunk) * chunk
    stats = jnp.stack([grad.astype(dtype), hess.astype(dtype)], axis=0)
    stats = jnp.pad(stats, ((0, 0), (0, n_pad - n)))
    # pad rows carry zero stats; their bin id (0) contributes nothing
    binned_p = jnp.pad(binned, ((0, n_pad - n), (0, 0)))

    def body(acc, args):
        stats_c, bins_c = args  # [2, c], [c, F]
        bins_i = bins_c.astype(jnp.int32)
        cols = jnp.arange(B, dtype=jnp.int32)
        parts = []
        for f in range(F):
            onehot_f = (bins_i[:, f][:, None] == cols).astype(dtype)
            parts.append(jax.lax.dot(
                stats_c, onehot_f, precision=jax.lax.Precision.HIGHEST
            ))  # [2, B]
        return acc + jnp.stack(parts, axis=1), None

    acc0 = jnp.zeros((2, F, B), dtype)
    out, _ = jax.lax.scan(
        body,
        acc0,
        (
            stats.reshape(2, n_pad // chunk, chunk).transpose(1, 0, 2),
            binned_p.reshape(n_pad // chunk, chunk, F),
        ),
    )
    return out


def select_splits(
    GL: jnp.ndarray,          # [K, F, B-1] left-of-boundary residual sums
    CL: jnp.ndarray,          # [K, F, B-1] left-of-boundary counts
    GT: jnp.ndarray,          # [K] node residual sums
    CT: jnp.ndarray,          # [K] node counts
    sum_g2: jnp.ndarray,      # [K] node Σ residual² (impurity leaf test)
    thresholds: jnp.ndarray,  # [F, B-1] — +inf past a feature's last boundary
    min_samples_split: int = 2,
    min_samples_leaf: int = 1,
) -> Splits:
    """sklearn-equivalent friedman_mse split selection from cumulative sums.

    A node becomes a leaf when its residual variance is ≤ machine eps
    (sklearn's pure-node test), it has fewer than ``min_samples_split``
    samples, or no boundary leaves ≥ ``min_samples_leaf`` on both sides.
    Ties in gain resolve to the first (feature, boundary) in flat order
    (sklearn breaks ties by a seeded feature permutation — immaterial for
    metric-level parity, noted per SURVEY.md §7).
    """
    GR = GT[:, None, None] - GL
    CR = CT[:, None, None] - CL

    valid = (
        (CL >= min_samples_leaf)
        & (CR >= min_samples_leaf)
        & jnp.isfinite(thresholds)[None, :, :]
    )
    diff = GL / jnp.maximum(CL, 1) - GR / jnp.maximum(CR, 1)
    proxy = diff * diff * CL * CR  # friedman proxy; CT constant per node
    proxy = jnp.where(valid, proxy, -jnp.inf)

    K, F, Bm1 = proxy.shape
    flat = proxy.reshape(K, F * Bm1)
    best = jnp.argmax(flat, axis=-1).astype(jnp.int32)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=-1)[:, 0]
    f = best // Bm1
    b = best % Bm1
    thr = thresholds[f, b]

    # Node-level leaf tests (sklearn DepthFirstTreeBuilder)
    mean = GT / jnp.maximum(CT, 1)
    impurity = jnp.maximum(sum_g2 / jnp.maximum(CT, 1) - mean * mean, 0.0)
    do_split = (
        (CT >= min_samples_split)
        & (impurity > _IMPURITY_EPS)
        & jnp.isfinite(best_gain)
    )
    return Splits(do_split=do_split, feature=f, boundary=b, threshold=thr, gain=best_gain)


def best_splits(
    hists: NodeHistograms,
    thresholds: jnp.ndarray,
    min_samples_split: int = 2,
    min_samples_leaf: int = 1,
) -> Splits:
    """Split selection from per-node histograms (the generic depth≥2 path)."""
    GL = jnp.cumsum(hists.grad, axis=-1)[..., :-1]
    CL = jnp.cumsum(hists.count, axis=-1)[..., :-1]
    GT = jnp.sum(hists.grad, axis=-1)[:, 0]
    CT = jnp.sum(hists.count, axis=-1)[:, 0]
    sum_g2 = jnp.sum(hists.grad2, axis=-1)[:, 0]
    return select_splits(
        GL, CL, GT, CT, sum_g2, thresholds, min_samples_split, min_samples_leaf
    )
