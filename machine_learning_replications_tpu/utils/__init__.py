"""Cross-cutting utilities: CV fold replication, profiling, logging."""

from machine_learning_replications_tpu.utils.cv import (
    kfold_test_masks,
    stratified_kfold_test_masks,
)

__all__ = ["kfold_test_masks", "stratified_kfold_test_masks"]
