"""Tracing / profiling / numerics-guard subsystem (SURVEY.md §5).

The reference imports ``time`` and never uses it (``train_ensemble_public.py:6``)
— it has no profiling, tracing, or sanitizer story at all. The TPU build
supplies:

  * ``PhaseTimer`` — wall-clock accounting per pipeline phase (ingest,
    impute, select, member fits, …), blocking on device completion so a
    phase's time is real work, not dispatch. The ≥10× speedup claim in
    BASELINE.json is measured with these. Since the ``obs`` subsystem
    landed it is a thin adapter over ``obs.spans`` — phases also appear
    as spans in the Perfetto timeline when a tracer is active.
  * ``device_trace`` — ``jax.profiler`` capture around a region, producing
    a Perfetto/TensorBoard trace directory of on-device timelines.
  * ``nan_guard`` — opt-in ``jax_debug_nans`` scope, the functional-world
    stand-in for a race/memory sanitizer: XLA's pure semantics make data
    races structurally absent, so the failure class worth guarding is
    numerics (SURVEY.md §5 "Race detection").
"""

from __future__ import annotations

import contextlib
import os
import sys
import time
from typing import Iterator

import jax

from machine_learning_replications_tpu.obs import spans


class PhaseTimer:
    """Accumulates named phase durations; phases may repeat (times sum).

    Now a thin adapter over ``obs.spans``: each phase opens a span (so a
    run with an active tracer gets the phase in its Perfetto timeline,
    nested under whatever span encloses it) and the span's exit performs
    the device blocking. JAX dispatch is asynchronous, so a phase's exit
    blocks on everything the body registered via the yielded handle — the
    recorded time is real device work, not dispatch:

    >>> t = PhaseTimer()
    >>> with t.phase("fit") as ph:
    ...     result = ph.block(train())
    >>> print(t.report())
    """

    def __init__(self) -> None:
        self.seconds: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[spans.SpanHandle]:
        t0 = time.perf_counter()
        try:
            # The span blocks on registered work at ITS exit, which is
            # inside this timing scope — identical semantics to the old
            # standalone implementation.
            with spans.span(name) as ph:
                yield ph
        finally:
            dt = time.perf_counter() - t0
            self.seconds[name] = self.seconds.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> str:
        total = sum(self.seconds.values())
        lines = [f"{'phase':<24} {'calls':>5} {'seconds':>10} {'share':>7}"]
        for name, s in sorted(self.seconds.items(), key=lambda kv: -kv[1]):
            share = s / total if total else 0.0
            lines.append(
                f"{name:<24} {self.counts[name]:>5d} {s:>10.4f} {share:>6.1%}"
            )
        lines.append(f"{'total':<24} {'':>5} {total:>10.4f}")
        return "\n".join(lines)


def stage_say(msg: str) -> None:
    """One timestamped stderr progress line, shared by both pipeline stage
    runners (checkpointed and straight-through) so their output stays
    grep-identical — they now route through ``obs.journal.stage_scope``,
    the single code path that formats these lines. A multi-hour scaled fit
    with six silent stages is undiagnosable from outside (r4 lesson: a 4M
    single-core run gave no signal of which stage it was in for hours).
    The timestamp is ISO-8601 UTC: a time-of-day-only local stamp is
    ambiguous the moment a scaled fit crosses midnight or the log is read
    in another timezone. Opt out with ``MLR_TPU_PROGRESS=0`` (e.g. fits
    inside tight candidate loops)."""
    if os.environ.get("MLR_TPU_PROGRESS", "1") == "0":
        return
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(f"[pipeline {stamp}] {msg}", file=sys.stderr, flush=True)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture an on-device profiler trace (view with Perfetto/TensorBoard)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def nan_guard(enable: bool = True) -> Iterator[None]:
    """Raise on the first NaN produced inside the scope (jax_debug_nans)."""
    if not enable:
        yield
        return
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)
