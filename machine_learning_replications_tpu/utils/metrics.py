"""Device-side evaluation metrics (L5').

The reference evaluates with ``sklearn.metrics``: ``classification_report`` at
threshold 0.5 (``train_ensemble_public.py:63-64``), ``plot_roc_curve`` with AUC
(``:67-77``) and ``plot_precision_recall_curve`` (``:79-88``), each wrapped in a
95% Wald confidence band ``1.96*sqrt(p*(1-p)/n)`` (``:76,:84``).

This module computes the same quantities on device with static shapes so they
can live inside a jitted eval step (SURVEY.md §5 "Metrics"): AUC via the
rank-statistic (Mann-Whitney) form with proper tie handling, ROC/PR curves as
fixed-length cumulative scans over the score-sorted order, and a
``classification_report``-equivalent returned as arrays rather than a string.
Plotting stays on host (``plots.py``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


def _average_ranks(scores: jnp.ndarray) -> jnp.ndarray:
    """1-based ranks with ties given their group-average rank."""
    s = jnp.sort(scores)
    lo = jnp.searchsorted(s, scores, side="left")
    hi = jnp.searchsorted(s, scores, side="right")
    return 0.5 * (lo + hi + 1).astype(s.dtype)


@jax.jit
def roc_auc(y_true: jnp.ndarray, scores: jnp.ndarray) -> jnp.ndarray:
    """AUC-ROC = P(score⁺ > score⁻) + ½P(tie), via average ranks.

    Equals sklearn's trapezoidal ``roc_auc_score`` exactly (including tied
    scores). Returns NaN when a class is empty, as sklearn raises there.
    """
    y = y_true.astype(scores.dtype)
    n_pos = jnp.sum(y)
    n_neg = y.shape[0] - n_pos
    r = _average_ranks(scores)
    u = jnp.sum(r * y) - n_pos * (n_pos + 1.0) / 2.0
    return u / (n_pos * n_neg)


def roc_auc_batch_host(y_true, scores) -> "np.ndarray":
    """Tie-averaged rank AUC over a batch of score rows ``[L, m]`` → ``[L]``,
    in host numpy (scipy ``rankdata`` along the row axis).

    The same U statistic as ``roc_auc`` (tested against it), for host-side
    model-selection tables — e.g. the sweep's 45-cell grid, where one
    device dispatch + fetch per cell costs more than the whole evaluation.
    Mirrors ``roc_auc``'s empty-class contract by returning NaN rows
    rather than warning."""
    import numpy as np
    from scipy.stats import rankdata

    y = np.asarray(y_true, np.float64)
    n_pos = y.sum()
    n_neg = y.size - n_pos
    scores = np.atleast_2d(np.asarray(scores, np.float64))
    if n_pos == 0 or n_neg == 0:
        return np.full(scores.shape[0], np.nan)
    r = rankdata(scores, axis=-1, method="average")
    u = (r * y[None, :]).sum(axis=-1) - n_pos * (n_pos + 1.0) / 2.0
    return u / (n_pos * n_neg)


class RocCurve(NamedTuple):
    """Fixed-length ROC scan: point k uses the top-k scores as positives."""

    fpr: jnp.ndarray  # [n+1]
    tpr: jnp.ndarray  # [n+1]
    thresholds: jnp.ndarray  # [n+1] — descending; [0] is +inf (no positives)


@jax.jit
def roc_curve(y_true: jnp.ndarray, scores: jnp.ndarray) -> RocCurve:
    """ROC points over every score cut, in descending-threshold order.

    Shape is static ([n+1]); sklearn's variant drops collinear/tied points,
    which only thins the polyline — the trapezoid area is identical (tied
    thresholds yield repeated points that contribute zero area).
    """
    order = jnp.argsort(-scores)
    y = y_true[order].astype(scores.dtype)
    tp = jnp.concatenate([jnp.zeros(1, y.dtype), jnp.cumsum(y)])
    fp = jnp.concatenate([jnp.zeros(1, y.dtype), jnp.cumsum(1.0 - y)])
    n_pos = tp[-1]
    n_neg = fp[-1]
    thr = jnp.concatenate([jnp.array([jnp.inf], scores.dtype), scores[order]])
    return RocCurve(fpr=fp / n_neg, tpr=tp / n_pos, thresholds=thr)


class PrCurve(NamedTuple):
    precision: jnp.ndarray  # [n+1] — ends at 1.0 (zero-recall convention)
    recall: jnp.ndarray     # [n+1] — descending from 1 to 0
    thresholds: jnp.ndarray  # [n]


@jax.jit
def precision_recall_curve(y_true: jnp.ndarray, scores: jnp.ndarray) -> PrCurve:
    """PR points over every cut (sklearn convention: recall descends to 0,
    final precision pinned to 1). Tied thresholds yield repeated points."""
    order = jnp.argsort(-scores)
    y = y_true[order].astype(scores.dtype)
    tp = jnp.cumsum(y)
    k = jnp.arange(1, y.shape[0] + 1, dtype=y.dtype)
    n_pos = tp[-1]
    # Walk from the smallest threshold up (reverse of the sorted order).
    precision = jnp.concatenate([(tp / k)[::-1], jnp.ones(1, y.dtype)])
    recall = jnp.concatenate([(tp / n_pos)[::-1], jnp.zeros(1, y.dtype)])
    return PrCurve(
        precision=precision, recall=recall, thresholds=scores[order][::-1]
    )


@jax.jit
def average_precision(y_true: jnp.ndarray, scores: jnp.ndarray) -> jnp.ndarray:
    """AP = Σ (R_k − R_{k−1}) · P_k over descending thresholds (sklearn def).

    With tied scores sklearn collapses ties before summing; here each tied
    row contributes its own step, which telescopes to the same value only
    when precision is constant across the tie — for continuous scores
    (the framework's use) the two agree to machine precision.
    """
    pr = precision_recall_curve(y_true, scores)
    # recall descends; steps are negative diffs
    dr = pr.recall[:-1] - pr.recall[1:]
    return jnp.sum(dr * pr.precision[:-1])


class ClassificationReport(NamedTuple):
    """Per-class arrays indexed [neg, pos] — the classification_report fields."""

    precision: jnp.ndarray  # [2]
    recall: jnp.ndarray     # [2]
    f1: jnp.ndarray         # [2]
    support: jnp.ndarray    # [2]
    accuracy: jnp.ndarray   # []
    macro_avg: jnp.ndarray      # [3] precision/recall/f1
    weighted_avg: jnp.ndarray   # [3]


@jax.jit
def classification_report(
    y_true: jnp.ndarray, y_pred: jnp.ndarray
) -> ClassificationReport:
    """Binary classification_report (reference eval at threshold 0.5,
    ``train_ensemble_public.py:63-64``) as device arrays."""
    yt = y_true.astype(jnp.float32)
    yp = y_pred.astype(jnp.float32)
    out = []
    for cls in (0.0, 1.0):
        t = jnp.where(cls == 1.0, yt, 1.0 - yt)
        p = jnp.where(cls == 1.0, yp, 1.0 - yp)
        tp = jnp.sum(t * p)
        prec = tp / jnp.maximum(jnp.sum(p), 1.0)
        rec = tp / jnp.maximum(jnp.sum(t), 1.0)
        f1 = jnp.where(
            prec + rec > 0.0, 2.0 * prec * rec / (prec + rec), 0.0
        )
        out.append((prec, rec, f1, jnp.sum(t)))
    precision = jnp.stack([out[0][0], out[1][0]])
    recall = jnp.stack([out[0][1], out[1][1]])
    f1 = jnp.stack([out[0][2], out[1][2]])
    support = jnp.stack([out[0][3], out[1][3]])
    acc = jnp.mean((yt == yp).astype(jnp.float32))
    w = support / jnp.sum(support)
    macro = jnp.stack([jnp.mean(precision), jnp.mean(recall), jnp.mean(f1)])
    weighted = jnp.stack(
        [jnp.sum(w * precision), jnp.sum(w * recall), jnp.sum(w * f1)]
    )
    return ClassificationReport(
        precision=precision, recall=recall, f1=f1, support=support,
        accuracy=acc, macro_avg=macro, weighted_avg=weighted,
    )


def wald_ci_halfwidth(p: jnp.ndarray, n: int | jnp.ndarray) -> jnp.ndarray:
    """95% Wald band half-width ``1.96*sqrt(p*(1-p)/n)`` — the reference's
    hand-rolled CI formula (``train_ensemble_public.py:76,:84``)."""
    return 1.96 * jnp.sqrt(p * (1.0 - p) / n)


def report_text(rep: ClassificationReport) -> str:
    """Host-side pretty printer mirroring sklearn's report layout."""
    import numpy as np

    rows = []
    hdr = f"{'':>12} {'precision':>9} {'recall':>9} {'f1-score':>9} {'support':>9}"
    rows.append(hdr)
    for i, name in enumerate(("0.0", "1.0")):
        rows.append(
            f"{name:>12} {float(rep.precision[i]):>9.2f} "
            f"{float(rep.recall[i]):>9.2f} {float(rep.f1[i]):>9.2f} "
            f"{int(np.asarray(rep.support[i])):>9d}"
        )
    n = int(np.asarray(jnp.sum(rep.support)))
    rows.append("")
    rows.append(f"{'accuracy':>12} {'':>9} {'':>9} {float(rep.accuracy):>9.2f} {n:>9d}")
    for name, avg in (("macro avg", rep.macro_avg), ("weighted avg", rep.weighted_avg)):
        rows.append(
            f"{name:>12} {float(avg[0]):>9.2f} {float(avg[1]):>9.2f} "
            f"{float(avg[2]):>9.2f} {n:>9d}"
        )
    return "\n".join(rows)
