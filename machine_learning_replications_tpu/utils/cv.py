"""Deterministic replication of sklearn's unshuffled CV fold assignment.

The reference's CV is fully deterministic despite the seeds it passes around:
``LassoCV(cv=10)`` → ``KFold(10, shuffle=False)`` (contiguous blocks) and
``StackingClassifier(cv=None)`` → ``StratifiedKFold(5, shuffle=False)``
(per-class block assignment). Replicating the assignments exactly keeps
fold-level parity with sklearn available to the differential tests
(SURVEY.md §7 "RNG parity": fold assignment is feasible to replicate;
in-solver RNG is not).

Masks, not index lists: every fold shares one static shape so fold fits can
``vmap`` (SURVEY.md §7 "fold-size padding with masked reductions").
"""

from __future__ import annotations

import numpy as np


def kfold_test_masks(n: int, k: int) -> np.ndarray:
    """``KFold(k, shuffle=False)``: contiguous blocks, first ``n % k`` folds
    one row larger. Returns ``[k, n]`` float 0/1 test masks."""
    sizes = np.full(k, n // k)
    sizes[: n % k] += 1
    masks = np.zeros((k, n))
    start = 0
    for i, sz in enumerate(sizes):
        masks[i, start : start + sz] = 1.0
        start += sz
    return masks


def stratified_kfold_test_masks_within(
    y: np.ndarray, k: int, row_mask: np.ndarray
) -> np.ndarray:
    """Stratified k-fold test masks of the subset ``row_mask == 1``, expanded
    back to full-length ``[k, n]`` masks (rows outside the subset are 0 in
    every fold). Matches sklearn fitting ``StratifiedKFold(k)`` on the
    subset — the nested Platt CV inside each stacking fold fit."""
    y = np.asarray(y)
    rows = np.where(np.asarray(row_mask) > 0.5)[0]
    sub = stratified_kfold_test_masks(y[rows], k)  # [k, n_sub]
    masks = np.zeros((k, y.shape[0]))
    masks[:, rows] = sub
    return masks


def stratified_subsample_indices(
    y: np.ndarray,
    m: int,
    rows: np.ndarray | None = None,
    seed: int = 0,
) -> np.ndarray:
    """Deterministic stratified subsample of ``m`` indices (from ``rows``,
    default all): per-class counts by largest-remainder apportionment of the
    class frequencies, rows drawn without replacement by a seeded Generator.
    Returns sorted indices into the full array — the scaled-regime guard's
    sampling primitive (SURVEY.md §7 "SVC on TPU": subsample above the
    kernel-matrix threshold)."""
    y = np.asarray(y)
    rows = np.arange(y.shape[0]) if rows is None else np.asarray(rows)
    if m >= rows.shape[0]:
        return np.sort(rows)
    rng = np.random.default_rng(seed)
    ysub = y[rows]
    classes, counts = np.unique(ysub, return_counts=True)
    quota = m * counts / counts.sum()
    take = np.floor(quota).astype(int)
    # largest remainders round up until the total hits m
    for c in np.argsort(-(quota - take))[: m - take.sum()]:
        take[c] += 1
    picked = []
    for c, t in zip(classes, take):
        members = rows[ysub == c]
        picked.append(rng.choice(members, size=t, replace=False))
    return np.sort(np.concatenate(picked))


def stratified_kfold_test_masks(y: np.ndarray, k: int) -> np.ndarray:
    """``StratifiedKFold(k, shuffle=False)`` exactly as sklearn assigns it:
    for each class, its occurrences (in row order) are dealt into folds in
    blocks sized by interleaving the sorted class sequence."""
    y = np.asarray(y)
    classes, y_enc = np.unique(y, return_inverse=True)
    n_classes = classes.shape[0]
    y_order = np.sort(y_enc)
    allocation = np.asarray(
        [np.bincount(y_order[i::k], minlength=n_classes) for i in range(k)]
    )  # [k, n_classes]
    test_folds = np.empty(y.shape[0], dtype=int)
    for c in range(n_classes):
        folds_for_class = np.arange(k).repeat(allocation[:, c])
        test_folds[y_enc == c] = folds_for_class
    masks = np.zeros((k, y.shape[0]))
    for i in range(k):
        masks[i, test_folds == i] = 1.0
    return masks
