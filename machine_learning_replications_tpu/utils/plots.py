"""Host-side ROC / PR figures with 95% Wald CI bands (L5').

The reference plots ``metrics.plot_roc_curve`` and
``metrics.plot_precision_recall_curve`` and fills a hand-rolled 95% Wald
band ``1.96·sqrt(p(1−p)/n)`` around each curve
(``train_ensemble_public.py:67-88``). Curves and the band half-widths are
computed on device (``utils.metrics``); only the matplotlib rendering runs
on host, against the non-interactive Agg backend so it works headless —
the reference instead blocks on a GUI ``plt.show()``
(``train_ensemble_public.py:90``).
"""

from __future__ import annotations

import os

import numpy as np

from machine_learning_replications_tpu.utils import metrics


def _axes():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def roc_figure(
    y_true: np.ndarray,
    scores: np.ndarray,
    *,
    label: str = "ensemble",
    out_path: str | os.PathLike | None = None,
):
    """ROC curve + AUC in the legend + Wald CI band, reference style
    (``train_ensemble_public.py:67-77``). Returns the matplotlib figure;
    saves a PNG when ``out_path`` is given."""
    plt = _axes()
    curve = metrics.roc_curve(y_true, scores)
    auc = float(metrics.roc_auc(y_true, scores))
    fpr = np.asarray(curve.fpr)
    tpr = np.asarray(curve.tpr)
    half = np.asarray(metrics.wald_ci_halfwidth(curve.tpr, y_true.shape[0]))

    fig, ax = plt.subplots(figsize=(6, 5))
    ax.plot(fpr, tpr, label=f"{label} (AUC = {auc:.2f})")
    ax.fill_between(
        fpr,
        np.clip(tpr - half, 0, 1),
        np.clip(tpr + half, 0, 1),
        alpha=0.25,
        linewidth=0,
    )
    ax.plot([0, 1], [0, 1], linestyle="--", linewidth=0.8, color="grey")
    ax.set_xlabel("False positive rate")
    ax.set_ylabel("True positive rate")
    ax.set_title("ROC (95% Wald CI band)")
    ax.legend(loc="lower right")
    if out_path is not None:
        fig.savefig(os.fspath(out_path), dpi=150, bbox_inches="tight")
    return fig


def pr_figure(
    y_true: np.ndarray,
    scores: np.ndarray,
    *,
    label: str = "ensemble",
    out_path: str | os.PathLike | None = None,
):
    """Precision-recall curve + AP + Wald CI band
    (``train_ensemble_public.py:79-88``)."""
    plt = _axes()
    curve = metrics.precision_recall_curve(y_true, scores)
    ap = float(metrics.average_precision(y_true, scores))
    rec = np.asarray(curve.recall)
    prec = np.asarray(curve.precision)
    half = np.asarray(metrics.wald_ci_halfwidth(curve.precision, y_true.shape[0]))

    fig, ax = plt.subplots(figsize=(6, 5))
    ax.plot(rec, prec, label=f"{label} (AP = {ap:.2f})")
    ax.fill_between(
        rec,
        np.clip(prec - half, 0, 1),
        np.clip(prec + half, 0, 1),
        alpha=0.25,
        linewidth=0,
    )
    ax.set_xlabel("Recall")
    ax.set_ylabel("Precision")
    ax.set_title("Precision-Recall (95% Wald CI band)")
    ax.legend(loc="lower left")
    if out_path is not None:
        fig.savefig(os.fspath(out_path), dpi=150, bbox_inches="tight")
    return fig
